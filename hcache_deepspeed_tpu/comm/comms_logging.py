"""Communication operation logging.

Reference analog: ``deepspeed/utils/comms_logging.py`` ``CommsLogger`` fed by
``@timed_op`` wrappers on every collective (``comm/comm.py:101-134``), and
``dist.log_summary()`` (``comm/comm.py:428``).

On TPU collectives are issued inside traced/compiled programs, so per-call
host-side wall timing is meaningless; instead we record, at *trace time*, the
op type, message size and mesh axes for every collective the facade emits, and
report aggregate counts/volumes. Wall-clock attribution comes from the XLA
profiler (``platform.profiler_start``), which names each collective.
"""

import math
from collections import defaultdict

from ..utils.logging import log_dist


def convert_size(size_bytes):
    if size_bytes == 0:
        return "0B"
    units = ("B", "KB", "MB", "GB", "TB", "PB")
    i = int(math.floor(math.log(size_bytes, 1024)))
    return f"{round(size_bytes / 1024 ** i, 2)} {units[i]}"


class CommsLogger:
    def __init__(self, enabled=False, verbose=False, prof_all=True,
                 prof_ops=None, debug=False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.prof_ops = prof_ops or []
        self.debug = debug
        # op_name -> msg_size -> [count, total_bytes]
        self.comms_dict = defaultdict(lambda: defaultdict(lambda: [0, 0]))
        # op_name -> op kind ("collective" | "collective_permute"):
        # which transport carried the bytes. Ring-decomposed sites
        # (comm/ring.py) record per-chunk permute sends under their own
        # kind so the decomposed wire is attributable, not silently
        # folded into (or missing from) the monolithic-collective rows.
        self.op_kinds = {}

    def configure(self, enabled=None, verbose=None, prof_all=None,
                  prof_ops=None, debug=None):
        if enabled is not None:
            self.enabled = enabled
        if verbose is not None:
            self.verbose = verbose
        if prof_all is not None:
            self.prof_all = prof_all
        if prof_ops is not None:
            self.prof_ops = prof_ops
        if debug is not None:
            self.debug = debug

    def should_log(self, op_name):
        if not self.enabled:
            return False
        return self.prof_all or op_name in self.prof_ops

    def log_collective(self, op_name, n_bytes, axes=(),
                       op_kind="collective"):
        """Byte attribution for a collective issued OUTSIDE the comm
        facade — the explicit ZeRO reduce-scatter and all-gather bucket
        sites (``runtime/zero/zeropp.py``: ``zero_reduce_scatter``,
        ``zero_bucket_reduce_scatter``, ``zero_bucket_all_gather``).
        Before these sites logged, only the gather/all-reduce paths
        were fully attributed and ``log_summary`` under-reported the
        reduce lane's wire volume. Convention: ``n_bytes`` is the
        per-device collective INPUT buffer (the same convention the
        facade's ``reduce_scatter``/``all_gather`` wrappers use), so
        bucketed and per-leaf programs report identical totals.

        ``op_kind="collective_permute"`` marks decomposed ring-chunk
        sends (``comm/ring.py``): one record per permute step, so the
        ring transport's bytes land in the accounting —
        ``wire_savings_summary`` / ``axis_summary`` rows carry the kind
        — instead of being silently unattributed."""
        self.op_kinds[op_name] = op_kind
        self.append(op_name, tuple(axes), int(n_bytes))

    def log_quantized(self, op_name, wire_bytes, unquantized_equiv_bytes,
                      axes=(), op_kind="collective"):
        """Byte attribution for a QUANTIZED collective: record the
        actual wire volume under ``op_name`` and the volume the same
        collective would have carried full-width under
        ``op_name + "_unquantized_equiv"``. Every quantized wire site
        (qwZ gather, qgZ all-to-all, the bucketed quantized
        reduce-scatter, Domino's int8 all-reduce) reports through this
        single convention so ``wire_savings_summary`` — and the tests
        that gate attribution — can pair them mechanically."""
        if not self.should_log(op_name):
            return
        self.op_kinds[op_name] = op_kind
        self.append(op_name, tuple(axes), int(wire_bytes))
        self.append(op_name + "_unquantized_equiv", tuple(axes),
                    int(unquantized_equiv_bytes))

    def wire_savings_summary(self):
        """Pair each quantized op with its ``_unquantized_equiv``
        record: ``{op: {"wire_bytes", "unquantized_equiv_bytes",
        "saved_bytes", "fraction"}}`` — the per-collective wire-bytes
        evidence ``bench.py --zero-overlap`` emits alongside the
        overlap ratios."""
        totals = {}
        for op, by_axis in self.axis_summary().items():
            totals[op] = sum(t for _, t in by_axis.values())
        out = {}
        for op, total in sorted(totals.items()):
            if op.endswith("_unquantized_equiv"):
                continue
            equiv = totals.get(op + "_unquantized_equiv")
            if equiv is None:
                continue
            out[op] = {
                "wire_bytes": total,
                "unquantized_equiv_bytes": equiv,
                "saved_bytes": equiv - total,
                "fraction": round(total / equiv, 4) if equiv else None,
                "op_kind": self.op_kinds.get(op, "collective"),
            }
        return out

    def permute_bytes_summary(self, kinds=("collective_permute",)):
        """Total bytes per op carried by decomposed ring permutes
        (``op_kind == "collective_permute"``): ``{op: total_bytes}``.
        The matched-pair complement of :meth:`wire_savings_summary` for
        the ring transport — proves ring-chunk traffic is attributed.
        Per-mesh-axis breakdown: :meth:`permute_axis_bytes`. ``kinds``
        widens the filter (e.g. ``("collective_permute",
        "fused_permute")`` for the lumped summary a fused run must
        reconcile against byte-exactly)."""
        out = {}
        for op, by_axis in self.axis_summary().items():
            if self.op_kinds.get(op) in kinds:
                out[op] = sum(t for _, t in by_axis.values())
        return out

    def fused_bytes_summary(self):
        """Total bytes per op carried INSIDE fused
        computation-collective kernels (``op_kind == "fused_permute"``,
        logged per in-kernel ring step by
        ``ops/fused_collective_matmul.py``): ``{op: total_bytes}``.
        The fused kernel's wire volume is never silent: these rows
        reconcile byte-exactly with what the unfused transport of the
        same payload logs as ``collective_permute`` rows (gated by
        test_wire_bytes.py)."""
        out = {}
        for op, by_axis in self.axis_summary().items():
            if self.op_kinds.get(op) == "fused_permute":
                out[op] = sum(t for _, t in by_axis.values())
        return out

    def permute_axis_bytes(self):
        """Ring-permute bytes attributed PER MESH-AXIS NAME:
        ``{op: {axis_label: total_bytes}}`` — the hierarchical
        transport (``comm/hierarchical.py``) labels every phase with
        the mesh axis its bytes physically ride (the LAST component of
        the axis group; flat rings label with the collective axis
        itself), so intra- vs inter-axis wire volume is separately
        queryable and the per-axis wire-cost model
        (``profiling/hlo_audit.py``) can price it. The matched-pair
        convention is untouched: quantized long-haul phases still
        report ``<op>_longhaul`` / ``..._unquantized_equiv`` pairs
        through :meth:`wire_savings_summary`."""
        out = {}
        for op, by_axis in self.axis_summary().items():
            if self.op_kinds.get(op) not in ("collective_permute",
                                             "fused_permute"):
                continue
            per_axis = {}
            for axes, (_, total) in by_axis.items():
                label = axes.rpartition(",")[2] or axes
                per_axis[label] = per_axis.get(label, 0) + total
            out[op] = per_axis
        return out

    def total_axis_bytes(self, kinds=("collective_permute",
                                      "fused_permute")):
        """Aggregate ``{axis_label: bytes}`` over every op of the given
        kinds — the direct input to ``hlo_audit.wire_cost_seconds``.
        ``_unquantized_equiv`` shadow rows and ``_longhaul``
        matched-pair site markers are excluded (bookkeeping, not wire —
        the long-haul phase's actual sends are already logged per
        permute step by the underlying rings)."""
        totals = {}
        for op, by_axis in self.axis_summary().items():
            if self.op_kinds.get(op) not in kinds \
                    or op.endswith("_unquantized_equiv") \
                    or op.endswith("_longhaul"):
                continue
            for axes, (_, total) in by_axis.items():
                label = axes.rpartition(",")[2] or axes
                totals[label] = totals.get(label, 0) + total
        return totals

    def append(self, op_name, axes, msg_size):
        if not self.should_log(op_name):
            return
        axis_group = ",".join(axes) if axes else "world"
        key = f"{op_name}@{axis_group}"
        rec = self.comms_dict[key][msg_size]
        rec[0] += 1
        rec[1] += msg_size
        # trace-time collective record -> telemetry span stream (the
        # nvtx-range analog; lazy import keeps comm importable first)
        from ..telemetry.tracer import get_tracer
        tracer = get_tracer()
        if tracer.enabled:
            tracer.instant(f"comm.{op_name}", bytes=int(msg_size),
                           axes=axis_group)
        if self.verbose:
            log_dist(f"comm op: {key} | msg size: {convert_size(msg_size)}",
                     ranks=[0])

    def log_all(self):
        if not self.comms_dict:
            log_dist("comms logger: no collectives recorded", ranks=[0])
            return
        lines = [f"{'Comm op (axis group)':<40} {'Message size':>14} "
                 f"{'Count':>8} {'Total volume':>14}"]
        for op, sizes in sorted(self.comms_dict.items()):
            for size, (count, total) in sorted(sizes.items()):
                lines.append(f"{op:<40} {convert_size(size):>14} {count:>8} "
                             f"{convert_size(total):>14}")
        log_dist("\n".join(lines), ranks=[0])

    def axis_summary(self):
        """Per-axis-group traffic breakdown
        ``{op_name: {axis_group: (count, total_bytes)}}`` — the
        partitioned-parameter profiler analog (reference:
        ``runtime/zero/partitioned_param_profiler.py`` EventCounter
        count/numel per event): how much gather/reduce volume each mesh
        axis carries, for the monitor and for hpZ-style wire-locality
        checks."""
        out = {}
        for key, sizes in self.comms_dict.items():
            op, _, axes = key.partition("@")
            count = sum(c for c, _ in sizes.values())
            total = sum(t for _, t in sizes.values())
            out.setdefault(op, {})[axes] = (count, total)
        return out

    def monitor_events(self, step: int):
        """``(tag, value, step)`` triples for ``monitor.write_events``:
        total bytes per collective per axis group."""
        return [(f"Comms/{op}@{axes}", float(total), step)
                for op, by_axis in sorted(self.axis_summary().items())
                for axes, (_, total) in sorted(by_axis.items())]

    def summary_events(self, step: int = 0):
        """The ``log_summary`` aggregate (op → count / total bytes) as
        monitor event triples, so comm volume lands in the same sink as
        step metrics instead of only the ``log_dist`` text table."""
        out = []
        for op, by_axis in sorted(self.axis_summary().items()):
            for axes, (count, total) in sorted(by_axis.items()):
                out.append((f"CommsSummary/{op}@{axes}/count",
                            float(count), step))
                out.append((f"CommsSummary/{op}@{axes}/bytes",
                            float(total), step))
        return out

    def log_summary(self, monitor=None, step: int = 0):
        """Print the aggregate table AND, when a monitor is given,
        route it through ``MonitorMaster.write_events``."""
        self.log_all()
        if monitor is not None and getattr(monitor, "enabled", True):
            events = self.summary_events(step)
            if events:
                monitor.write_events(events)

    def reset(self):
        self.comms_dict.clear()
        self.op_kinds.clear()


_comms_logger = CommsLogger()


def get_comms_logger() -> CommsLogger:
    return _comms_logger
