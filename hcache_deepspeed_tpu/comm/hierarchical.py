"""Topology-aware hierarchical collectives: multi-axis mesh ring
decomposition with long-haul-only quantization.

Reference analogs:
* ZeRO++ hpZ (PAPERS.md) — hierarchy beats flat at scale: secondary
  groups keep the heavy traffic on the fast links,
* EQuARX (PAPERS.md) — quantization should be spent
  bandwidth-proportionally: compress the slow-axis hops, leave the
  fast-axis hops full width,
* The Big Send-off / T3 (PAPERS.md) — multi-dimensional decomposed
  collectives built from point-to-point sends.

The flat rings in ``comm/ring.py`` (PR 9) treat the data axis as a 1-D
ring, but the v5e-256 target (BASELINE.json) is a 2-D ICI torus: a flat
ring's logical neighbor hops stripe over physically different links,
so its wire bytes are unattributable to an axis and its quantization
(when on) is spent uniformly. This module factors the flat shard_map
axis into a declared multi-axis mesh (:class:`HierMeshSpec`, e.g.
``2 x 4`` over 8 devices, rank = outer * a1 + inner) and re-expresses
every collective as a sequence of **grouped ring phases, one per mesh
axis** (inner/fast axis first, outer/long-haul axis last), reusing the
hpZ ``axis_index_groups`` machinery in ``comm/ring.py``:

* **hierarchical all-gather** — intra-axis ring gather, then the
  gathered block rides the inter-axis rings; final row order is global
  rank order, so the result is bitwise-equal to
  ``jax.lax.all_gather`` and to the flat :func:`~.ring.ring_all_gather`
  (pure data movement).
* **hierarchical all-to-all / reduce-scatter** — per-phase grouped
  direct delivery (:func:`~.ring.decomposed_all_to_all_rows`): after
  the phase for mesh dim ``j``, the payload's dim-``j`` index has been
  exchanged from DEST coordinate to SOURCE coordinate. Every raw
  contribution still arrives unreduced, so the destination folds all
  ``n`` rows in source-index order — the same fold as the flat
  decomposed reduce-scatter and (measured, pinned by test_ring.py) as
  XLA's native ``psum_scatter``: bitwise-equal to both.
* **axis-selective quantization** (``longhaul_bits=8`` or ``4``) — the
  long-haul phase's payload is int8 group-quantized (nibble-packed for
  4 bits — the ``qwire.py`` packing) with fp32 group scales; fast-axis
  phases stay full width. The receiver dequantizes on arrival except
  its OWN long-haul row, which never crossed the slow wire and stays
  exact. For the reduce direction an error-feedback residual
  (``runtime/onebit.py error_feedback_step`` — the same machinery as
  the qrs wire) carries the quantization error forward; the own-row
  residual is pinned to zero because that row ships exact. Quantized
  sites report matched ``<op>_longhaul`` / ``..._unquantized_equiv``
  byte pairs through the comms logger, like every quantized wire site.

Wire attribution: every ring phase passes its mesh-axis name as
``wire_axis``, so permute bytes land per axis in the comms logger
(``CommsLogger.permute_axis_bytes()``) — intra- vs inter-axis wire
volume is separately queryable, and ``profiling/hlo_audit.py``'s
per-axis wire-cost model can price it in seconds against declared
per-axis link bandwidths.

Cost honesty: the hierarchical exchange moves MORE total logical bytes
than the flat direct-delivery ring (transit duplication at the phase
corners: ``sum_j (n_j - 1) * n / n_j`` row-sends vs the flat ring's
``n - 1``), but every byte is attributed to the axis it rides, the
long-haul axis carries exactly its unavoidable share, and that share
alone can be compressed. On a pod whose inter-axis links are several
times slower than ICI, modeled wire seconds drop even as logical bytes
rise — which is the point, and what the wire-cost model makes visible.

Everything here must run INSIDE a ``shard_map`` region (manual axis)
and is sim-deterministic (no ambient clock/RNG — the analysis purity
rules gate this module).
"""

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .comms_logging import get_comms_logger
from .ring import (_chunk_bounds, _index_order_fold,
                   decomposed_all_to_all_rows, ring_all_gather)

#: legal wire widths for the long-haul phase (int8 / nibble-packed int4)
LONGHAUL_WIRE_BITS = (4, 8)

#: default axis names for a 2-D spec: outer = long haul, inner = fast
DEFAULT_2D_AXIS_NAMES = ("inter", "intra")

#: legal axis roles for a composed factoring: ``data`` axes carry the
#: ZeRO collectives (shards, gathers, the fused kernel's ring);
#: ``model``/``pipe``/``expert`` axes are declared-but-orthogonal
#: parallelism dims the ZeRO transport must NOT ride (the (data,
#: model, pipe) 3-D factoring of the v5e-256 target).
MESH_AXIS_ROLES = ("data", "model", "pipe", "expert")


@dataclass(frozen=True)
class MeshAxis:
    """One mesh axis: name, size, (for the wire-cost model) the
    per-device link bandwidth bytes ride on this axis, and its
    parallelism ``role`` (``MESH_AXIS_ROLES``; non-``data`` roles make
    the spec a composed multi-parallelism factoring whose ZeRO
    collectives ride only the data axes — :meth:`HierMeshSpec.
    zero_subspec`)."""
    name: str
    size: int
    gbytes_per_s: Optional[float] = None
    role: str = "data"


@dataclass(frozen=True)
class HierMeshSpec:
    """A declared multi-axis factoring of the flat collective axis.

    ``axes`` is outer-to-inner; global rank ``r`` has coordinate
    ``(r // stride_j) % size_j`` on axis ``j`` (row-major mixed radix),
    so the INNER-most axis is the contiguous/fast one — the hpZ
    convention (consecutive ranks share a node/slice). ``longhaul``
    names the axis whose hops are the slow wire (quantization target,
    inter-axis wire accounting); by default the outermost axis."""
    axes: Tuple[MeshAxis, ...]
    longhaul: str

    @property
    def sizes(self) -> Tuple[int, ...]:
        return tuple(ax.size for ax in self.axes)

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(ax.name for ax in self.axes)

    @property
    def world(self) -> int:
        return int(np.prod(self.sizes))

    @property
    def longhaul_dim(self) -> int:
        return self.names.index(self.longhaul)

    @property
    def roles(self) -> Tuple[str, ...]:
        return tuple(ax.role for ax in self.axes)

    @property
    def data_dims(self) -> Tuple[int, ...]:
        """Axis indices whose role is ``data`` — the dims the ZeRO
        collectives (and the fused kernel's ring) ride."""
        return tuple(j for j, ax in enumerate(self.axes)
                     if ax.role == "data")

    @property
    def zero_world(self) -> int:
        """Product of the data-role axis sizes: the ZeRO shard count a
        composed factoring yields (== ``world`` for all-data specs)."""
        return int(np.prod([self.axes[j].size for j in self.data_dims]))

    def zero_subspec(self) -> "HierMeshSpec":
        """The spec restricted to its data-role axes — what every
        hierarchical transport actually rides. Identity for all-data
        specs (every pre-roles spec). When the declared long-haul axis
        is a non-data axis, the subspec's long haul falls to its
        outermost data axis (the slowest link the ZeRO wire touches)."""
        if all(ax.role == "data" for ax in self.axes):
            return self
        axes = tuple(self.axes[j] for j in self.data_dims)
        longhaul = self.longhaul if any(
            ax.name == self.longhaul for ax in axes) else axes[0].name
        return HierMeshSpec(axes=axes, longhaul=longhaul)

    def bandwidths(self) -> Dict[str, Optional[float]]:
        return {ax.name: ax.gbytes_per_s for ax in self.axes}

    def describe(self) -> Dict:
        """JSON-safe spec row (bench artifact payload)."""
        return {
            "shape": list(self.sizes), "axis_names": list(self.names),
            "longhaul_axis": self.longhaul,
            "axis_roles": list(self.roles),
            "zero_world": self.zero_world,
            "link_gbytes_per_s": {
                ax.name: ax.gbytes_per_s for ax in self.axes},
        }


def make_mesh_spec(shape: Sequence[int],
                   axis_names: Optional[Sequence[str]] = None,
                   link_gbytes_per_s: Optional[Sequence[float]] = None,
                   longhaul_axis: Optional[str] = None,
                   axis_roles: Optional[Sequence[str]] = None
                   ) -> HierMeshSpec:
    """Build and validate a :class:`HierMeshSpec` from config values —
    typed ``HDSConfigError`` rejections for every degenerate shape, no
    silent clamps (the PR 5 convention)."""
    from ..runtime.config import HDSConfigError
    shape = [int(s) for s in (shape or ())]
    if len(shape) < 2:
        raise HDSConfigError(
            f"zero_mesh_shape={shape}: a hierarchical mesh needs at "
            f"least 2 axes (a 1-axis mesh IS the flat ring — use "
            f"zero_collective_impl=decomposed)")
    for s in shape:
        if s < 2:
            raise HDSConfigError(
                f"zero_mesh_shape={shape}: axis of size {s} — every "
                f"mesh axis must have size >= 2 (a size-1 axis has no "
                f"ring; drop it from the shape)")
    if axis_names is None:
        axis_names = DEFAULT_2D_AXIS_NAMES if len(shape) == 2 else \
            tuple(f"axis{j}" for j in range(len(shape)))
    axis_names = [str(a) for a in axis_names]
    if len(axis_names) != len(shape):
        raise HDSConfigError(
            f"zero_mesh_axis_names={axis_names} must match "
            f"zero_mesh_shape={shape} ({len(shape)} axes)")
    if len(set(axis_names)) != len(axis_names):
        raise HDSConfigError(
            f"zero_mesh_axis_names={axis_names}: duplicate axis names")
    if link_gbytes_per_s is not None \
            and len(link_gbytes_per_s) != len(shape):
        raise HDSConfigError(
            f"zero_mesh_link_gbps={list(link_gbytes_per_s)} must give "
            f"one per-axis bandwidth per mesh axis ({len(shape)})")
    if longhaul_axis is None:
        longhaul_axis = axis_names[0]
    if longhaul_axis not in axis_names:
        raise HDSConfigError(
            f"zero_longhaul_axis={longhaul_axis!r} names an unknown "
            f"mesh axis; declared axes are {axis_names}")
    if axis_roles is None:
        axis_roles = ["data"] * len(shape)
    axis_roles = [str(r) for r in axis_roles]
    if len(axis_roles) != len(shape):
        raise HDSConfigError(
            f"zero_mesh_axis_roles={axis_roles} must give one role per "
            f"mesh axis ({len(shape)})")
    for r in axis_roles:
        if r not in MESH_AXIS_ROLES:
            raise HDSConfigError(
                f"zero_mesh_axis_roles={axis_roles}: unknown role "
                f"{r!r}; legal roles are {MESH_AXIS_ROLES}")
    if "data" not in axis_roles:
        raise HDSConfigError(
            f"zero_mesh_axis_roles={axis_roles}: a composed factoring "
            f"needs at least one data-role axis — the ZeRO collectives "
            f"(and the fused kernel's ring) have no axis to ride")
    axes = tuple(
        MeshAxis(name=axis_names[j], size=shape[j],
                 gbytes_per_s=(float(link_gbytes_per_s[j])
                               if link_gbytes_per_s is not None else None),
                 role=axis_roles[j])
        for j in range(len(shape)))
    return HierMeshSpec(axes=axes, longhaul=longhaul_axis)


def mesh_spec_from_zero_config(zcfg) -> Optional[HierMeshSpec]:
    """The spec a ``ZeroConfig`` declares, or ``None`` when the
    transport is not hierarchical (parse-time validation already ran;
    this is the engine-build constructor)."""
    if getattr(zcfg, "zero_collective_impl", "native") not in (
            "hierarchical", "fused"):
        return None
    return make_mesh_spec(zcfg.zero_mesh_shape,
                          zcfg.zero_mesh_axis_names,
                          zcfg.zero_mesh_link_gbps,
                          zcfg.zero_longhaul_axis,
                          getattr(zcfg, "zero_mesh_axis_roles", None))


def validate_mesh_spec(spec: HierMeshSpec, *, world_size: int,
                       longhaul_bits: Optional[int] = None) -> None:
    """Topology-time checks (engine build, where the world size is
    known): the mesh must exactly factor the flat axis, and the
    long-haul wire width must be one the packing supports."""
    from ..runtime.config import HDSConfigError
    if spec.zero_world != world_size:
        detail = "" if spec.zero_world == spec.world else (
            f" (the spec's data-role axes "
            f"{[spec.names[j] for j in spec.data_dims]} of the "
            f"{spec.world}-device composed factoring)")
        raise HDSConfigError(
            f"zero_mesh_shape={list(spec.sizes)} describes "
            f"{spec.zero_world} ZeRO shards{detail} but the data "
            f"world size is {world_size}; the mesh shape must factor "
            f"the axis exactly")
    if longhaul_bits is not None and longhaul_bits not in \
            LONGHAUL_WIRE_BITS:
        raise HDSConfigError(
            f"zero_longhaul_wire_bits={longhaul_bits}: the long-haul "
            f"wire ships int8 or nibble-packed int4 payloads — use 8 "
            f"or 4 (or null for full width)")


def axis_groups(sizes: Sequence[int], dim: int) -> List[List[int]]:
    """``axis_index_groups`` for mesh dim ``dim`` of a row-major rank
    factoring: every group holds the ranks that vary ONLY along that
    dim (the hpZ group-construction generalized to any axis)."""
    ranks = np.arange(int(np.prod(sizes))).reshape(tuple(sizes))
    moved = np.moveaxis(ranks, dim, -1).reshape(-1, sizes[dim])
    return [[int(r) for r in g] for g in moved]


def axis_subgroups(sizes: Sequence[int], dim: int,
                   span: int) -> List[List[int]]:
    """Split every dim-``dim`` group into aligned runs of ``span``
    consecutive coordinates — the grouped-ring phase structure of an
    hpZ tier that only PARTIALLY covers a mesh axis. ``span`` must
    divide the axis size (checked by :func:`hpz_tier_dims`)."""
    out: List[List[int]] = []
    for g in axis_groups(sizes, dim):
        for s in range(0, len(g), span):
            out.append(g[s:s + span])
    return out


def hpz_tier_dims(spec: HierMeshSpec, hpz: int) -> List[Tuple[int, int]]:
    """Map ``zero_hpz_partition_size`` onto the mesh: the hpZ group
    (``hpz`` consecutive ranks, row-major) must be a contiguous sub-box
    of the declared mesh — whole innermost axes plus, at most, an even
    divisor of the next axis out. Returns the per-dim coverage
    ``[(dim, span)]`` innermost-first (``span == size`` means the axis
    is entirely inside the fast tier), which is exactly the grouped
    ring phase plan of the unified hpZ-on-mesh gathers.

    This replaces the PR 12 blanket "hpZ and the mesh both claim the
    fast tier" rejection with a real tiering: only GENUINE mismatches
    (hpz neither a divisor nor a whole multiple of the inner axis
    sizes, or hpz exceeding the mesh) raise, all typed
    ``HDSConfigError`` — no silent clamps."""
    from ..runtime.config import HDSConfigError
    hpz = int(hpz)
    if hpz <= 1:
        return []
    # composed factorings: hpZ tiers over the data-role sub-box only
    # (identity for all-data specs); returned dims index the subspec
    spec = spec.zero_subspec()
    sizes = spec.sizes
    covered: List[Tuple[int, int]] = []
    remaining = hpz
    for dim in range(len(sizes) - 1, -1, -1):
        a = sizes[dim]
        name = spec.axes[dim].name
        if remaining >= a:
            if remaining % a:
                raise HDSConfigError(
                    f"zero_hpz_partition_size={hpz} does not map onto "
                    f"zero_mesh_shape={list(sizes)}: the remainder "
                    f"{remaining} is not a whole multiple of axis "
                    f"{name!r} (size {a}) — hpZ groups of consecutive "
                    f"ranks must tile a contiguous sub-box of the "
                    f"row-major mesh")
            covered.append((dim, a))
            remaining //= a
        else:
            if a % remaining:
                raise HDSConfigError(
                    f"zero_hpz_partition_size={hpz} does not map onto "
                    f"zero_mesh_shape={list(sizes)}: {remaining} is "
                    f"neither a divisor nor a multiple of axis "
                    f"{name!r} (size {a}) — make hpz a divisor of the "
                    f"fast-tier axis or a whole-axis multiple")
            covered.append((dim, remaining))
            remaining = 1
        if remaining == 1:
            break
    if remaining != 1:
        raise HDSConfigError(
            f"zero_hpz_partition_size={hpz} exceeds the mesh world "
            f"{spec.world} (zero_mesh_shape={list(sizes)})")
    return covered


def _gather_phases(spec: HierMeshSpec, hpz: Optional[int] = None):
    """Grouped ring phase plan of a hierarchical gather, innermost
    (fast) axis first: ``[(dim, axis_index_groups, span)]``. With
    ``hpz`` the phases are restricted to the hpZ tier — the gather
    stays inside each group of ``hpz`` consecutive ranks, riding only
    the mesh axes (or aligned sub-runs of one axis) that tier covers."""
    if hpz and hpz > 1:
        return [(dim, axis_subgroups(spec.sizes, dim, span), span)
                for dim, span in hpz_tier_dims(spec, hpz)]
    return [(dim, axis_groups(spec.sizes, dim), spec.sizes[dim])
            for dim in range(len(spec.sizes) - 1, -1, -1)]


def _my_coord(axis_name, sizes, dim):
    """This device's (traced) coordinate along mesh dim ``dim``."""
    stride = int(np.prod(sizes[dim + 1:])) if dim + 1 < len(sizes) else 1
    return (jax.lax.axis_index(axis_name) // stride) % sizes[dim]


def _quantize_block(x, group_size, bits):
    """Groupwise-quantize ``x`` as ONE block: ``(payload, scale,
    qlast)`` — payload nibble-packed for bits=4 (the ``qwire.py``
    packing)."""
    from ..ops.quantizer import quantize
    from ..runtime.zero.qwire import pack_int4
    gsz = max(1, min(int(group_size), x.size))
    q, scale, _, _ = quantize(x, group_size=gsz,
                              num_bits=4 if bits == 4 else 8)
    payload = pack_int4(q) if bits == 4 else q
    return payload, scale, q.shape[-1]


def _dequantize_rows(payload, scale, qlast, shape, count, bits):
    """Per-leading-row inverse of :func:`_quantize_block`: ``[m, ...]``
    payload+scales (each row one independently quantized block) ->
    ``[m, *shape]`` fp32."""
    from ..ops.quantizer import dequantize
    from ..runtime.zero.qwire import unpack_int4

    def one(p, s):
        q = unpack_int4(p, qlast) if bits == 4 else p
        return dequantize(q, s, shape, count)

    return jax.vmap(one)(payload, scale)


def _row_quantizer(width, group_size, bits):
    """Per-row groupwise quantize / dequantize for ``[a, width]``
    buffers (the long-haul reduce phase: each row is one peer's block,
    quantized independently so the receiver can dequantize it alone).
    Same group layout and int4 packing as ``runtime/zero/qwire.py``."""
    from ..ops.quantizer import quantize
    from ..runtime.zero.qwire import pack_int4
    gsz = max(1, min(int(group_size), int(width)))
    num_bits = 4 if bits == 4 else 8

    def quant(c):
        def one(row):
            return quantize(row, group_size=gsz, num_bits=num_bits)[:2]
        q, s = jax.vmap(one)(c)
        payload = pack_int4(q) if bits == 4 else q
        return payload, s, q.shape[-1]

    def deq(payload, scale, qlast):
        return _dequantize_rows(payload, scale, qlast, (int(width),),
                                int(width), bits)

    return quant, deq


def _log_longhaul_pair(op_name, axis_name, wire_axis, payload, scale,
                       equiv_bytes):
    """Matched quantized/unquantized-equiv byte pair for a long-haul
    quantized phase — the same convention every quantized wire site
    uses, so ``wire_savings_summary`` pairs it mechanically."""
    get_comms_logger().log_quantized(
        op_name + "_longhaul",
        payload.size * payload.dtype.itemsize + 4 * scale.size,
        int(equiv_bytes), (axis_name, wire_axis),
        op_kind="collective_permute")


def _gather_run(x, axis_name, spec: HierMeshSpec, phases, *, chunks,
                longhaul_bits, group_size, op_name):
    """One full multi-phase gather of ``x`` over ``phases`` (from
    :func:`_gather_phases`): ``[n_g, *x.shape]`` in group-rank order,
    ``n_g`` = the product of the phase spans."""
    sizes = spec.sizes
    cur = x[None]                                  # [lead=1, *x.shape]
    for dim, groups, span in phases:
        ax = spec.axes[dim]
        if longhaul_bits is not None and ax.name == spec.longhaul:
            payload, scale, qlast = _quantize_block(cur, group_size,
                                                    longhaul_bits)
            _log_longhaul_pair(op_name, axis_name, ax.name, payload,
                               scale, cur.size * cur.dtype.itemsize)
            p_all = ring_all_gather(
                payload, axis_name, axis_index_groups=groups,
                chunks=chunks, op_name=op_name, wire_axis=ax.name)
            s_all = ring_all_gather(
                scale, axis_name, axis_index_groups=groups,
                chunks=chunks, op_name=op_name, wire_axis=ax.name)
            deq = _dequantize_rows(p_all, s_all, qlast, cur.shape,
                                   cur.size, longhaul_bits)
            deq = deq.astype(cur.dtype)
            # own long-haul row never shipped: keep it bit-exact
            # (position within the phase group = coordinate mod span)
            my_c = _my_coord(axis_name, sizes, dim) % span
            wide = jax.lax.dynamic_update_slice_in_dim(
                deq, cur[None], my_c, axis=0)
        else:
            wide = ring_all_gather(
                cur, axis_name, axis_index_groups=groups, chunks=chunks,
                op_name=op_name, wire_axis=ax.name)
        cur = wide.reshape((wide.shape[0] * cur.shape[0],) + x.shape)
    return cur                                     # [n_g, *x.shape]


def hierarchical_all_gather(x, axis_name, spec: HierMeshSpec, *,
                            chunks: int = 1,
                            pipeline_chunks: int = 1,
                            longhaul_bits: Optional[int] = None,
                            group_size: int = 2048,
                            hpz: Optional[int] = None,
                            op_name: str = "hier_all_gather"):
    """Hierarchical ring all-gather: ``[n_g, *x.shape]`` stacked result
    in GLOBAL RANK order within the gather group — the same layout
    (and, full-width, the same bits) as
    ``jax.lax.all_gather(x, axis_name)`` and the flat
    :func:`~.ring.ring_all_gather`. Without ``hpz`` the group is the
    whole mesh (``n_g = n``); with ``hpz > 1`` the gather runs the
    UNIFIED hpZ tier (:func:`hpz_tier_dims`): grouped ring phases over
    the mesh axes the hpZ box covers, ``n_g = hpz`` — bitwise-equal to
    the native grouped gather over ``hpz`` consecutive ranks.

    Phases run inner (fast) axis to outer: each phase ring-gathers the
    block gathered so far over that axis's groups, so the fast wire
    carries ``(a_inner - 1) * |x|`` per device and the long haul
    ``(a_outer - 1) * a_inner * |x|`` — separately attributed.

    ``pipeline_chunks > 1`` PHASE-PIPELINES the gather: the payload is
    split into that many column chunks and each chunk rides its own
    full phase chain, so chunk k's long-haul ring consumes ONLY chunk
    k's intra output — chunk k+1's intra phase is structurally
    independent of chunk k's long-haul phase (the same def-use
    discipline the PR 9 flat rings use between steps), which the HLO
    auditor scores as cross-axis permute pairs. Pure data movement:
    bitwise-equal to the unpipelined form at any chunk count. (Under
    ``longhaul_bits`` each chunk quantizes independently — group
    boundaries follow the chunk split, so the pipelined lossy wire is
    deterministic but not bit-identical to the unpipelined lossy wire;
    gated by trajectory tolerance like every lossy wire.)

    ``longhaul_bits`` (8 / 4): the long-haul phase ships the gathered
    block int8/int4 group-quantized + fp32 scales instead of full
    width. Rows from this device's OWN long-haul coordinate never cross
    the slow wire and stay bit-exact; every other row dequantizes on
    arrival (deterministic — a re-gather reconstructs identical
    values, which is what keeps forward and backward re-gathers at the
    same linearization point). Matched byte pairs are logged under
    ``<op_name>_longhaul``."""
    # composed (data, model, pipe, ...) factorings: the ZeRO gather
    # rides only the data-role axes (identity for all-data specs)
    spec = spec.zero_subspec()
    phases = _gather_phases(spec, hpz)
    n_g = 1
    for _, _, span in phases:
        n_g *= span

    def run(piece):
        return _gather_run(piece, axis_name, spec, phases,
                           chunks=chunks, longhaul_bits=longhaul_bits,
                           group_size=group_size, op_name=op_name)

    if pipeline_chunks is None or pipeline_chunks <= 1 or x.size <= 1:
        return run(x)
    flat = x.reshape(-1)
    pieces = [run(flat[lo:hi]).reshape(n_g, -1)
              for lo, hi in _chunk_bounds(flat.shape[0],
                                          pipeline_chunks)]
    wide = pieces[0] if len(pieces) == 1 \
        else jnp.concatenate(pieces, axis=1)
    return wide.reshape((n_g,) + x.shape)


def _a2a_run(flat_rows, axis_name, spec: HierMeshSpec, *, chunks,
             op_name):
    """One full multi-phase row exchange of ``flat_rows`` ``[n, w]``:
    returns ``[n, w]`` received rows in source-rank order."""
    sizes = spec.sizes
    cur = flat_rows.reshape(tuple(sizes) + (-1,))
    for dim in range(len(sizes) - 1, -1, -1):
        groups = axis_groups(sizes, dim)
        lead = jnp.moveaxis(cur, dim, 0)
        got = decomposed_all_to_all_rows(
            lead.reshape(sizes[dim], -1), axis_name,
            axis_index_groups=groups, chunks=chunks, op_name=op_name,
            wire_axis=spec.axes[dim].name)
        cur = jnp.moveaxis(got.reshape(lead.shape), 0, dim)
    return cur.reshape(flat_rows.shape)


def hierarchical_all_to_all_rows(rows, axis_name, spec: HierMeshSpec, *,
                                 chunks: int = 1,
                                 pipeline_chunks: int = 1,
                                 op_name: str = "hier_all_to_all"):
    """Hierarchical row exchange: ``rows`` is ``[n, ...]`` with row
    ``d`` destined for global rank ``d``; returns ``[n, ...]`` received
    rows in SOURCE-rank order — the same layout (and bits) as
    ``jax.lax.all_to_all(rows, axis_name, 0, 0)`` and the flat
    :func:`~.ring.decomposed_all_to_all_rows`.

    One grouped direct-delivery phase per mesh axis, inner to outer:
    the phase for dim ``j`` exchanges, within each dim-``j`` group, the
    blocks indexed by the dim-``j`` DEST coordinate — afterwards that
    index holds the dim-``j`` SOURCE coordinate. Every byte is
    attributed to the mesh axis it rides.

    ``pipeline_chunks > 1`` phase-pipelines the exchange: the row width
    is split into that many column chunks, each riding its own full
    phase chain — chunk k's long-haul delivery is structurally
    independent of chunk k+1's intra delivery. Pure data movement:
    bitwise-equal to the unpipelined form."""
    spec = spec.zero_subspec()
    sizes = spec.sizes
    n = int(np.prod(sizes))
    if rows.shape[0] != n:
        raise ValueError(f"hierarchical_all_to_all_rows needs leading "
                         f"dim == mesh world {n}; got {rows.shape}")
    rest = rows.shape[1:]
    flat = rows.reshape(n, -1)
    if pipeline_chunks is None or pipeline_chunks <= 1 \
            or flat.shape[1] <= 1:
        return _a2a_run(flat, axis_name, spec, chunks=chunks,
                        op_name=op_name).reshape((n,) + rest)
    pieces = [_a2a_run(flat[:, lo:hi], axis_name, spec, chunks=chunks,
                       op_name=op_name)
              for lo, hi in _chunk_bounds(flat.shape[1],
                                          pipeline_chunks)]
    out = pieces[0] if len(pieces) == 1 \
        else jnp.concatenate(pieces, axis=1)
    return out.reshape((n,) + rest)


def hierarchical_reduce_scatter_sum(x, axis_name, spec: HierMeshSpec, *,
                                    chunks: int = 1,
                                    pipeline_chunks: int = 1,
                                    longhaul_bits: Optional[int] = None,
                                    residual=None,
                                    group_size: int = 2048,
                                    op_name: str = "hier_reduce_scatter"):
    """Hierarchical reduce-scatter SUM over the leading dim: ``x`` is
    ``[n * m, ...]``, returns ``[m, ...]`` — bitwise-equal (full-width)
    to ``jax.lax.psum_scatter(..., tiled=True)`` and to the flat
    :func:`~.ring.decomposed_reduce_scatter_sum`, because the transport
    (:func:`hierarchical_all_to_all_rows`) delivers every raw
    contribution and the destination folds them in source-index order
    (fp32 accumulation for sub-fp32 floats) — reduction is never done
    in-network, which is the only way any decomposition matches the
    native fold.

    ``pipeline_chunks > 1`` phase-pipelines the transport (column
    chunks ride independent phase chains) AND the fold: chunk k's fold
    consumes only chunk k's deliveries, so it can start while chunk
    k+1 is still on the wire. The fold order per element is unchanged
    (source-index, elementwise over the width), so the pipelined form
    is bitwise-equal to the unpipelined one at full width.

    ``longhaul_bits`` (8 / 4): contributions CROSSING the long-haul
    axis ship int8/int4 + fp32 scales; contributions that stay on the
    fast axis (this device's own long-haul coordinate) ship full width
    and fold bit-exactly. ``residual`` is the error-feedback state for
    the quantized portion (fp32, shaped like the long-haul phase
    payload; ``None`` with bits set seeds zeros; under pipelining the
    residual columns follow the deterministic chunk-concatenation
    layout) — the own-coordinate slice is pinned to zero since those
    rows never quantize. Returns ``(out, new_residual)`` when
    ``longhaul_bits`` is set, else ``out`` (the flat-ring
    signature)."""
    spec = spec.zero_subspec()
    sizes = spec.sizes
    n = int(np.prod(sizes))
    if x.shape[0] % n:
        raise ValueError(f"hierarchical_reduce_scatter_sum needs "
                         f"leading dim divisible by mesh world {n}; "
                         f"got {x.shape}")
    m = x.shape[0] // n
    chunk_shape = (m,) + x.shape[1:]
    rows = x.reshape(n, -1)
    W = rows.shape[1]
    if pipeline_chunks is None or pipeline_chunks <= 1 or W <= 1:
        bounds = [(0, W)]
    else:
        bounds = _chunk_bounds(W, pipeline_chunks)
    if longhaul_bits is None:
        outs = []
        for lo, hi in bounds:
            ordered = hierarchical_all_to_all_rows(
                rows[:, lo:hi], axis_name, spec, chunks=chunks,
                op_name=op_name)
            outs.append(_index_order_fold(ordered))
        out = outs[0] if len(outs) == 1 else jnp.concatenate(outs)
        return out.reshape(chunk_shape)
    a_lh = sizes[spec.longhaul_dim]
    outs, res_outs = [], []
    off = 0
    for lo, hi in bounds:
        # this chunk's slice of the [a_longhaul, (n/a)*W] residual —
        # columns follow the chunk-concatenation layout below
        rw = (n // a_lh) * (hi - lo)
        res_k = None if residual is None else residual[:, off:off + rw]
        off += rw
        ordered, nres = _longhaul_quantized_exchange(
            rows[:, lo:hi], axis_name, spec, chunks=chunks,
            bits=longhaul_bits, residual=res_k, group_size=group_size,
            op_name=op_name)
        # mixed exact/dequantized rows: fold in fp32 (source-index
        # order, like every decomposed reduce), cast back at the end
        outs.append(_index_order_fold(ordered.astype(jnp.float32)))
        res_outs.append(nres)
    out = outs[0] if len(outs) == 1 else jnp.concatenate(outs)
    new_res = res_outs[0] if len(res_outs) == 1 \
        else jnp.concatenate(res_outs, axis=1)
    return out.astype(x.dtype).reshape(chunk_shape), new_res


def _longhaul_quantized_exchange(rows, axis_name, spec, *, chunks, bits,
                                 residual, group_size, op_name):
    """The quantized-long-haul variant of
    :func:`hierarchical_all_to_all_rows` (reduce direction): fast-axis
    phases run full width (their rows stay in the input dtype); at the
    long-haul phase each outgoing per-peer block is error-feedback
    quantized, shipped as int8/int4 + fp32 scales, and dequantized on
    arrival — except the own-coordinate block, which is delivered
    locally and stays exact. Returns ``(ordered_rows [n, W] fp32,
    new_residual [a_longhaul, W * n/a_longhaul] fp32)``."""
    from ..runtime.onebit import error_feedback_step
    sizes = spec.sizes
    n = int(np.prod(sizes))
    L = spec.longhaul_dim
    residual_out = None
    cur = rows.reshape(tuple(sizes) + (-1,))
    for dim in range(len(sizes) - 1, -1, -1):
        ax = spec.axes[dim]
        groups = axis_groups(sizes, dim)
        lead = jnp.moveaxis(cur, dim, 0)
        a = sizes[dim]
        lead2 = lead.reshape(a, -1)
        if dim == L:
            my_c = _my_coord(axis_name, sizes, dim)
            quant, deq = _row_quantizer(lead2.shape[1], group_size,
                                        bits)
            if residual is None:
                residual = jnp.zeros(lead2.shape, jnp.float32)
            qlast_box = {}

            def compress(c):
                payload, scale, qlast = quant(c)
                qlast_box["v"] = qlast
                return (payload, scale), deq(payload, scale, qlast)

            (payload, scale), _, new_res = error_feedback_step(
                lead2.astype(jnp.float32), residual, compress)
            _log_longhaul_pair(op_name, axis_name, ax.name, payload,
                               scale, lead2.size * lead2.dtype.itemsize)
            p_t = decomposed_all_to_all_rows(
                payload, axis_name, axis_index_groups=groups,
                chunks=chunks, op_name=op_name, wire_axis=ax.name)
            s_t = decomposed_all_to_all_rows(
                scale, axis_name, axis_index_groups=groups,
                chunks=chunks, op_name=op_name, wire_axis=ax.name)
            got = deq(p_t, s_t, qlast_box["v"])
            # own block is delivered locally: exact, and its residual
            # is pinned to zero (that error never rides a wire, so
            # feeding it back would inject a phantom correction)
            own = jnp.take(lead2, my_c, axis=0).astype(jnp.float32)
            got = jax.lax.dynamic_update_slice_in_dim(
                got, own[None], my_c, axis=0)
            own_mask = (jnp.arange(a) == my_c)[:, None]
            residual_out = jnp.where(own_mask, 0.0, new_res)
            cur = jnp.moveaxis(
                got.reshape((a,) + lead.shape[1:]), 0, dim)
        else:
            got = decomposed_all_to_all_rows(
                lead2, axis_name, axis_index_groups=groups,
                chunks=chunks, op_name=op_name, wire_axis=ax.name)
            cur = jnp.moveaxis(got.reshape(lead.shape), 0, dim)
    return cur.reshape((n, -1)), residual_out


def hierarchical_all_reduce_sum(x, axis_name, spec: HierMeshSpec, *,
                                chunks: int = 1,
                                pipeline_chunks: int = 1,
                                op_name: str = "hier_all_reduce"):
    """Hierarchical all-reduce SUM = hierarchical reduce-scatter +
    hierarchical all-gather (value-equivalent to ``jax.lax.psum``,
    bitwise-equal to the flat :func:`~.ring.ring_all_reduce_sum` — both
    fold all ``n`` raw contributions at the destination in source-index
    order). Arbitrary shapes: flattened and zero-padded to a multiple
    of the mesh world size."""
    spec = spec.zero_subspec()
    n = spec.world
    shape, size = x.shape, x.size
    pad = (-size) % n
    flat = jnp.pad(x.reshape(-1), (0, pad))
    mine = hierarchical_reduce_scatter_sum(
        flat, axis_name, spec, chunks=chunks,
        pipeline_chunks=pipeline_chunks, op_name=op_name)
    full = hierarchical_all_gather(
        mine, axis_name, spec, chunks=chunks,
        pipeline_chunks=pipeline_chunks, op_name=op_name)
    return full.reshape(-1)[:size].reshape(shape)


def mesh_bookkeeping_report(spec: HierMeshSpec) -> Dict:
    """Host-side (pure numpy, no devices) consistency gate for a
    declared — possibly composed — factoring: the spec-level 16x16
    bookkeeping evidence the fused-kernel bench phase commits. Checks,
    for EVERY rank of the declared world:

    * mixed-radix round trip — the row-major coordinate tuple
      ``(r // stride_j) % size_j`` reconstructs ``r`` exactly,
    * group partition — for every axis, :func:`axis_groups` partitions
      ``range(world)`` into disjoint groups of exactly that axis's
      size (the ``axis_index_groups`` every grouped ring phase runs
      on),
    * role factoring — ``zero_world * (non-data world) == world`` and
      the data-only :meth:`~HierMeshSpec.zero_subspec` round-trips its
      own coordinates (the sub-box the ZeRO transports and the fused
      kernel's ring actually ride).

    Returns a JSON-safe dict with per-check booleans and an ``ok``
    conjunction — artifact evidence, not an exception path (config
    validation already raises on malformed specs)."""
    sizes = spec.sizes
    world = spec.world
    strides = [int(np.prod(sizes[j + 1:])) for j in range(len(sizes))]
    ranks = np.arange(world)
    coords = [(ranks // strides[j]) % sizes[j]
              for j in range(len(sizes))]
    rebuilt = sum(coords[j] * strides[j] for j in range(len(sizes)))
    roundtrip_ok = bool(np.array_equal(rebuilt, ranks))
    groups_ok = True
    for dim in range(len(sizes)):
        groups = axis_groups(sizes, dim)
        seen = [r for g in groups for r in g]
        groups_ok &= all(len(g) == sizes[dim] for g in groups)
        groups_ok &= sorted(seen) == list(range(world))
    sub = spec.zero_subspec()
    nondata = world // spec.zero_world if spec.zero_world else 0
    factoring_ok = spec.zero_world * nondata == world \
        and sub.world == spec.zero_world \
        and all(sub.axes[i].role == "data" for i in range(len(sub.axes)))
    sub_strides = [int(np.prod(sub.sizes[j + 1:]))
                   for j in range(len(sub.sizes))]
    sub_ranks = np.arange(sub.world)
    sub_rebuilt = sum(((sub_ranks // sub_strides[j]) % sub.sizes[j])
                      * sub_strides[j] for j in range(len(sub.sizes)))
    sub_ok = bool(np.array_equal(sub_rebuilt, sub_ranks)) \
        and sub.longhaul in sub.names
    ok = roundtrip_ok and bool(groups_ok) and factoring_ok and sub_ok
    return {
        "spec": spec.describe(),
        "world": world,
        "zero_world": spec.zero_world,
        "nondata_world": nondata,
        "rank_coord_roundtrip_ok": roundtrip_ok,
        "axis_groups_partition_ok": bool(groups_ok),
        "role_factoring_ok": factoring_ok,
        "zero_subspec_ok": sub_ok,
        "ok": ok,
    }
