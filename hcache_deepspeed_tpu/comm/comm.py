"""Collective-communication facade.

Reference analog: ``deepspeed/comm/comm.py`` (808 LoC) — a
torch.distributed-signature facade over a pluggable ``Backend``
(``comm/backend.py:25``), with ``init_distributed`` (:636) doing rendezvous
and env discovery, every collective wrapped in ``@timed_op`` for logging, and
capability probes with chunked fallbacks (:252-333).

TPU-native re-design:

* **Rendezvous** → ``jax.distributed.initialize()`` (one controller process
  per host; chips inside a process need no rendezvous at all). Env discovery
  keeps the reference's spirit: explicit args > ``HDS_*``/torch-style env
  vars > cloud TPU metadata auto-detection (handled inside jax).
* **Collectives** → thin wrappers over ``jax.lax`` ops on *named mesh axes*.
  A "process group" argument becomes an axis name (or tuple of axis names)
  of the global mesh — see ``parallel/topology.py``. These wrappers are
  traced into jitted programs; XLA chooses ICI/DCN routing and fuses/combines
  (the reference's coalescing manager and `has_all_gather_into_tensor`
  fallback machinery have no equivalent because XLA always provides the
  fused form).
* **Logging** → trace-time size/op recording via ``CommsLogger`` plus XLA
  profiler ranges, replacing host-side ``@timed_op`` timing.

These functions must be called inside a ``shard_map``/``pjit`` context where
the named axes are bound (like the reference's requirement that
``init_process_group`` precede collective calls).
"""

import os

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.logging import logger
from .comms_logging import get_comms_logger

_initialized = False


# ------------------------------------------------------------------ #
# Reduce ops (reference: deepspeed/comm/reduce_op.py mirrors torch)
# ------------------------------------------------------------------ #
class ReduceOp:
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PRODUCT = "product"


def _normalize_axes(group):
    """A 'group' is a mesh-axis name or tuple of names. None = all axes of
    the current shard_map context is not expressible; require explicit."""
    if group is None:
        raise ValueError(
            "group=None: pass a mesh axis name (e.g. 'data') or tuple; on "
            "TPU the named mesh axis *is* the process group")
    if isinstance(group, str):
        return (group,)
    return tuple(group)


def _log(op_name, x, axes):
    try:
        size = x.size * x.dtype.itemsize
    except Exception:
        size = 0
    get_comms_logger().append(op_name, axes, size)


# ------------------------------------------------------------------ #
# Rendezvous / process bootstrap
# ------------------------------------------------------------------ #
def init_distributed(dist_backend=None,
                     auto_mpi_discovery=True,
                     init_method=None,
                     rank=-1,
                     world_size=-1,
                     timeout=None,
                     coordinator_address=None):
    """Bootstrap multi-host execution.

    Reference: ``comm/comm.py:636 init_distributed`` (+ mpi/AML/SageMaker env
    discovery :705-808). Here rendezvous is only needed across *hosts*;
    single-host (even 256-chip single-slice via one controller) needs nothing.
    Safe to call multiple times.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "HDS_COORDINATOR_ADDRESS")
    num_processes = world_size if world_size > 0 else _env_int(
        "HDS_NUM_PROCESSES", _env_int("WORLD_SIZE", -1))
    process_id = rank if rank >= 0 else _env_int(
        "HDS_PROCESS_ID", _env_int("RANK", -1))

    if coordinator_address or num_processes > 1:
        kwargs = {}
        if coordinator_address:
            kwargs["coordinator_address"] = coordinator_address
        if num_processes > 0:
            kwargs["num_processes"] = num_processes
        if process_id >= 0:
            kwargs["process_id"] = process_id
        if _platform_is_cpu():
            # Cross-process collectives on the CPU backend need a
            # transport (TPU rides ICI/DCN natively); gloo is jax's
            # built-in one. The reference's analog is the CCL backend
            # for CPU runs (SURVEY §2.2). Must be set before backends
            # initialise.
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except Exception as e:   # older jax spelling
                logger.warning(f"cpu collectives unavailable: {e}")
        logger.info(f"jax.distributed.initialize({kwargs})")
        jax.distributed.initialize(**kwargs)
    else:
        # Cloud TPU pod slices auto-discover through the metadata server;
        # initialize() is then arg-free. Probe the env FIRST — touching
        # jax.process_count() would initialise the backend and make
        # jax.distributed.initialize() impossible.
        if _looks_like_pod():
            try:
                jax.distributed.initialize()
            except Exception as e:  # already initialised or not a pod
                logger.warning(f"jax.distributed.initialize() skipped: {e}")
    _initialized = True


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _platform_is_cpu():
    """True when jax may run on the cpu backend — decided WITHOUT
    touching jax.devices()/default_backend(), which would initialise the
    backend and foreclose jax.distributed.initialize(). Unset platform
    counts as cpu (jax falls back to cpu when no accelerator is found,
    and the gloo knob is harmless on TPU)."""
    cfg = getattr(jax.config, "jax_platforms", None)
    platforms = cfg or os.environ.get("JAX_PLATFORMS", "")
    first = platforms.split(",")[0].strip().lower()
    return first in ("", "cpu")


def _looks_like_pod():
    return any(k in os.environ for k in
               ("TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS"))


def is_initialized():
    return _initialized


def get_rank():
    return jax.process_index()


def get_world_size():
    return jax.process_count()


def get_local_device_count():
    return jax.local_device_count()


def get_global_device_count():
    return jax.device_count()


def barrier():
    """Host-level barrier across all processes."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("hds_barrier")


# ------------------------------------------------------------------ #
# In-program collectives (called under shard_map over the global mesh)
# ------------------------------------------------------------------ #
def all_reduce(x, op=ReduceOp.SUM, group=None):
    """Reference: comm.py:221 all_reduce → here lax.p* on mesh axes."""
    axes = _normalize_axes(group)
    _log("all_reduce", x, axes)
    if op == ReduceOp.SUM:
        return lax.psum(x, axes)
    if op == ReduceOp.AVG:
        return lax.pmean(x, axes)
    if op == ReduceOp.MAX:
        return lax.pmax(x, axes)
    if op == ReduceOp.MIN:
        return lax.pmin(x, axes)
    if op == ReduceOp.PRODUCT:
        # no native pprod; exp/log trick is unstable — use allgather+prod
        g = lax.all_gather(x, axes)
        return jnp.prod(g, axis=0)
    raise ValueError(f"unknown reduce op {op}")


def all_gather(x, group=None, axis=0, tiled=True):
    """Reference: all_gather_into_tensor (comm.py:252). ``tiled=True``
    concatenates along ``axis`` (torch semantics); False stacks a new dim."""
    axes = _normalize_axes(group)
    _log("all_gather", x, axes)
    return lax.all_gather(x, axes, axis=axis, tiled=tiled)


def reduce_scatter(x, op=ReduceOp.SUM, group=None, scatter_dimension=0):
    """Reference: reduce_scatter_tensor (comm.py:289)."""
    axes = _normalize_axes(group)
    _log("reduce_scatter", x, axes)
    assert op in (ReduceOp.SUM, ReduceOp.AVG)
    out = lax.psum_scatter(x, axes, scatter_dimension=scatter_dimension,
                           tiled=True)
    if op == ReduceOp.AVG:
        out = out / _group_size(axes)
    return out


def all_to_all(x, group=None, split_axis=0, concat_axis=0):
    """Reference: all_to_all_single (comm.py:351); backbone of Ulysses and
    MoE dispatch."""
    axes = _normalize_axes(group)
    _log("all_to_all", x, axes)
    if len(axes) != 1:
        raise ValueError("all_to_all runs over exactly one mesh axis")
    return lax.all_to_all(x, axes[0], split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def ppermute(x, perm, group=None):
    """Point-to-point ring shift (reference: pipeline p2p send/recv,
    ``runtime/pipe/p2p.py`` — TPU-native form is a collective permute)."""
    axes = _normalize_axes(group)
    _log("ppermute", x, axes)
    if len(axes) != 1:
        raise ValueError("ppermute runs over exactly one mesh axis")
    return lax.ppermute(x, axes[0], perm)


def broadcast(x, src=0, group=None):
    """Broadcast from mesh-coordinate ``src`` along ``group`` axes."""
    axes = _normalize_axes(group)
    _log("broadcast", x, axes)
    if len(axes) != 1:
        raise ValueError("broadcast runs over one mesh axis")
    ax = axes[0]
    idx = lax.axis_index(ax)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(masked, ax)


def axis_index(group):
    axes = _normalize_axes(group)
    if len(axes) == 1:
        return lax.axis_index(axes[0])
    # row-major linearised index over multiple axes
    idx = lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx


def _group_size(axes):
    size = 1
    for a in axes:
        size *= lax.axis_size(a)
    return size


def get_group_size(group):
    """Static group size from the installed topology (host-side)."""
    from ..parallel.topology import get_topology
    topo = get_topology()
    return int(jnp.prod(jnp.array(
        [topo.axis_size(a) for a in _normalize_axes(group)])))


def log_summary(monitor=None, step=0):
    """Reference: ``dist.log_summary()`` (comm/comm.py:428) — prints the
    aggregate op → count/volume table; with ``monitor`` the same
    aggregate also rides ``MonitorMaster.write_events`` so comm volume
    lands beside the step metrics."""
    get_comms_logger().log_summary(monitor=monitor, step=step)


def configure(enabled=None, verbose=None, prof_all=None, prof_ops=None,
              debug=None):
    get_comms_logger().configure(enabled, verbose, prof_all, prof_ops,
                                 debug)
