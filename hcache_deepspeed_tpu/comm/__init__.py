from .comm import (ReduceOp, all_gather, all_reduce, all_to_all, axis_index,
                   barrier, broadcast, configure, get_global_device_count,
                   get_local_device_count, get_rank, get_world_size,
                   init_distributed, is_initialized, log_summary, ppermute,
                   reduce_scatter)
from .comms_logging import CommsLogger, get_comms_logger
from .hierarchical import (HierMeshSpec, MeshAxis, axis_groups,
                           hierarchical_all_gather,
                           hierarchical_all_reduce_sum,
                           hierarchical_all_to_all_rows,
                           hierarchical_reduce_scatter_sum,
                           make_mesh_spec, validate_mesh_spec)
from .overlap import CollectiveIssue, Ticket
from .ring import (COLLECTIVE_IMPLS, decomposed_all_to_all_rows,
                   decomposed_reduce_scatter_sum, ring_all_gather,
                   ring_all_reduce_sum)

__all__ = [
    "CollectiveIssue", "Ticket",
    "ReduceOp", "all_gather", "all_reduce", "all_to_all", "axis_index",
    "barrier", "broadcast", "configure", "get_global_device_count",
    "get_local_device_count", "get_rank", "get_world_size",
    "init_distributed", "is_initialized", "log_summary", "ppermute",
    "reduce_scatter", "CommsLogger", "get_comms_logger",
    "COLLECTIVE_IMPLS", "ring_all_gather", "ring_all_reduce_sum",
    "decomposed_all_to_all_rows", "decomposed_reduce_scatter_sum",
    "HierMeshSpec", "MeshAxis", "axis_groups", "make_mesh_spec",
    "validate_mesh_spec", "hierarchical_all_gather",
    "hierarchical_all_to_all_rows", "hierarchical_reduce_scatter_sum",
    "hierarchical_all_reduce_sum",
]
