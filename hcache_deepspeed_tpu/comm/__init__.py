from .comm import (ReduceOp, all_gather, all_reduce, all_to_all, axis_index,
                   barrier, broadcast, configure, get_global_device_count,
                   get_local_device_count, get_rank, get_world_size,
                   init_distributed, is_initialized, log_summary, ppermute,
                   reduce_scatter)
from .comms_logging import CommsLogger, get_comms_logger
from .overlap import CollectiveIssue, Ticket

__all__ = [
    "CollectiveIssue", "Ticket",
    "ReduceOp", "all_gather", "all_reduce", "all_to_all", "axis_index",
    "barrier", "broadcast", "configure", "get_global_device_count",
    "get_local_device_count", "get_rank", "get_world_size",
    "init_distributed", "is_initialized", "log_summary", "ppermute",
    "reduce_scatter", "CommsLogger", "get_comms_logger",
]
