"""Lock-discipline race detection (family ``locks``).

Per class that uses ``with self.<lock>`` anywhere, infer the set of
attribute paths the lock guards and flag undisciplined access:

* **HDS-L001** — a guarded attribute is *mutated* outside the lock
  (assignment, aug-assignment, subscript store, or a mutating method
  call such as ``append``/``clear``/``pop``) in any method other than
  ``__init__``.
* **HDS-L002** — a guarded attribute is *snapshot-read* outside the
  lock: used as the iterable of a ``for``/comprehension or passed to a
  copying builtin (``list``/``dict``/``sorted``/``sum``/...). Bare
  reference reads, truthiness, ``len``, membership tests and single
  subscript reads are deliberately NOT flagged — under the GIL those
  are single atomic operations, and flagging them drowned the real
  races in noise (that exemption is the rule refinement the fleet's
  ``has_work`` / the server's ``healthy`` demanded; see
  docs/analysis.md).
* **HDS-L003** — a lock acquisition lexically nested inside another
  lock's ``with`` block in a module that does not declare its order
  via a module-level ``__hds_lock_order__ = ("OuterClass._lock",
  "InnerClass._lock")`` tuple. (Cross-method nesting — taking lock B
  inside a helper called under lock A — is invisible to lexical
  analysis; the *dynamic* lock-order sentinel in
  :mod:`.runtime` owns that half.)

Inference details that keep the rule quiet on disciplined code:

* Guarded paths are dotted up to two levels (``_ingress``,
  ``scheduler.done``): a subscript store into ``self.scheduler.done``
  guards that path, not the whole ``scheduler`` object.
* A *private* method whose every intra-class call site sits inside the
  lock inherits the lock context (fixpoint) — helpers like the
  server's ``_estimated_demand_blocks`` are analyzed as locked.
  Public methods and properties never inherit: they are externally
  callable by definition.
* Call sites inside a method suppressed by a def-line allow pragma do
  not count toward the fixpoint — the fleet's virtual-clock ``step()``
  is single-threaded by contract and must not leak "unlocked caller"
  evidence onto the helpers the thread-mode pump calls under the
  lock.
"""

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import AnalysisContext, Finding, ModuleInfo, Rule

#: method names that mutate their receiver in place
MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "remove",
    "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse", "difference_update",
    "intersection_update", "symmetric_difference_update",
})

#: builtins that take a snapshot of (iterate) their argument
SNAPSHOT_BUILTINS = frozenset({
    "list", "tuple", "dict", "set", "frozenset", "sorted", "sum",
    "min", "max", "any", "all", "enumerate", "map", "filter",
    "reversed",
})


def _is_lockish_name(name: str) -> bool:
    return "lock" in name.lower()


def _lock_ctx_name(expr: ast.expr) -> Optional[str]:
    """The lock-ish name a ``with`` context expr acquires, if any:
    ``self._lock`` -> "_lock"; ``self._locked(r)`` -> "_locked";
    anything else -> None."""
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and \
            expr.value.id == "self" and _is_lockish_name(expr.attr):
        return expr.attr
    if isinstance(expr, ast.Call):
        return _lock_ctx_name(expr.func)
    return None


def _self_path(expr: ast.expr, max_depth: int = 2) -> Optional[str]:
    """Dotted attribute path rooted at ``self``, up to ``max_depth``
    levels: ``self._ingress`` -> "_ingress";
    ``self.scheduler.done`` -> "scheduler.done"; deeper chains
    truncate to their two-level prefix."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not (isinstance(node, ast.Name) and node.id == "self"):
        return None
    parts.reverse()
    if not parts:
        return None
    return ".".join(parts[:max_depth])


def _read_path(expr: ast.expr) -> Optional[str]:
    """Self-path of a read expression, seeing through the dict view
    calls (``self.counters.items()`` reads ``counters``)."""
    p = _self_path(expr)
    if p is not None:
        return p
    if isinstance(expr, ast.Call) and \
            isinstance(expr.func, ast.Attribute) and \
            expr.func.attr in ("items", "values", "keys"):
        return _self_path(expr.func.value)
    return None


@dataclass
class _Access:
    path: str
    line: int
    locked: bool
    method: str
    kind: str        # "mutate" | "iter" | "snapshot"
    symbol: str


@dataclass
class _MethodFacts:
    name: str
    node: ast.FunctionDef
    is_public: bool = False
    is_property: bool = False
    accesses: List[_Access] = field(default_factory=list)
    #: (callee, call_site_locked) for self.method() calls
    calls: List[Tuple[str, bool]] = field(default_factory=list)
    #: whole method covered by an allow pragma for L-codes — its call
    #: sites don't count as "unlocked caller" evidence
    suppressed: bool = False


class _MethodWalker(ast.NodeVisitor):
    """Walk one method body tracking lexical lock depth and recording
    guarded-path accesses + intra-class calls."""

    def __init__(self, facts: _MethodFacts, mod: ModuleInfo):
        self.facts = facts
        self.mod = mod
        self.depth = 0

    # -- lock blocks ---------------------------------------------- #
    def visit_With(self, node: ast.With) -> None:
        # only a *direct* self-lock attribute guards this class's
        # state; ``self._locked(r)`` (a Call) acquires some OTHER
        # object's lock and contributes nothing to self-discipline
        own = sum(1 for item in node.items
                  if isinstance(item.context_expr, ast.Attribute) and
                  _lock_ctx_name(item.context_expr) is not None)
        for item in node.items:
            self.visit(item.context_expr)
        self.depth += own
        for stmt in node.body:
            self.visit(stmt)
        self.depth -= own

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return  # nested defs: skip (their lock context is unknowable)

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    # -- mutations ------------------------------------------------- #
    def _record(self, path: Optional[str], node: ast.AST,
                kind: str, symbol: str) -> None:
        if path is None:
            return
        self.facts.accesses.append(_Access(
            path=path, line=node.lineno, locked=self.depth > 0,
            method=self.facts.name, kind=kind, symbol=symbol))

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._target(tgt)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._target(node.target)
        self.visit(node.value)

    def _target(self, tgt: ast.expr) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._target(elt)
        elif isinstance(tgt, ast.Attribute):
            self._record(_self_path(tgt), tgt, "mutate", tgt.attr)
        elif isinstance(tgt, ast.Subscript):
            base = _self_path(tgt.value)
            if base is not None:
                self._record(base, tgt, "mutate",
                             base.rsplit(".", 1)[-1])
            else:
                self.visit(tgt.value)
            self.visit(tgt.slice)

    # -- calls: mutators + intra-class edges ----------------------- #
    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            recv_path = _self_path(func.value)
            if recv_path is not None and func.attr in MUTATORS:
                self._record(recv_path, node, "mutate",
                             recv_path.rsplit(".", 1)[-1])
            if isinstance(func.value, ast.Name) and \
                    func.value.id == "self":
                self.facts.calls.append((func.attr, self.depth > 0))
        if isinstance(func, ast.Name) and \
                func.id in SNAPSHOT_BUILTINS:
            for arg in node.args:
                p = _read_path(arg)
                if p is not None:
                    self._record(p, arg, "snapshot",
                                 p.rsplit(".", 1)[-1])
        self.generic_visit(node)

    # -- iteration ------------------------------------------------- #
    def visit_For(self, node: ast.For) -> None:
        p = _read_path(node.iter)
        if p is not None:
            self._record(p, node.iter, "iter", p.rsplit(".", 1)[-1])
        self.generic_visit(node)

    def _comp(self, node) -> None:
        for gen in node.generators:
            p = _read_path(gen.iter)
            if p is not None:
                self._record(p, gen.iter, "iter",
                             p.rsplit(".", 1)[-1])
        self.generic_visit(node)

    visit_ListComp = _comp
    visit_SetComp = _comp
    visit_DictComp = _comp
    visit_GeneratorExp = _comp


def _method_facts(cls: ast.ClassDef,
                  mod: ModuleInfo) -> Dict[str, _MethodFacts]:
    out: Dict[str, _MethodFacts] = {}
    for node in cls.body:
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        facts = _MethodFacts(name=node.name, node=node)
        facts.is_public = not node.name.startswith("_")
        for dec in node.decorator_list:
            if isinstance(dec, ast.Name) and dec.id == "property":
                facts.is_property = True
        facts.suppressed = any(
            code.startswith("HDS-L")
            for code in mod.allows.get(node.lineno, ()))
        walker = _MethodWalker(facts, mod)
        for stmt in node.body:     # not .visit(node): the nested-def
            walker.visit(stmt)     # skip would swallow the method
        out[node.name] = facts
    return out


def _uses_self_lock(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, ast.With):
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Attribute) and \
                        isinstance(expr.value, ast.Name) and \
                        expr.value.id == "self" and \
                        _is_lockish_name(expr.attr):
                    return True
    return False


def _locked_context_fixpoint(
        methods: Dict[str, _MethodFacts]) -> Set[str]:
    """Private, non-property methods whose every intra-class call site
    is lock-held (directly or via an already-locked caller) inherit
    the lock context. Call sites inside suppressed methods are
    ignored. Methods with no intra-class call sites stay unlocked
    (someone external calls them)."""
    callers: Dict[str, List[Tuple[str, bool]]] = {}
    for m in methods.values():
        if m.suppressed:
            continue
        for callee, locked in m.calls:
            if callee in methods:
                callers.setdefault(callee, []).append(
                    (m.name, locked))
    locked_ctx: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, m in methods.items():
            if name in locked_ctx or m.is_public or m.is_property \
                    or name == "__init__":
                continue
            sites = callers.get(name)
            if not sites:
                continue
            if all(locked or caller in locked_ctx
                   for caller, locked in sites):
                locked_ctx.add(name)
                changed = True
    return locked_ctx


class LockDisciplineRule(Rule):
    family = "locks"
    codes = ("HDS-L001", "HDS-L002", "HDS-L003")

    def check_module(self, mod: ModuleInfo,
                     ctx: AnalysisContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and \
                    _uses_self_lock(node):
                findings.extend(self._check_class(node, mod))
        findings.extend(self._check_nesting(mod))
        return findings

    # ------------------------------------------------------------- #
    def _check_class(self, cls: ast.ClassDef,
                     mod: ModuleInfo) -> List[Finding]:
        methods = _method_facts(cls, mod)
        locked_ctx = _locked_context_fixpoint(methods)

        def effective(acc: _Access) -> bool:
            return acc.locked or acc.method in locked_ctx

        guarded: Set[str] = set()
        for m in methods.values():
            for acc in m.accesses:
                if acc.kind == "mutate" and effective(acc) and \
                        m.name != "__init__":
                    guarded.add(acc.path)
        # a lock attribute itself is not "state" it guards
        guarded = {p for p in guarded
                   if not _is_lockish_name(p.split(".")[0])}
        out: List[Finding] = []
        for m in methods.values():
            if m.name == "__init__":
                continue
            for acc in m.accesses:
                if acc.path not in guarded or effective(acc):
                    continue
                if acc.kind == "mutate":
                    out.append(Finding(
                        code="HDS-L001", family=self.family,
                        path=mod.relpath, line=acc.line,
                        qualname=f"{cls.name}.{m.name}",
                        symbol=acc.path,
                        message=(f"'self.{acc.path}' is mutated "
                                 f"under the lock elsewhere in "
                                 f"{cls.name} but mutated here "
                                 f"without it")))
                elif acc.kind in ("iter", "snapshot"):
                    out.append(Finding(
                        code="HDS-L002", family=self.family,
                        path=mod.relpath, line=acc.line,
                        qualname=f"{cls.name}.{m.name}",
                        symbol=acc.path,
                        message=(f"snapshot read of guarded "
                                 f"'self.{acc.path}' outside the "
                                 f"lock ({acc.kind} is not atomic "
                                 f"against concurrent mutation)")))
        return out

    # ------------------------------------------------------------- #
    def _check_nesting(self, mod: ModuleInfo) -> List[Finding]:
        out: List[Finding] = []

        def lockish(expr: ast.expr) -> Optional[str]:
            # any receiver counts here — the inner lock is usually
            # someone ELSE's (``other.inner_lock``, ``self._locked(r)``)
            if isinstance(expr, ast.Attribute) and \
                    _is_lockish_name(expr.attr):
                return expr.attr
            if isinstance(expr, ast.Name) and \
                    _is_lockish_name(expr.id):
                return expr.id
            if isinstance(expr, ast.Call):
                return lockish(expr.func)
            return None

        def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
            for child in ast.iter_child_nodes(node):
                held_here = held
                if isinstance(child, ast.With):
                    names = [
                        lockish(i.context_expr)
                        for i in child.items
                        if lockish(i.context_expr) is not None]
                    if names and held and mod.lock_order is None:
                        out.append(Finding(
                            code="HDS-L003", family=self.family,
                            path=mod.relpath, line=child.lineno,
                            qualname="<module>",
                            symbol=f"{held[-1]}->{names[0]}",
                            message=(
                                f"lock '{names[0]}' acquired while "
                                f"holding '{held[-1]}' with no "
                                f"module-level __hds_lock_order__ "
                                f"declaration")))
                    held_here = held + tuple(names)
                walk(child, held_here)

        walk(mod.tree, ())
        return out
