"""Virtual-clock / determinism purity lints (family ``purity``).

The committed chaos digests (CHAOS_SERVE, FLEET_SERVE, DISAGG_SERVE)
assert byte-identical same-seed replay of the whole serving stack.
These rules forbid, in declared sim-deterministic modules, exactly the
constructs that would silently break that property:

* **HDS-P001** — ambient wall-clock reads: ``time.time()``,
  ``time.monotonic()`` (+ ``_ns`` variants), ``datetime.now()`` /
  ``utcnow()`` / ``today()``. Interval timing via
  ``time.perf_counter`` is NOT flagged — measuring how long something
  took doesn't steer the simulation; reading "now" does. Sanctioned
  sites (the ``MonotonicClock`` implementation, the perf registry's
  CLI-injectable freshness default) carry allow pragmas.
* **HDS-P002** — unseeded RNG: any call through the module-level
  ``random.*`` / ``np.random.*`` global streams, or
  ``default_rng()`` / ``random.Random()`` constructed without a seed.
  Checked package-wide (not just sim modules): a shared global stream
  is a cross-test, cross-thread determinism hazard everywhere in this
  repo. Seeded generators (``default_rng(seed)``) pass.
* **HDS-P003** — ``id()`` / ``hash()`` inside an ordering key
  (``sorted``/``sort``/``min``/``max`` ``key=``): CPython ids are
  allocation addresses and str hashes are salted per process — both
  silently reorder events between runs.
* **HDS-P004** — iterating a ``set`` (literal, comprehension,
  ``set()`` call, or a local variable bound to one) without
  ``sorted()``: hash-salted iteration order feeding event ordering is
  the classic digest-breaker.
"""

import ast
from typing import Dict, Iterable, List, Optional, Set

from .core import AnalysisContext, Finding, ModuleInfo, Rule

_WALL_CLOCK = {
    ("time", "time"), ("time", "time_ns"),
    ("time", "monotonic"), ("time", "monotonic_ns"),
    ("datetime", "now"), ("datetime", "utcnow"),
    ("datetime", "today"),
}

#: np.random module-level functions that consume the GLOBAL stream
_NP_GLOBAL_OK = {"default_rng", "Generator", "SeedSequence",
                 "PCG64", "Philox", "BitGenerator", "RandomState"}


def _dotted(node: ast.expr) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return ".".join(parts)
    return None


def _is_unseeded(call: ast.Call) -> bool:
    """``default_rng()`` / ``Random()`` with no positional seed (or an
    explicit ``None``) draws OS entropy — unseeded."""
    if not call.args:
        return True
    first = call.args[0]
    return isinstance(first, ast.Constant) and first.value is None


def _set_locals(func: ast.AST) -> Set[str]:
    """Local names bound (once) to a set expression in this scope —
    the cheap flow-insensitive approximation that catches
    ``s = set(...) ... for x in s``."""
    bound: Dict[str, bool] = {}
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) and \
                len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            is_set = isinstance(node.value, (ast.Set, ast.SetComp)) \
                or (isinstance(node.value, ast.Call) and
                    isinstance(node.value.func, ast.Name) and
                    node.value.func.id in ("set", "frozenset"))
            # rebinding to a non-set clears the mark
            bound[name] = is_set if name not in bound \
                else (bound[name] and is_set)
    return {n for n, ok in bound.items() if ok}


class PurityRule(Rule):
    family = "purity"
    codes = ("HDS-P001", "HDS-P002", "HDS-P003", "HDS-P004")

    def check_module(self, mod: ModuleInfo,
                     ctx: AnalysisContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        qual = _QualTracker(mod)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                findings.extend(self._check_call(node, mod, qual))
        if mod.sim_deterministic:
            findings.extend(self._check_set_iteration(mod, qual))
        return findings

    # ------------------------------------------------------------- #
    def _check_call(self, call: ast.Call, mod: ModuleInfo,
                    qual) -> List[Finding]:
        out: List[Finding] = []
        name = _dotted(call.func)
        if name is None:
            return out
        head, _, tail = name.partition(".")
        # P001: ambient clock in sim-deterministic modules
        if mod.sim_deterministic:
            leaf = name.rsplit(".", 1)[-1]
            if (head, leaf) in _WALL_CLOCK or \
                    ("datetime", leaf) in _WALL_CLOCK and \
                    "datetime" in name:
                out.append(Finding(
                    code="HDS-P001", family=self.family,
                    path=mod.relpath, line=call.lineno,
                    qualname=qual.at(call.lineno), symbol=name,
                    message=(f"wall-clock call {name}() in a "
                             f"sim-deterministic module — read the "
                             f"injected Clock/now= instead")))
        # P002: global-stream / unseeded RNG (package-wide)
        if name.startswith("np.random.") or \
                name.startswith("numpy.random."):
            leaf = name.rsplit(".", 1)[-1]
            if leaf not in _NP_GLOBAL_OK:
                out.append(self._p002(call, mod, qual, name,
                                      "module-level numpy RNG stream"))
            elif leaf in ("default_rng", "RandomState") and \
                    _is_unseeded(call):
                out.append(self._p002(call, mod, qual, name,
                                      "unseeded generator"))
        elif head == "random" and tail and "." not in tail:
            if tail == "Random":
                if _is_unseeded(call):
                    out.append(self._p002(call, mod, qual, name,
                                          "unseeded random.Random"))
            elif tail[0].islower():
                out.append(self._p002(call, mod, qual, name,
                                      "module-level stdlib RNG "
                                      "stream"))
        # P003: id()/hash() ordering keys
        if mod.sim_deterministic and isinstance(call.func, (
                ast.Name, ast.Attribute)):
            fn_leaf = name.rsplit(".", 1)[-1]
            if fn_leaf in ("sorted", "sort", "min", "max"):
                for kw in call.keywords:
                    if kw.arg == "key" and _mentions_id_hash(kw.value):
                        out.append(Finding(
                            code="HDS-P003", family=self.family,
                            path=mod.relpath, line=call.lineno,
                            qualname=qual.at(call.lineno),
                            symbol=fn_leaf,
                            message=("ordering key uses id()/hash() "
                                     "— address/salt dependent, "
                                     "reorders between runs")))
        return out

    def _p002(self, call: ast.Call, mod: ModuleInfo, qual,
              name: str, why: str) -> Finding:
        return Finding(
            code="HDS-P002", family=self.family, path=mod.relpath,
            line=call.lineno, qualname=qual.at(call.lineno),
            symbol=name,
            message=(f"{name}() draws from a {why} — use a seeded "
                     f"np.random.default_rng(seed) (overridable "
                     f"default)"))

    # ------------------------------------------------------------- #
    def _check_set_iteration(self, mod: ModuleInfo,
                             qual) -> List[Finding]:
        out: List[Finding] = []

        def scope_check(scope: ast.AST) -> None:
            set_names = _set_locals(scope)

            def is_set_expr(e: ast.expr) -> bool:
                if isinstance(e, (ast.Set, ast.SetComp)):
                    return True
                if isinstance(e, ast.Call) and \
                        isinstance(e.func, ast.Name) and \
                        e.func.id in ("set", "frozenset"):
                    return True
                return isinstance(e, ast.Name) and \
                    e.id in set_names

            for node in ast.walk(scope):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and \
                        node is not scope:
                    continue
                iters: List[ast.expr] = []
                if isinstance(node, ast.For):
                    iters.append(node.iter)
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.DictComp,
                                       ast.GeneratorExp)):
                    iters.extend(g.iter for g in node.generators)
                for it in iters:
                    if is_set_expr(it):
                        out.append(Finding(
                            code="HDS-P004", family=self.family,
                            path=mod.relpath, line=it.lineno,
                            qualname=qual.at(it.lineno),
                            symbol="set-iteration",
                            message=("iterating a set in a sim-"
                                     "deterministic module — wrap in "
                                     "sorted() so hash salting can't "
                                     "reorder events")))

        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                scope_check(node)
        return out


def _mentions_id_hash(expr: ast.expr) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id in ("id", "hash"):
            return True
        if isinstance(node, ast.Name) and node.id in ("id", "hash"):
            # bare ``key=id``
            return True
    return False


class _QualTracker:
    """line -> enclosing Class.method / function qualname."""

    def __init__(self, mod: ModuleInfo):
        self._spans: List = []

        def walk(node, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    name = (f"{prefix}.{child.name}"
                            if prefix else child.name)
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        self._spans.append(
                            (child.lineno,
                             child.end_lineno or child.lineno, name))
                    walk(child, name)
                else:
                    walk(child, prefix)

        walk(mod.tree, "")
        self._spans.sort()

    def at(self, line: int) -> str:
        best = "<module>"
        for start, end, name in self._spans:
            if start <= line <= end:
                best = name   # innermost wins (spans sorted by start)
        return best
