"""Concurrency & determinism analyzer.

``python -m hcache_deepspeed_tpu.analysis`` runs four rule families
over the tree (lock discipline, determinism purity, repo conventions,
perf-artifact provenance) against the committed
``analysis/BASELINE.json``; :mod:`.runtime` is the dynamic lock-order
sentinel the serving/chaos test suites enable. See docs/analysis.md.
"""

from .core import (AnalysisConfig, Finding, Report, baseline_path,
                   gate, load_baseline, run_analysis, save_baseline)
from .runtime import (LockOrderError, OrderedLock, disable_sentinel,
                      enable_sentinel, make_lock, observed_edges,
                      sentinel, sentinel_enabled)

__all__ = [
    "AnalysisConfig", "Finding", "Report", "run_analysis", "gate",
    "load_baseline", "save_baseline", "baseline_path",
    "LockOrderError", "OrderedLock", "make_lock", "sentinel",
    "sentinel_enabled", "enable_sentinel", "disable_sentinel",
    "observed_edges",
]
