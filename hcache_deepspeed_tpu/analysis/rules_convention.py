"""Repo-convention lints (family ``convention``).

* **HDS-C001** — async tracer spans must be begin/end paired: a
  literal span name passed to ``async_begin`` somewhere in the tree
  must have a matching literal ``async_end`` somewhere in the tree
  (cross-module: the scheduler opens ``"request"``; the scheduler OR
  the fleet may close it). Computed names (``async_begin(
  self._migration_span(reason), ...)``) are skipped — pairing them is
  the trace validator's runtime job. Checked package-wide in
  ``finalize``.
* **HDS-C002** — the "no silent clamps" rule: ``validate_*``
  functions must reject with a typed :class:`HDSConfigError`, not a
  bare builtin. Data-format validators that *document* their raise
  type in the docstring (e.g. ``validate_trace`` raising
  ``ValueError`` by contract) are exempt — the contract is explicit,
  which is the point.
* **HDS-C003** — an ``# hds: allow(...)`` pragma without a reason:
  suppressions document deliberate exceptions; a bare one is just a
  mute button and is rejected (the pragma is also ignored, so the
  underlying finding still fires).
* **HDS-C004** — a serving-path async span (literal name under the
  ``sched.`` / ``serve.`` / ``fleet.`` / ``fabric.`` prefixes)
  carrying neither a ``uid=`` nor a ``trace=`` attribute: without the
  request identity on the span, the multi-tracer assembler cannot
  link it into the per-request causal DAG, and the span is
  unattributable noise in the exported timeline (for ``fabric.*``
  spans the cross-process assembler additionally pairs worker rows by
  uid — an identity-less crossing can never render as an arrow).
  Computed names are skipped (the trace validator owns their runtime
  pairing, and the real emitters stamp identity on the live objects).
"""

import ast
import re
from typing import Dict, Iterable, List, Tuple

from .core import AnalysisContext, Finding, ModuleInfo, Rule

_TYPED_ERRORS = ("HDSConfigError",)

#: async-span name prefixes that identify serving-path request flow —
#: the spans the causal assembler must be able to key by request
_REQUEST_SPAN_RE = re.compile(r"^(sched|serve|fleet|fabric)\.")

#: keyword attributes that satisfy the request-identity requirement
_IDENTITY_ATTRS = ("uid", "trace")


class ConventionRule(Rule):
    family = "convention"
    codes = ("HDS-C001", "HDS-C002", "HDS-C003", "HDS-C004")

    def check_module(self, mod: ModuleInfo,
                     ctx: AnalysisContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        begins = ctx.shared.setdefault("span_begins", {})
        ends = ctx.shared.setdefault("span_ends", set())
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr in ("async_begin", "async_end") and node.args:
                    first = node.args[0]
                    if isinstance(first, ast.Constant) and \
                            isinstance(first.value, str):
                        if attr == "async_begin":
                            begins.setdefault(
                                first.value,
                                (mod.relpath, node.lineno))
                        else:
                            ends.add(first.value)
                        if _REQUEST_SPAN_RE.match(first.value) and \
                                not any(kw.arg in _IDENTITY_ATTRS
                                        for kw in node.keywords):
                            findings.append(Finding(
                                code="HDS-C004", family=self.family,
                                path=mod.relpath, line=node.lineno,
                                qualname="<module>",
                                symbol=first.value,
                                message=(
                                    f"serving async span "
                                    f"{first.value!r} carries no "
                                    f"uid=/trace= attribute — the "
                                    f"causal assembler cannot link "
                                    f"it into a per-request DAG")))
            if isinstance(node, ast.FunctionDef) and \
                    node.name.startswith("validate_"):
                findings.extend(self._check_validator(node, mod))
        for line, codes in mod.bad_pragmas:
            findings.append(Finding(
                code="HDS-C003", family=self.family,
                path=mod.relpath, line=line, qualname="<module>",
                symbol=codes,
                message=(f"allow pragma for {codes} has no reason — "
                         f"suppressions must document why the site "
                         f"is sanctioned")))
        return findings

    # ------------------------------------------------------------- #
    def _check_validator(self, fn: ast.FunctionDef,
                         mod: ModuleInfo) -> List[Finding]:
        doc = ast.get_docstring(fn) or ""
        out: List[Finding] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            name = None
            if isinstance(exc, ast.Call):
                f = exc.func
                name = f.id if isinstance(f, ast.Name) else (
                    f.attr if isinstance(f, ast.Attribute) else None)
            elif isinstance(exc, ast.Name):
                name = exc.id
            if name is None or name in _TYPED_ERRORS:
                continue
            if name in doc:
                # documented raise contract (a data-format validator,
                # not a config validator) — the exemption that keeps
                # validate_trace's declared ValueError legal
                continue
            out.append(Finding(
                code="HDS-C002", family=self.family,
                path=mod.relpath, line=node.lineno,
                qualname=fn.name, symbol=name,
                message=(f"config validator raises {name} — raise "
                         f"typed HDSConfigError (or document the "
                         f"raise type in the docstring for data-"
                         f"format validators)")))
        return out

    # ------------------------------------------------------------- #
    def finalize(self, ctx: AnalysisContext) -> Iterable[Finding]:
        begins: Dict[str, Tuple[str, int]] = ctx.shared.get(
            "span_begins", {})
        ends = ctx.shared.get("span_ends", set())
        out: List[Finding] = []
        for name, (relpath, line) in sorted(begins.items()):
            if name not in ends:
                out.append(Finding(
                    code="HDS-C001", family=self.family,
                    path=relpath, line=line, qualname="<module>",
                    symbol=name,
                    message=(f"async span {name!r} is begun but "
                             f"never ended by any literal "
                             f"async_end in the tree")))
        return out
