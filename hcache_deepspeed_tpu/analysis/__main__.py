"""``python -m hcache_deepspeed_tpu.analysis`` — the analyzer CLI.

Default run: walk the package (plus ``bench.py`` when run inside the
repo), apply every rule family, fold in ``perf lint``, and gate
against the committed baseline.

Exit codes: 0 clean; 1 new (non-baselined) findings; 2 stale baseline
entries (a baselined finding no longer fires — remove it or
regenerate); 3 bad invocation.
"""

import argparse
import json
import os
import sys

from .core import (AnalysisConfig, baseline_path, gate,
                   load_baseline, run_analysis, save_baseline)


def _default_config(root, families):
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    repo = os.path.dirname(os.path.abspath(root))
    bench = os.path.join(repo, "bench.py")
    extra = (bench,) if os.path.exists(bench) else ()
    return AnalysisConfig(
        root=root, extra_files=extra,
        perf_lint=bool(extra), repo_root=repo if extra else None,
        families=tuple(families) if families else None)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "python -m hcache_deepspeed_tpu.analysis",
        description="concurrency & determinism analyzer "
                    "(lock discipline / purity / conventions / perf)")
    p.add_argument("--root", default=None,
                   help="package dir to scan (default: the installed "
                        "hcache_deepspeed_tpu package)")
    p.add_argument("--families", default=None,
                   help="comma list: locks,purity,convention,perf")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: {baseline_path()})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report raw findings, ignore the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept current findings into the baseline "
                        "(existing reasons are preserved)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--verbose", action="store_true",
                   help="also list sanctioned (pragma'd) sites")
    args = p.parse_args(argv)

    families = None
    if args.families:
        families = [f.strip() for f in args.families.split(",")
                    if f.strip()]
        known = {"locks", "purity", "convention", "perf"}
        bad = set(families) - known
        if bad:
            print(f"unknown families: {sorted(bad)} "
                  f"(known: {sorted(known)})")
            return 3
    config = _default_config(args.root, families)
    report = run_analysis(config)

    if args.write_baseline:
        old = load_baseline(args.baseline)
        entries = {}
        for f in report.findings:
            entries[f.fingerprint] = old.get(
                f.fingerprint,
                f"baselined pre-existing finding: {f.message}")
        path = save_baseline(entries, args.baseline)
        print(f"wrote {len(entries)} entries -> {path}")
        return 0

    baseline = {} if args.no_baseline \
        else load_baseline(args.baseline)
    new, stale = gate(report, baseline)

    if args.as_json:
        print(json.dumps({
            "modules": report.n_modules,
            "findings": [f.render() for f in report.findings],
            "new": [f.render() for f in new],
            "stale_baseline": stale,
            "sanctioned": [f.render() for f, _ in report.sanctioned],
            "by_family": report.by_family,
        }, indent=1))
    else:
        for f in new:
            print(f.render())
        for fp in stale:
            print(f"STALE BASELINE: {fp} no longer fires — remove "
                  f"the entry (or --write-baseline)")
        if args.verbose and report.sanctioned:
            print(f"-- {len(report.sanctioned)} sanctioned site(s):")
            for f, _ in report.sanctioned:
                print(f"   {f.render()}")
        fam = ", ".join(f"{k}={v}" for k, v in
                        sorted(report.by_family.items())) or "none"
        print(f"analysis: {report.n_modules} modules, "
              f"{len(report.findings)} finding(s) [{fam}], "
              f"{len(new)} new, {len(stale)} stale baseline, "
              f"{len(report.sanctioned)} sanctioned")
    if new:
        return 1
    if stale:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
