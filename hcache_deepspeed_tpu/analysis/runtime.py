"""Dynamic lock-order sentinel.

The static L003 rule only sees *lexically* nested ``with`` blocks; the
real hazard in this codebase is inter-procedural — the fleet pump holds
``ServingFleet._lock`` while ``_drain_pass`` takes a replica's
``ServingServer._lock`` three calls down. This module instruments the
locks themselves:

* :func:`make_lock` is the factory the serving stack uses instead of
  ``threading.Lock()``. With the sentinel disabled (the default, and
  production) it returns a plain ``threading.Lock`` — zero overhead,
  the same contract as the tracer and the fault injector. With the
  sentinel enabled (the fleet/server/chaos test suites turn it on via
  an autouse fixture) it returns an :class:`OrderedLock`.
* Each :class:`OrderedLock` acquisition records, per thread, the stack
  of held lock *names* and adds an edge ``held -> acquiring`` to a
  process-wide lock-order graph. A new edge that closes a cycle means
  two code paths acquire the same locks in opposite orders — a future
  deadlock — and raises :class:`LockOrderError` **deterministically at
  the acquisition that closed the cycle**, turning a would-be hung CI
  into a red test with both acquisition stacks in the message.

Names are class-granular (``"ServingFleet._lock"``), so N replica
server locks share one node: the graph checks the *discipline*
("fleet before server"), which is also what a module declares
statically via ``__hds_lock_order__``.
"""

import threading
import traceback
from typing import Dict, List, Optional, Tuple


class LockOrderError(RuntimeError):
    """Two code paths acquire locks in opposite orders (graph cycle).

    Raised at the acquisition that closed the cycle, with the stack
    that created each conflicting edge — deterministic, unlike the
    deadlock it predicts."""


class _SentinelState:
    def __init__(self):
        self.enabled = False
        self._graph_lock = threading.Lock()
        #: edge (held, acquiring) -> abbreviated stack that added it
        self.edges: Dict[Tuple[str, str], str] = {}
        self._held = threading.local()

    # -- per-thread held stack ------------------------------------ #
    def held(self) -> List[str]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    # -- graph ----------------------------------------------------- #
    def note_acquire(self, name: str) -> None:
        if not self.enabled:
            # an OrderedLock outliving its sentinel scope (e.g. a
            # fleet kept across tests) goes inert, it never raises
            return
        stack = self.held()
        if stack:
            self._add_edge(stack[-1], name)
        stack.append(name)

    def note_release(self, name: str) -> None:
        stack = self.held()
        # release order may differ from acquire order (with-blocks
        # guarantee LIFO, but bare acquire/release pairs may not)
        if name in stack:
            stack.reverse()
            stack.remove(name)
            stack.reverse()

    def _add_edge(self, held: str, acquiring: str) -> None:
        if held == acquiring:
            raise LockOrderError(
                f"re-acquiring {acquiring!r} while already holding "
                f"it (non-reentrant lock deadlock)\n"
                + "".join(traceback.format_stack(limit=8)))
        key = (held, acquiring)
        with self._graph_lock:
            if key in self.edges:
                return
            cycle = self._path(acquiring, held)
            if cycle is not None:
                prior = " ; ".join(
                    f"{a}->{b}: {self.edges[(a, b)]}"
                    for a, b in zip(cycle, cycle[1:]))
                raise LockOrderError(
                    f"lock-order cycle: acquiring {acquiring!r} "
                    f"while holding {held!r}, but the reverse order "
                    f"{' -> '.join(cycle)} was already observed.\n"
                    f"prior edge(s): {prior}\n"
                    f"this acquisition:\n"
                    + "".join(traceback.format_stack(limit=8)))
            self.edges[key] = "".join(
                traceback.format_stack(limit=4)[:-1])[-400:]

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS for a path src -> dst through recorded edges."""
        stack = [(src, [src])]
        seen = {src}
        adj: Dict[str, List[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in sorted(adj.get(node, ())):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def reset(self) -> None:
        with self._graph_lock:
            self.edges.clear()
        self._held = threading.local()


_STATE = _SentinelState()


class OrderedLock:
    """``threading.Lock`` wrapper that feeds the lock-order graph.

    Drop-in for the ``with``-statement and acquire/release/locked
    surface the serving stack uses. The order check happens BEFORE
    blocking on the underlying lock, so a violation raises instead of
    deadlocking."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        _STATE.note_acquire(self.name)
        ok = self._lock.acquire(blocking, timeout)
        if not ok:
            _STATE.note_release(self.name)
        return ok

    def release(self) -> None:
        self._lock.release()
        _STATE.note_release(self.name)

    def locked(self) -> bool:
        return self._lock.locked()

    def held_by_current_thread(self) -> bool:
        return self.name in _STATE.held()

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"OrderedLock({self.name!r})"


def make_lock(name: str):
    """The lock factory the serving stack calls in ``__init__``:
    plain ``threading.Lock`` unless the sentinel is enabled."""
    if _STATE.enabled:
        return OrderedLock(name)
    return threading.Lock()


def sentinel_enabled() -> bool:
    return _STATE.enabled


def enable_sentinel() -> _SentinelState:
    """Turn the sentinel on (fresh graph). Locks created by
    :func:`make_lock` from now on are instrumented; existing plain
    locks are unaffected."""
    _STATE.reset()
    _STATE.enabled = True
    return _STATE


def disable_sentinel() -> None:
    _STATE.enabled = False
    _STATE.reset()


class sentinel:
    """``with sentinel() as state:`` — scoped enable, always disables,
    exposes the observed edge set for assertions."""

    def __enter__(self) -> _SentinelState:
        return enable_sentinel()

    def __exit__(self, *exc) -> bool:
        disable_sentinel()
        return False


def observed_edges() -> Dict[Tuple[str, str], str]:
    """Copy of the current lock-order graph (test assertion surface)."""
    with _STATE._graph_lock:
        return dict(_STATE.edges)
