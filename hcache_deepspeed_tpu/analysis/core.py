"""Static-analysis core: walker, rule registry, findings, baseline.

The repo's headline results are gated on two invariants nothing was
machine-checking until now: **byte-identical same-seed replay** (the
CHAOS_SERVE / FLEET_SERVE / DISAGG_SERVE digests) and **coherent
thread-shared state** across the server loop, fleet pump, metrics HTTP
thread and restore lanes. This package checks them the same way
``perf lint`` checks artifact provenance: an AST walk over the tree,
a registry of rule families with per-finding codes, and a committed
baseline so pre-existing findings don't block the tier-1 gate while
*new* ones do.

Vocabulary:

* **Finding** — one violation, identified by a stable fingerprint
  ``code:path:qualname:symbol`` (deliberately line-free, so moving
  code doesn't stale the baseline; a genuinely new access site of the
  same symbol in the same scope is the same discipline bug).
* **Sanctioned site** — a finding suppressed in-source by an allow
  pragma ``# hds: allow(CODE) <reason>``. The reason is mandatory
  (an allow without one is itself a finding, HDS-C003): the pragma
  *documents* a deliberate exception, it does not hide it. Sanctioned
  sites are reported separately, never silently dropped.
* **Baseline** — ``analysis/BASELINE.json``, fingerprint -> reason.
  The gate fails on any finding not in the baseline AND on any
  baseline entry that no longer fires (stale entries rot into cover
  for future regressions, so they are errors too).
* **Sim-deterministic module** — a module whose behavior must be a
  pure function of its inputs (trace, seed, virtual clock) because
  committed digests replay it byte-for-byte. Declared either by the
  config's path patterns (:data:`SIM_DETERMINISTIC`) or in-file via
  ``__hds_sim_deterministic__ = True``.
"""

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

#: path patterns (relpath prefixes, '/'-separated) declared
#: sim-deterministic: the committed chaos/fleet/disagg digests replay
#: these byte-for-byte, so ambient wall-clock, unseeded RNG and
#: hash-order iteration are forbidden here. ``perf/`` is included
#: because ``build_index`` documents "deterministic for a fixed
#: (tree, now)" — its one wall-clock default is a sanctioned site.
SIM_DETERMINISTIC = (
    "hcache_deepspeed_tpu/serving/",
    "hcache_deepspeed_tpu/resilience/",
    "hcache_deepspeed_tpu/fabric/",
    "hcache_deepspeed_tpu/comm/ring.py",
    "hcache_deepspeed_tpu/comm/hierarchical.py",
    "hcache_deepspeed_tpu/runtime/zero/qwire.py",
    "hcache_deepspeed_tpu/perf/",
    "hcache_deepspeed_tpu/utils/io_bench.py",
)

_ALLOW_RE = re.compile(
    r"#\s*hds:\s*allow\(\s*([A-Z0-9\-,\s]+?)\s*\)\s*(.*)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    code: str          # e.g. "HDS-L001"
    family: str        # "locks" | "purity" | "convention" | "perf"
    path: str          # repo-relative, '/'-separated
    line: int
    qualname: str      # "Class.method", "function", or "<module>"
    symbol: str        # the offending attribute / callable / name
    message: str

    @property
    def fingerprint(self) -> str:
        return f"{self.code}:{self.path}:{self.qualname}:{self.symbol}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.code} "
                f"[{self.qualname}] {self.message}")


@dataclass
class ModuleInfo:
    """One parsed source module plus the metadata rules consult."""

    path: str                   # absolute
    relpath: str                # analysis-root-relative, '/'-separated
    tree: ast.Module
    lines: List[str]
    #: line -> set of allowed codes (pragma on that line; a pragma on
    #: a ``def`` line covers the whole function body)
    allows: Dict[int, Set[str]] = field(default_factory=dict)
    #: (line, codes) of pragmas missing a reason — themselves findings
    bad_pragmas: List[Tuple[int, str]] = field(default_factory=list)
    sim_deterministic: bool = False
    #: module declares its lock acquisition order (L003 consults this)
    lock_order: Optional[Tuple[str, ...]] = None

    def allowed(self, code: str, line: int) -> bool:
        """A finding at ``line`` is sanctioned when its line — or the
        comment line directly above it — carries an allow pragma for
        its code (def-line pragmas were already range-expanded)."""
        for ln in (line, line - 1):
            if code in self.allows.get(ln, ()):
                return True
        return False


@dataclass
class AnalysisConfig:
    """What to scan and under which declarations."""

    #: directory whose ``**/*.py`` is analyzed
    root: str = ""
    #: extra single files (repo mode adds ``bench.py``)
    extra_files: Tuple[str, ...] = ()
    #: relpath prefixes declared sim-deterministic (in-file
    #: ``__hds_sim_deterministic__ = True`` also works)
    sim_deterministic: Tuple[str, ...] = SIM_DETERMINISTIC
    #: run the perf-registry source lint (needs a repo root carrying
    #: bench.py; fixture runs leave it off)
    perf_lint: bool = False
    #: repo root for perf_lint (defaults to parent of ``root``)
    repo_root: Optional[str] = None
    #: rule families to run (None = all registered)
    families: Optional[Tuple[str, ...]] = None


class AnalysisContext:
    """Shared state across modules for cross-module rules (e.g. the
    async-span pairing ledger)."""

    def __init__(self, config: AnalysisConfig):
        self.config = config
        self.modules: List[ModuleInfo] = []
        self.shared: Dict[str, object] = {}


class Rule:
    """One rule family: per-module check + cross-module finalize."""

    family = "base"
    codes: Tuple[str, ...] = ()

    def check_module(self, mod: ModuleInfo,
                     ctx: AnalysisContext) -> Iterable[Finding]:
        return ()

    def finalize(self, ctx: AnalysisContext) -> Iterable[Finding]:
        return ()


# ----------------------------------------------------------------- #
# parsing
# ----------------------------------------------------------------- #
def _parse_pragmas(mod: ModuleInfo) -> None:
    for i, line in enumerate(mod.lines, start=1):
        m = _ALLOW_RE.search(line)
        if not m:
            continue
        codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
        reason = m.group(2).strip().lstrip("-—– ").strip()
        if not reason:
            mod.bad_pragmas.append((i, ",".join(sorted(codes))))
            continue
        mod.allows.setdefault(i, set()).update(codes)


def _expand_def_pragmas(mod: ModuleInfo) -> None:
    """A pragma on (or directly above) a ``def``/``class`` line covers
    the whole body — the method-level suppression used for e.g. the
    fleet's virtual-clock ``step()``, whose single-threaded-by-contract
    mutations would otherwise need a pragma per line."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            continue
        codes: Set[str] = set()
        for ln in (node.lineno, node.lineno - 1):
            codes |= mod.allows.get(ln, set())
        if not codes:
            continue
        for ln in range(node.lineno, (node.end_lineno or node.lineno)
                        + 1):
            mod.allows.setdefault(ln, set()).update(codes)


def _module_declarations(mod: ModuleInfo) -> None:
    for node in mod.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if not isinstance(tgt, ast.Name):
                continue
            if tgt.id == "__hds_sim_deterministic__":
                try:
                    mod.sim_deterministic = bool(
                        ast.literal_eval(node.value))
                except ValueError:
                    pass
            if tgt.id == "__hds_lock_order__":
                try:
                    mod.lock_order = tuple(
                        ast.literal_eval(node.value))
                except ValueError:
                    mod.lock_order = ()


def load_module(path: str, relpath: str,
                config: AnalysisConfig) -> ModuleInfo:
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    tree = ast.parse(source, filename=path)
    mod = ModuleInfo(path=path, relpath=relpath, tree=tree,
                     lines=source.splitlines())
    mod.sim_deterministic = any(
        relpath == pat or relpath.startswith(pat)
        for pat in config.sim_deterministic)
    _module_declarations(mod)
    _parse_pragmas(mod)
    _expand_def_pragmas(mod)
    return mod


def iter_source_files(config: AnalysisConfig):
    """(abspath, relpath) for every analyzed module, sorted for
    deterministic finding order."""
    out = []
    root = os.path.abspath(config.root)
    base = os.path.basename(root)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__")
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            out.append((path, f"{base}/{rel}"))
    for extra in config.extra_files:
        out.append((os.path.abspath(extra), os.path.basename(extra)))
    return out


# ----------------------------------------------------------------- #
# the run
# ----------------------------------------------------------------- #
@dataclass
class Report:
    findings: List[Finding]
    sanctioned: List[Tuple[Finding, int]]   # (finding, pragma line)
    n_modules: int = 0

    @property
    def by_family(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.family] = out.get(f.family, 0) + 1
        return out

    @property
    def codes(self) -> Set[str]:
        return {f.code for f in self.findings}


def registered_rules() -> List[Rule]:
    from .rules_convention import ConventionRule
    from .rules_locks import LockDisciplineRule
    from .rules_purity import PurityRule
    return [LockDisciplineRule(), PurityRule(), ConventionRule()]


def run_analysis(config: AnalysisConfig) -> Report:
    rules = registered_rules()
    if config.families is not None:
        rules = [r for r in rules if r.family in config.families]
    ctx = AnalysisContext(config)
    raw: List[Finding] = []
    for path, relpath in iter_source_files(config):
        mod = load_module(path, relpath, config)
        ctx.modules.append(mod)
        for rule in rules:
            raw.extend(rule.check_module(mod, ctx))
    for rule in rules:
        raw.extend(rule.finalize(ctx))
    if config.perf_lint and (config.families is None or
                             "perf" in config.families):
        raw.extend(_perf_lint_findings(config))
    # split sanctioned (pragma'd) from live findings
    by_rel = {m.relpath: m for m in ctx.modules}
    findings: List[Finding] = []
    sanctioned: List[Tuple[Finding, int]] = []
    for f in raw:
        mod = by_rel.get(f.path)
        if mod is not None and mod.allowed(f.code, f.line):
            sanctioned.append((f, f.line))
        else:
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.symbol))
    return Report(findings=findings, sanctioned=sanctioned,
                  n_modules=len(ctx.modules))


def _perf_lint_findings(config: AnalysisConfig) -> List[Finding]:
    """Fold ``perf lint`` (artifact literals without a registry
    schema) in as the fourth family so one CLI runs everything."""
    from ..perf.registry import lint_sources, repo_root
    root = config.repo_root
    if root is None:
        try:
            root = repo_root(config.root)
        except FileNotFoundError:
            return []
    out = []
    for violation in lint_sources(root=root):
        loc, _, msg = violation.partition(": ")
        path, _, line = loc.rpartition(":")
        out.append(Finding(
            code="HDS-PERF1", family="perf",
            path=path.replace(os.sep, "/"),
            line=int(line) if line.isdigit() else 0,
            qualname="<module>",
            symbol=msg.split("'")[1] if "'" in msg else "artifact",
            message=msg))
    return out


# ----------------------------------------------------------------- #
# baseline
# ----------------------------------------------------------------- #
def baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE.json")


def load_baseline(path: Optional[str] = None) -> Dict[str, str]:
    path = path or baseline_path()
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return {}
    return dict(data.get("entries", {}))


def save_baseline(entries: Dict[str, str],
                  path: Optional[str] = None) -> str:
    path = path or baseline_path()
    payload = {
        "version": 1,
        "note": ("fingerprint -> reason for pre-existing findings the "
                 "gate tolerates; stale entries (no longer firing) "
                 "FAIL the gate — regenerate with --write-baseline"),
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1)
        fh.write("\n")
    return path


def gate(report: Report, baseline: Dict[str, str]
         ) -> Tuple[List[Finding], List[str]]:
    """(new findings not in baseline, stale baseline fingerprints)."""
    fired = {f.fingerprint for f in report.findings}
    new = [f for f in report.findings
           if f.fingerprint not in baseline]
    stale = sorted(fp for fp in baseline if fp not in fired)
    return new, stale
