"""Serving benchmark: prefill + ragged-decode throughput.

Reference analog: the FastGen benchmark harness behind
``blogs/deepspeed-fastgen/README.md`` (throughput/latency curves for the
v2 ragged engine). Measures, for a model served by
:class:`InferenceEngineV2`:

* prefill tokens/sec at a given prompt length,
* steady-state decode tokens/sec at several concurrent-batch sizes,
* decode latency as a function of *actual* context length (the paged
  kernel's work should scale with tokens in cache, not max_context).

CLI: ``bin/hds_serve_bench`` (JSON lines, one per measurement).
"""

import argparse
import functools
import json
import os
import time

import numpy as np

from .scheduling import SchedulingError, SchedulingResult

_PARAM_CACHE = {}


def _emit(results, row):
    # append + stream one result row (partial results survive a crash)
    results.append(row)
    print(json.dumps(row), flush=True)


_MODEL_SIZES = {
    "tiny": dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                 n_layer=2, n_head=4, n_kv_head=2),
    "1b": dict(vocab_size=32000, hidden_size=2048,
               intermediate_size=5504, n_layer=24, n_head=16,
               n_kv_head=16),
    "7b": dict(vocab_size=32000, hidden_size=4096,
               intermediate_size=11008, n_layer=32, n_head=32,
               n_kv_head=32),
}


def _model_config(model_size: str, max_context: int):
    """Config alone (shape math, no weights — the decode diag's
    floors-only mode must not pay a 7B host init for four tuples)."""
    from ..models.llama import LlamaConfig
    return LlamaConfig(max_positions=max_context, dtype="bfloat16",
                       use_flash=False, **_MODEL_SIZES[model_size])


def _model_params(model_size: str, max_context: int):
    """Config + params for one model size, built ONCE per process and on
    the HOST backend — re-initializing 4 GB of fp32 weights on the chip
    for every engine variant both wastes time and OOMs the pool (each
    new engine's init spike lands while the previous engine's weights
    are still resident)."""
    import jax
    import jax.numpy as jnp

    from ..models.llama import LlamaForCausalLM

    key = (model_size, max_context)
    if key not in _PARAM_CACHE:
        cfg = _model_config(model_size, max_context)
        model = LlamaForCausalLM(cfg)
        batch_init = {"input_ids": np.zeros((1, 8), np.int32)}
        try:
            host = jax.devices("cpu")[0]
        except RuntimeError:
            host = None
        import contextlib
        import os
        ctx = jax.default_device(host) if host is not None \
            else contextlib.nullcontext()
        prev = os.environ.get("HDS_DISABLE_PALLAS")
        os.environ["HDS_DISABLE_PALLAS"] = "1"   # tracing on the host
        try:
            with ctx:
                # cast to the serving dtype ON HOST: the engine casts
                # anyway, and shipping fp32 doubles the host->device
                # bytes (minutes of wall clock for 7B on a slow link)
                params = jax.tree.map(
                    lambda p: np.asarray(
                        p.astype(cfg.compute_dtype)
                        if jnp.issubdtype(p.dtype, jnp.floating) else p),
                    model.init(jax.random.PRNGKey(0), batch_init,
                               train=False)["params"])
        finally:
            if prev is None:
                os.environ.pop("HDS_DISABLE_PALLAS", None)
            else:
                os.environ["HDS_DISABLE_PALLAS"] = prev
        _PARAM_CACHE[key] = (cfg, params)
    return _PARAM_CACHE[key]


def _engine(model_size: str, max_context: int, batch: int,
            quantize: str = "", prefill_chunk: int = 0,
            latents: bool = False, latent_dtype: str = "",
            prefix_caching: bool = False):
    from .config import RaggedInferenceEngineConfig
    from .engine_v2 import InferenceEngineV2

    cfg, params = _model_params(model_size, max_context)
    blocks_needed = batch * (-(-max_context // 64)) + 2
    quant = {}
    if quantize:
        # group 128 = one TPU lane row: sub-lane groups (e.g. 64) pad
        # the stored int8 q and every quantization temp 2x. For the
        # k-major fused layout a LARGER group halves scale rows and
        # kernel grid steps — overridable for measurement sweeps.
        group = int(os.environ.get("HDS_QUANT_GROUP", "128"))
        quant = {"enabled": True, "bits": 8, "group_size": group,
                 "min_size": 1024,
                 "use_fused_kernel": quantize == "fused"}
    eng = InferenceEngineV2(
        cfg, params,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": max(batch, 8),
                           "max_ragged_batch_size": 8192,
                           "max_ragged_sequence_count": max(batch, 8),
                           "max_context": max_context,
                           "prefill_chunk": prefill_chunk,
                           "prefix_caching": prefix_caching},
            kv_cache={"block_size": 64, "num_blocks": blocks_needed,
                      "cache_dtype": "bfloat16"},
            quantization=quant,
            hcache={"enable_latents": latents,
                    "latent_dtype": latent_dtype}))
    return cfg, eng


def run_restore(model_size="tiny", max_context=512, prompt_len=128,
                batches=(1, 4), quantize="", prefill_chunk=0,
                latent_dtype=""):
    """HCache headline: time-to-cache-ready for a returning sequence —
    ``restore_kv`` (QKV-only replay from saved latents) vs a full prefill
    recompute. This is the fork's distinctive capability
    (reference: ``engine_v2.py:108`` restore_kv vs re-``put``); the
    restore path runs one GEMM triple per layer instead of the whole
    transformer stack, so the speedup should approach
    total-FLOPs / QKV-FLOPs as the model grows.

    The latents are harvested once from a latents-enabled twin engine;
    the timed engine runs with latent capture OFF so the prefill baseline
    is a plain recompute (no latent materialization + D2H in the timed
    loop — that cost belongs to the *first* pass, not the re-prefill
    being compared against)."""
    results = []
    emit = functools.partial(_emit, results)

    rng = np.random.default_rng(0)
    for batch in batches:
        # harvest latents (same seed ⇒ identical weights as the timed
        # engine), then drop this engine
        cfg, eng_lat = _engine(model_size, max_context, batch,
                               latents=True, quantize=quantize,
                               prefill_chunk=prefill_chunk,
                               latent_dtype=latent_dtype)
        prompts = [list(rng.integers(0, cfg.vocab_size, (prompt_len,)))
                   for _ in range(batch)]
        uids = list(range(batch))
        _, latents = eng_lat.put(uids, prompts)
        del eng_lat

        cfg, eng = _engine(model_size, max_context, batch, latents=False,
                           quantize=quantize, prefill_chunk=prefill_chunk,
                           latent_dtype=latent_dtype)

        def sync():
            # through the axon tunnel block_until_ready may not drain the
            # queue — fetch a scalar from the cache instead
            np.asarray(eng.cache.k[0, 0, 0, 0])

        def clear():
            for u in uids:
                if eng.state.get_sequence(u) is not None:
                    eng.flush(u)

        # warm both programs (compile)
        eng.put(uids, prompts)
        clear()
        eng.restore_kv(uids, prompts, latents)
        sync()
        clear()

        reps = 5
        t0 = time.perf_counter()
        for _ in range(reps):
            eng.put(uids, prompts)
            sync()
            clear()
        prefill_ms = (time.perf_counter() - t0) / reps * 1000

        # the timed restore window runs under the span tracer so the
        # JSONL row carries the per-chunk staging breakdown (where the
        # restore time goes: chunks, shipped bytes, host staging ms)
        from ..telemetry import bench_extra
        from ..telemetry.tracer import get_tracer
        tracer = get_tracer()
        tracer_was = tracer.enabled
        tracer.configure(enabled=True)
        tracer.clear()
        t0 = time.perf_counter()
        for _ in range(reps):
            eng.restore_kv(uids, prompts, latents)
            sync()
            clear()
        restore_ms = (time.perf_counter() - t0) / reps * 1000
        tracer.configure(enabled=tracer_was)
        breakdown = bench_extra(tracer.events())

        emit({
            "phase": "hcache-restore", "batch": batch,
            "prompt_len": prompt_len,
            "latent_dtype": latent_dtype,
            "latent_mb": round(sum(l.nbytes for l in latents) / 2**20, 1),
            "prefill_recompute_ms": round(prefill_ms, 2),
            "restore_kv_ms": round(restore_ms, 2),
            "speedup": round(prefill_ms / restore_ms, 2),
            "extra": {"step_breakdown": breakdown}})
        del eng
    return results


def run_restore_marginal(model_size="tiny", max_context=512,
                         prompt_len=128, batches=(1, 4), quantize="",
                         latent_dtype="", chain=8):
    """Marginal-cost decomposition of the HCache restore story.

    Through a high-latency host link (the axon relay: ~0.5 s per host
    round trip, ~50 MB/s H2D) the end-to-end numbers ``run_restore``
    reports are link-bound, not device-bound — both sides of the
    comparison measure the tunnel. This splits the three components by
    chaining ``chain`` dispatches with ONE final sync and fitting the
    slope (the same fixed-vs-marginal method as ``hds_decode_diag``):

      * ``prefill_ms``  — marginal device cost of a full-stack prefill
        (``put(defer_fetch=True)``: no per-call logits D2H);
      * ``replay_ms``   — marginal device cost of the QKV-only restore
        replay from HBM-staged latents (``model.restore_kv`` on a
        ``jax.Array`` slab: no ship);
      * ``link_gbps`` / ``ship_ms`` — measured H2D bandwidth and the
        latent-slab ship at that bandwidth (double-buffered behind
        compute in the real path).

    ``speedup_replay = prefill_ms / replay_ms`` is the hardware story:
    what a co-located host (multi-GB/s DMA, where ship hides entirely
    under replay) gets back per returning sequence."""
    import jax

    results = []
    emit = functools.partial(_emit, results)
    rng = np.random.default_rng(0)
    for batch in batches:
        cfg, eng_lat = _engine(model_size, max_context, batch,
                               latents=True, quantize=quantize,
                               latent_dtype=latent_dtype)
        prompts = [list(rng.integers(0, cfg.vocab_size, (prompt_len,)))
                   for _ in range(batch)]
        uids = list(range(batch))
        _, latents = eng_lat.put(uids, prompts)
        del eng_lat

        cfg, eng = _engine(model_size, max_context, batch, latents=False,
                           quantize=quantize, latent_dtype=latent_dtype)

        def sync():
            np.asarray(eng.cache.k[0, 0, 0, 0])

        def clear():
            for u in uids:
                if eng.state.get_sequence(u) is not None:
                    eng.flush(u)

        # --- the engine's own group staging (shared helper): creates
        # the sequences/blocks and the padded lane slab, so the staged
        # replay times the same compiled program restore_kv runs
        items = [(uid, np.asarray(p, np.int32), np.asarray(latents[j]))
                 for j, (uid, p) in enumerate(zip(uids, prompts))]
        lat, start, t_len, tables, seqs = eng._stage_restore_group(items)

        # --- measured H2D link bandwidth (the slab itself)
        jax.device_put(lat[:1]).block_until_ready()   # warm transfer path
        t0 = time.perf_counter()
        slab_dev = jax.device_put(lat)
        slab_dev.block_until_ready()
        ship_s = time.perf_counter() - t0
        link_gbps = lat.nbytes / max(ship_s, 1e-9) / 1e9

        # --- staged replay: warm (compile), then slope over `chain`
        eng.model.restore_kv(eng.cache, slab_dev, start, tables, t_len)
        sync()
        for seq in seqs:   # the staged group is now cache-resident
            seq.post_forward()

        def timed(fn, k):
            t0 = time.perf_counter()
            for _ in range(k):
                fn()
            sync()
            return time.perf_counter() - t0

        def replay_once():
            eng.model.restore_kv(eng.cache, slab_dev, start, tables,
                                 t_len)

        t1 = timed(replay_once, 1)
        tk = timed(replay_once, 1 + chain)
        replay_ms = max(tk - t1, 1e-9) / chain * 1000

        # --- full-stack prefill, deferred fetch (device cost only)
        clear()
        eng.put(uids, prompts, defer_fetch=True)   # warm the plain path
        sync()

        def prefill_once():
            clear()
            eng.put(uids, prompts, defer_fetch=True)

        t1 = timed(prefill_once, 1)
        tk = timed(prefill_once, 1 + chain)
        prefill_ms = max(tk - t1, 1e-9) / chain * 1000

        # --- end-to-end restore through this link (ship included)
        clear()

        def restore_once():
            clear()
            eng.restore_kv(uids, prompts, latents)

        restore_once()   # warm lane/group compile for this path
        t1 = timed(restore_once, 1)
        tk = timed(restore_once, 1 + chain)
        restore_e2e_ms = max(tk - t1, 1e-9) / chain * 1000

        def ratio(num, den):
            # slopes under the timer floor (CPU noise) make the ratio
            # meaningless — emit null rather than a absurd number
            return round(num / den, 2) if den > 1e-2 else None

        emit({
            "phase": "hcache-restore-marginal", "batch": batch,
            "prompt_len": prompt_len, "latent_dtype": latent_dtype,
            "latent_mb": round(lat.nbytes / 2**20, 2),
            "chain": chain,
            "link_gbps": round(link_gbps, 3),
            "ship_ms": round(ship_s * 1000, 2),
            "prefill_ms": round(prefill_ms, 2),
            "replay_ms": round(replay_ms, 2),
            "restore_e2e_ms": round(restore_e2e_ms, 2),
            "speedup_replay": ratio(prefill_ms, replay_ms),
            "speedup_e2e": ratio(prefill_ms, restore_e2e_ms)})
        clear()
        del eng
    return results


def run_restore_crossover(model_size="tiny", max_context=512,
                          prompt_lens=(32, 64, 128, 256), batch=1,
                          quantize="", latent_dtype="", chain=8,
                          out="RESTORE_CROSSOVER.jsonl"):
    """Crossover curve: marginal restore cost vs full prefill replay
    across prompt lengths, plus the analytic model's verdicts.

    For each prompt length the marginal device cost of a full-stack
    prefill and of the end-to-end restore (ship + QKV replay) are
    measured with the chained-dispatch slope method
    (:func:`run_restore_marginal`), the measured link bandwidth and
    prefill rate are fed into a :class:`~..serving.crossover.
    RestoreCrossoverModel` through its ``observe_*`` calibration hooks,
    and one JSONL row per length records both the measurement and the
    model's prediction — so the artifact shows where the measured
    curves cross AND whether the scheduler's analytic model would pick
    the cheaper side there. A summary row carries the calibrated rates
    and the first measured crossover length.

    Rows append to ``out`` (``out=""`` for stdout only)."""
    import jax

    from ..serving.crossover import CrossoverConfig, RestoreCrossoverModel

    results = []
    fh = open(out, "w") if out else None

    def emit(row):
        results.append(row)
        line = json.dumps(row)
        print(line, flush=True)
        if fh is not None:
            fh.write(line + "\n")
            fh.flush()

    rng = np.random.default_rng(0)
    cfg, eng_lat = _engine(model_size, max_context, batch, latents=True,
                           quantize=quantize, latent_dtype=latent_dtype)
    cfg, eng = _engine(model_size, max_context, batch, latents=False,
                       quantize=quantize, latent_dtype=latent_dtype)
    model = RestoreCrossoverModel(eng_lat.restore_profile(),
                                  CrossoverConfig(min_samples=1))

    def sync():
        np.asarray(eng.cache.k[0, 0, 0, 0])

    def clear(engine, uids):
        for u in uids:
            if engine.state.get_sequence(u) is not None:
                engine.flush(u)

    def timed(fn, k):
        t0 = time.perf_counter()
        for _ in range(k):
            fn()
        sync()
        return time.perf_counter() - t0

    curve = []
    for prompt_len in prompt_lens:
        if prompt_len >= max_context:
            continue
        prompts = [list(rng.integers(0, cfg.vocab_size, (prompt_len,)))
                   for _ in range(batch)]
        uids = list(range(batch))
        _, latents = eng_lat.put(uids, prompts)
        clear(eng_lat, uids)

        # marginal full-stack prefill (deferred fetch: device cost only)
        eng.put(uids, prompts, defer_fetch=True)   # warm
        sync()

        def prefill_once():
            clear(eng, uids)
            eng.put(uids, prompts, defer_fetch=True)

        t1 = timed(prefill_once, 1)
        tk = timed(prefill_once, 1 + chain)
        prefill_ms = max(tk - t1, 1e-9) / chain * 1000

        # measured link bandwidth for THIS length's latent slab
        clear(eng, uids)
        items = [(uid, np.asarray(p, np.int32), np.asarray(latents[j]))
                 for j, (uid, p) in enumerate(zip(uids, prompts))]
        lat, start, t_len, tables, seqs = eng._stage_restore_group(items)
        jax.device_put(lat[:1]).block_until_ready()
        t0 = time.perf_counter()
        jax.device_put(lat).block_until_ready()
        ship_s = time.perf_counter() - t0
        for seq in seqs:   # undo the staging state ops
            seq.post_forward()
        clear(eng, uids)

        # marginal end-to-end restore (ship + replay, double-buffered)
        def restore_once():
            clear(eng, uids)
            eng.restore_kv(uids, prompts, latents)

        restore_once()   # warm the restore chain at this bucket
        t1 = timed(restore_once, 1)
        tk = timed(restore_once, 1 + chain)
        restore_ms = max(tk - t1, 1e-9) / chain * 1000
        clear(eng, uids)

        tokens = batch * prompt_len
        model.observe_ship(lat.nbytes, ship_s)
        model.observe_prefill(tokens, prefill_ms / 1000)
        model.observe_replay(tokens, restore_ms / 1000)
        curve.append((prompt_len, prefill_ms, restore_ms))

        emit({
            "phase": "restore-crossover", "model": model_size,
            "batch": batch, "prompt_len": prompt_len,
            "latent_dtype": latent_dtype,
            "latent_mb": round(lat.nbytes / 2**20, 3),
            "link_gbps": round(lat.nbytes / max(ship_s, 1e-9) / 1e9, 3),
            "prefill_ms": round(prefill_ms, 3),
            "restore_ms": round(restore_ms, 3),
            "measured_winner": "restore" if restore_ms <= prefill_ms
            else "recompute",
            "model_choice": model.decide(prompt_len),
            "restore_pred_ms": round(
                model.restore_cost_s(prompt_len) * 1000, 3),
            "recompute_pred_ms": round(
                model.recompute_cost_s(prompt_len) * 1000, 3)})

    # first measured crossover: the shortest length where restore wins
    cross_at = next((pl for pl, pre, res in curve if res <= pre), None)
    emit({"phase": "restore-crossover-summary", "model": model_size,
          "batch": batch, "prompt_lens": [c[0] for c in curve],
          "crossover_prompt_len": cross_at,
          "calibration": model.summary()})
    if fh is not None:
        fh.close()
    return results


def run_sweep(model_size="tiny", max_context=512, prompt_len=128,
              max_new=32, rates=(1.0, 2.0, 4.0), n_requests=16,
              max_batch=8, seed=0, quantize="", prefill_chunk=0,
              prefix_caching=False):
    """Throughput-latency curve under open-loop Poisson arrivals — the
    FastGen headline benchmark shape (reference:
    ``blogs/deepspeed-fastgen/README.md`` throughput vs latency at a
    token-rate SLA). For each offered request rate: requests arrive on
    a Poisson clock, are admitted into the continuous ragged batch as
    KV blocks allow, and decode to ``max_new`` tokens; reports
    effective rps, time-to-first-token and end-to-end latency
    percentiles, and generated tokens/sec."""
    results = []
    emit = functools.partial(_emit, results)

    cfg, eng = _engine(model_size, max_context, max_batch,
                       quantize=quantize, prefill_chunk=prefill_chunk,
                       prefix_caching=prefix_caching)
    rng = np.random.default_rng(seed)
    # with prefix caching, model the system-prompt workload: every
    # request shares the same leading half of the prompt
    shared_prefix = list(rng.integers(0, cfg.vocab_size,
                                      (prompt_len // 2,))) \
        if prefix_caching else []
    if prompt_len + max_new - 1 > min(max_context, cfg.max_positions):
        raise ValueError(
            f"prompt_len {prompt_len} + max_new {max_new} exceeds "
            f"max_context {max_context}")

    def percentile(xs, q):
        return round(float(np.percentile(np.asarray(xs), q)), 3)

    # Warm EVERY program shape ONCE, off-clock (shapes depend on
    # prompt_len/max_batch, not the rate): prefill lane counts covering
    # each power-of-two bucket up to _bucket(max_batch) — admission can
    # batch that many prefills into one dispatch — and the ragged
    # decode dispatch at every decode bucket that can occur (bucket
    # minimum is 8). A compile landing inside a timed loop would
    # corrupt that rate's percentiles and flatter later rates.
    # under prefix caching the timed loop's prompts ATTACH the shared
    # prefix and prefill only the tail — warm with the same shape, and
    # keep one warm sequence alive so the registered chain survives the
    # warmup flushes into the timed phase (steady-state behavior)
    if prefix_caching:
        warm_prompt = shared_prefix + list(
            rng.integers(0, cfg.vocab_size,
                         (prompt_len - len(shared_prefix),)))
    else:
        warm_prompt = list(rng.integers(0, cfg.vocab_size,
                                        (prompt_len,)))
    warm_counts = []
    b = 1
    while b < max_batch:
        warm_counts.append(b)
        b *= 2
    warm_counts.append(max_batch)
    from .engine_v2 import _bucket
    keeper_uid = 10 ** 6
    if prefix_caching:
        eng.put([keeper_uid], [warm_prompt])   # owns the shared chain
    warmed_decode = set()
    for k in warm_counts:
        warm_uids = list(range(k))
        eng.put(warm_uids, [warm_prompt] * k)
        if _bucket(k) not in warmed_decode:
            # decode lane buckets: _bucket(k, minimum=8) — warm each
            # distinct bucket any in-flight count 1..max_batch can
            # produce (warm_counts covers every power of two, so the
            # bucket set is complete)
            eng.put(warm_uids, [[1]] * k)
            warmed_decode.add(_bucket(k))
        for u in warm_uids:
            eng.flush(u)

    for rps in rates:
        stats0 = dict(eng.prefix_stats) if prefix_caching else None
        prompts = [shared_prefix +
                   list(rng.integers(0, cfg.vocab_size,
                                     (prompt_len - len(shared_prefix),)))
                   for _ in range(n_requests)]
        arrive = np.cumsum(rng.exponential(1.0 / rps, n_requests))
        state = {}      # i -> dict(start, first=None, end=None, left, tok)
        pending = list(range(n_requests))
        active = []
        t0 = time.perf_counter()
        while pending or active:
            now = time.perf_counter() - t0
            # admit arrived requests that fit (block budget, batch cap)
            admit = []
            for i in list(pending):
                if arrive[i] > now or len(active) + len(admit) >= max_batch:
                    break
                cand = active + admit + [i]
                # budget the WHOLE stretch (prompt + decode tokens) at
                # admission, like generate(): a request admitted on
                # prefill-only arithmetic could run out of blocks or
                # context mid-decode and abort the sweep
                lens = [1] * len(active) + \
                    [len(prompts[j]) + max_new - 1 for j in admit + [i]]
                if eng.can_schedule([100 + j for j in cand], lens) != \
                        SchedulingResult.Success:
                    break
                admit.append(i)
            if not active and not admit:
                if arrive[pending[0]] <= now:
                    # first arrived request can never fit — surface the
                    # verdict for the SAME whole-stretch length the
                    # admission check used
                    raise SchedulingError(eng.can_schedule(
                        [100 + pending[0]],
                        [len(prompts[pending[0]]) + max_new - 1]))
                # idle until the next arrival
                time.sleep(max(0.0, arrive[pending[0]] -
                               (time.perf_counter() - t0)))
                continue
            for i in admit:
                pending.remove(i)
                state[i] = {"start": arrive[i], "first": None,
                            "end": None, "left": max_new, "tok": None}
            step = active + admit
            toks = [[state[i]["tok"]] if i in active else prompts[i]
                    for i in step]
            step_logits, _ = eng.put([100 + i for i in step], toks)
            now = time.perf_counter() - t0
            finished = []
            for j, i in enumerate(step):
                st = state[i]
                if st["first"] is None:
                    st["first"] = now - st["start"]   # TTFT
                st["tok"] = int(np.argmax(step_logits[j]))
                st["left"] -= 1
                if st["left"] <= 0:
                    st["end"] = now - st["start"]
                    finished.append(i)
            for i in finished:
                eng.flush(100 + i)
            active = [i for i in step if i not in finished]

        makespan = max(s["end"] + s["start"] for s in state.values())
        row_extra = {}
        if prefix_caching:
            # per-rate delta, not engine-lifetime cumulative counters
            row_extra = {"prefix_stats": {
                k: eng.prefix_stats[k] - stats0[k]
                for k in eng.prefix_stats}}
        emit({"phase": "sweep", "decode_path": "host-driven",
              "offered_rps": rps, **row_extra,
              "effective_rps": round(n_requests / makespan, 3),
              "ttft_s": {"p50": percentile(
                  [s["first"] for s in state.values()], 50),
                  "p90": percentile(
                      [s["first"] for s in state.values()], 90)},
              "e2e_s": {"p50": percentile(
                  [s["end"] for s in state.values()], 50),
                  "p90": percentile(
                      [s["end"] for s in state.values()], 90)},
              "gen_tokens_per_sec": round(
                  n_requests * max_new / makespan, 1)})
    return results


def run_sweep_fused(model_size="tiny", max_context=512, prompt_len=128,
                    max_new=32, rates=(1.0, 2.0, 4.0), n_requests=16,
                    max_batch=8, seed=0, quantize="", prefill_chunk=0):
    """Throughput-latency curve on the on-device ``generate_fused``
    loop, batch-synchronous: arrived requests form a wave (up to
    max_batch), the whole wave decodes on device in ONE program, and
    arrivals during a wave queue for the next one.

    Honesty notes vs :func:`run_sweep` (rows carry ``decode_path`` so
    artifacts can't be conflated): no mid-stretch admission — this is a
    different scheduling discipline than continuous batching, traded
    for one host sync per wave instead of per token. Through a
    high-RTT tunnel this is the path whose absolute numbers mean
    anything; TTFT is not separable on-device, so rows report
    end-to-end latency (queue wait + wave) only."""
    results = []
    emit = functools.partial(_emit, results)
    cfg, eng = _engine(model_size, max_context, max_batch,
                       quantize=quantize, prefill_chunk=prefill_chunk)
    rng = np.random.default_rng(seed)
    if prompt_len + max_new - 1 > min(max_context, cfg.max_positions):
        raise ValueError(
            f"prompt_len {prompt_len} + max_new {max_new} exceeds "
            f"max_context {max_context}")

    def percentile(xs, q):
        return round(float(np.percentile(np.asarray(xs), q)), 3)

    # warm every decode-lane bucket a wave can produce (n_steps and the
    # lane bucket are the static args; a compile inside the timed loop
    # would corrupt that rate's percentiles)
    warm_prompt = list(rng.integers(0, cfg.vocab_size, (prompt_len,)))
    k = 1
    warm_counts = []
    while k < max_batch:
        warm_counts.append(k)
        k *= 2
    warm_counts.append(max_batch)
    for k in warm_counts:
        eng.generate_fused([warm_prompt] * k, max_new_tokens=max_new)

    for rps in rates:
        prompts = [list(rng.integers(0, cfg.vocab_size, (prompt_len,)))
                   for _ in range(n_requests)]
        arrive = np.cumsum(rng.exponential(1.0 / rps, n_requests))
        pending = list(range(n_requests))
        e2e = {}
        waves = 0
        t0 = time.perf_counter()
        while pending:
            now = time.perf_counter() - t0
            ready = [i for i in pending if arrive[i] <= now]
            if not ready:
                time.sleep(max(0.0, arrive[pending[0]] -
                               (time.perf_counter() - t0)))
                continue
            wave = ready[:max_batch]
            eng.generate_fused([prompts[i] for i in wave],
                               max_new_tokens=max_new)
            done_at = time.perf_counter() - t0
            for i in wave:
                e2e[i] = done_at - arrive[i]
                pending.remove(i)
            waves += 1
        makespan = max(e2e[i] + arrive[i] for i in e2e)
        emit({"phase": "sweep-fused", "decode_path": "fused",
              "offered_rps": rps, "waves": waves,
              "effective_rps": round(n_requests / makespan, 3),
              "e2e_s": {"p50": percentile(list(e2e.values()), 50),
                        "p90": percentile(list(e2e.values()), 90)},
              "gen_tokens_per_sec": round(
                  n_requests * max_new / makespan, 1)})
    return results


def run_serve_loop(model_size="tiny", max_context=128, prompt_len=48,
                   max_new=24, rps=50.0, n_requests=64, seed=0,
                   num_blocks=10, block_size=16, max_lanes=4,
                   virtual_clock=False, parity_checks=3,
                   out="SERVE_LOOP.jsonl"):
    """Continuous-batching serving loop over a Poisson arrival trace.

    Drives the ``serving/`` subsystem end-to-end against a real engine:
    requests arrive open-loop at ``rps``, the scheduler admits them into
    the ragged batch, and the deliberately small KV pool (``num_blocks``)
    plus mixed priority classes force preempt→suspend-to-latents→
    ``restore_kv`` cycles mid-trace — the restore dispatch overlapped
    with resident decode. After the trace, every preempted request's
    token stream is re-derived with an uninterrupted ``generate`` run on
    the (now empty) engine and compared exactly: restore correctness is
    part of the artifact, not a side claim.

    Emits one jsonl row per request plus a summary row with TTFT/TPOT/
    queue-wait percentiles, preemption/restore counters, the restore
    overlap ratio and the parity verdict; rows also append to ``out``
    (set ``out=""`` to skip the file).

    ``virtual_clock=True`` replays the same trace on the deterministic
    simulated timeline instead of wall time (policy debugging; the
    acceptance path runs with it off).
    """
    from ..serving import (Request, ServerConfig, ServingServer,
                           VirtualClock)
    from .config import RaggedInferenceEngineConfig
    from .engine_v2 import InferenceEngineV2

    results = []
    fh = open(out, "w") if out else None

    def emit(row):
        results.append(row)
        line = json.dumps(row)
        print(line, flush=True)
        if fh is not None:
            fh.write(line + "\n")
            fh.flush()

    if prompt_len + max_new > max_context:
        raise ValueError(f"prompt_len {prompt_len} + max_new {max_new} "
                         f"exceeds max_context {max_context}")
    cfg, params = _model_params(model_size, max_context)

    def build_engine():
        return InferenceEngineV2(
            cfg, params,
            config=RaggedInferenceEngineConfig(
                state_manager={"max_tracked_sequences": 2 * max_lanes,
                               "max_ragged_batch_size": 4096,
                               "max_ragged_sequence_count": max_lanes,
                               "max_context": max_context},
                kv_cache={"block_size": block_size,
                          "num_blocks": num_blocks,
                          "cache_dtype": "bfloat16"},
                hcache={"enable_latents": True}))

    eng = build_engine()
    rng = np.random.default_rng(seed)

    # warm every program the trace can hit, off-clock: each prefill
    # lane bucket the pool can hold concurrently, the ragged decode
    # bucket, and the restore chain at both token buckets a mid-trace
    # restore can land in (a compile inside the trace would corrupt
    # the percentiles)
    warm_prompt = list(rng.integers(0, cfg.vocab_size, (prompt_len,)))
    per_req = -(-prompt_len // block_size)
    fit = max(1, min(max_lanes, (num_blocks - 1) // per_req))
    for k in range(1, fit + 1):
        uids = list(range(k))
        eng.put(uids, [warm_prompt] * k)
        if k == 1:
            # decode lanes bucket to 8 regardless of count, so one
            # decode warms the dispatch for every in-flight size
            eng.put(uids, [[1]])
        for u in uids:
            eng.flush(u)
    for t in sorted({prompt_len,
                     min(prompt_len + max_new - 1, max_context - 1)}):
        toks = list(rng.integers(0, cfg.vocab_size, (t,)))
        _, lat = eng.put([0], [toks])
        eng.flush(0)
        eng.restore_kv([0], [toks], [lat[0]])
        eng.flush(0)

    arrive = np.cumsum(rng.exponential(1.0 / rps, n_requests))
    clock = VirtualClock() if virtual_clock else None
    server = ServingServer(
        eng, clock=clock,
        config=ServerConfig(max_queue_depth=n_requests + 1,
                            kv_demand_fraction=float("inf")))
    # arrival times are trace-relative; rebase onto the server's clock
    # (VirtualClock starts at 0, MonotonicClock wherever it is now)
    base = server.clock.now()
    reqs = []
    for i in range(n_requests):
        prompt = list(rng.integers(0, cfg.vocab_size, (prompt_len,)))
        # mixed priority classes: the high-priority minority arrives
        # into a loaded pool and evicts low-priority residents
        reqs.append(Request(uid=i, prompt=prompt, max_new_tokens=max_new,
                            arrival_time=base + float(arrive[i]),
                            priority=5 if i % 5 == 4 else 0))
    # the traced window covers the whole served trace: the summary row
    # then carries the span-derived breakdown (restore staging chunks,
    # bytes, the pair-computed overlap ratio) beside the counters it
    # must agree with
    from ..telemetry import bench_extra
    from ..telemetry.tracer import get_tracer
    tracer = get_tracer()
    tracer_was = tracer.enabled
    tracer.configure(enabled=True)
    tracer.clear()
    t0 = time.perf_counter()
    metrics = server.run_trace(reqs)
    wall_s = time.perf_counter() - t0
    tracer.configure(enabled=tracer_was)
    step_breakdown = bench_extra(tracer.events())

    dropped = [r for r in reqs if r.state.name != "DONE"]
    for r in reqs:
        emit({"phase": "serve-loop", "request": r.uid,
              "priority": r.priority, "state": r.state.name,
              "tokens": len(r.tokens_out),
              "ttft_s": None if r.ttft() is None
              else round(r.ttft(), 4),
              "tpot_s": None if r.tpot() is None
              else round(r.tpot(), 5),
              "queue_wait_s": None if r.queue_wait() is None
              else round(r.queue_wait(), 4),
              "preemptions": r.n_preemptions,
              "restores": r.n_restores})

    # restore correctness: preempted streams must equal uninterrupted
    # greedy decode of the same prompt (the engine is empty post-trace)
    preempted = sorted((r for r in reqs if r.n_preemptions > 0),
                       key=lambda r: r.uid)
    parity = {"checked": 0, "ok": 0}
    for r in preempted[:parity_checks]:
        ref = eng.generate([r.prompt], max_new_tokens=r.max_new_tokens)
        parity["checked"] += 1
        parity["ok"] += int(ref[0] == r.tokens_out)

    s = metrics.summary()
    emit({"phase": "serve-loop-summary", "model": model_size,
          "n_requests": n_requests, "rps": rps,
          "prompt_len": prompt_len, "max_new": max_new,
          "kv_blocks": num_blocks, "block_size": block_size,
          "virtual_clock": bool(virtual_clock),
          "dropped": len(dropped),
          "wall_s": round(wall_s, 3),
          "ttft_s": s["ttft_s"], "tpot_s": s["tpot_s"],
          "queue_wait_s": s["queue_wait_s"],
          "preemptions": s["counters"]["preemptions"],
          "restores": s["counters"]["restores"],
          "restore_overlap_ratio":
              s["gauges"]["restore_overlap_ratio"],
          "restore_stats": dict(eng.restore_stats),
          "parity": parity,
          "gen_tokens_per_sec": round(
              s["counters"]["tokens_out"] / max(wall_s, 1e-9), 1),
          "extra": {"step_breakdown": step_breakdown}})

    # SLO burn rates + a format-validated Prometheus snapshot: the
    # exposition payload itself is operator surface, the artifact
    # records that it validated and what the burn gauges read at
    # trace end (ROADMAP item 4's future degradation input signal)
    from ..telemetry.prometheus import validate_prometheus_text
    snap = server.metrics_snapshot()
    prom_errors = validate_prometheus_text(snap["prometheus"])
    emit({"phase": "serve-loop-slo",
          "burn_rates": {o["name"]: o["burn_rate"]
                         for o in s.get("slo", {}).get("objectives",
                                                       [])},
          "objectives": s.get("slo", {}).get("objectives", []),
          "degraded_fraction":
              s.get("slo", {}).get("degraded_fraction", 0.0),
          "prometheus_bytes": len(snap["prometheus"]),
          "prometheus_valid": not prom_errors,
          "prometheus_errors": prom_errors[:5]})

    # regression sentinel self-compare vs the committed trajectory
    # (non-fatal: the artifact records the verdicts, `perf check`
    # gates with an exit code)
    from ..perf import self_check_rows
    emit(self_check_rows(out or "SERVE_LOOP.jsonl", results))
    if fh is not None:
        fh.close()
    if prom_errors:
        raise RuntimeError(
            f"prometheus snapshot failed validation: {prom_errors}")
    if dropped:
        raise RuntimeError(
            f"serve_loop dropped {len(dropped)} requests: "
            f"{[(r.uid, r.state.name, r.reject_reason) for r in dropped]}")
    if parity["checked"] and parity["ok"] != parity["checked"]:
        raise RuntimeError(f"restore parity failed: {parity}")
    return results


def run_chaos_serve(seed=0, n_requests=32, runs=2,
                    out="CHAOS_SERVE.jsonl", **chaos_kw):
    """Chaos serving mode: seeded fault plans replayed over the
    virtual-clock simulation (``resilience.chaos.run_chaos``), with
    the robustness invariants asserted and the determinism gate run
    inline (``runs`` identical-seed replays must produce identical
    event digests). Emits one jsonl row per request, one per fault
    site, a checkpoint-hardening phase (save retry under an injected
    ``ckpt.write`` fault + corrupt-manifest fallback), and a summary
    row. Exits nonzero (raises) on any invariant violation — the
    artifact IS the acceptance evidence."""
    import shutil
    import tempfile

    from ..resilience import run_chaos
    from ..resilience.faults import FaultPlan, FaultRule, injected

    results = []
    fh = open(out, "w") if out else None

    def emit(row):
        results.append(row)
        line = json.dumps(row)
        print(line, flush=True)
        if fh is not None:
            fh.write(line + "\n")
            fh.flush()

    chaos = [run_chaos(seed=seed, n_requests=n_requests, **chaos_kw)
             for _ in range(max(1, runs))]
    r = chaos[0]
    digests = [c.event_digest for c in chaos]
    deterministic = len(set(digests)) == 1
    emit({"phase": "chaos-plan", "seed": seed, "plan": r.plan})
    for req in r.requests:
        emit({"phase": "chaos-request", **req})
    for site, n in sorted(r.fault_summary["by_site"].items()):
        emit({"phase": "chaos-fault-site", "site": site, "fired": n})

    # checkpoint-hardening phase: a transient ckpt.write fault is
    # absorbed by the bounded save retry; a corrupted manifest on the
    # newest checkpoint falls back to the previous one on restore
    from ..runtime.checkpoint_engine import SyncCheckpointEngine
    from ..runtime.checkpointing import load_checkpoint, save_checkpoint
    tmp = tempfile.mkdtemp(prefix="hds_chaos_ckpt_")
    try:
        state_v1 = {"params": np.arange(8, dtype=np.float32)}
        state_v2 = {"params": np.arange(8, dtype=np.float32) * 2}
        save_checkpoint(tmp, "step1", state_v1, {"step": 1},
                        checkpoint_engine=SyncCheckpointEngine())
        with injected(FaultPlan(seed=seed, rules=[
                FaultRule("ckpt.write", at_hits=(1,))])):
            save_checkpoint(tmp, "step2", state_v2, {"step": 2},
                            checkpoint_engine=SyncCheckpointEngine())
        retried_ok = True
        manifest = os.path.join(tmp, "step2", "hds_manifest.json")
        with open(manifest, "w") as mf:
            mf.write("{corrupt json")
        template = {"params": np.zeros(8, np.float32)}
        restored, meta = load_checkpoint(tmp, None, template)
        fallback_ok = (restored is not None and
                       meta.get("fallback_from") == "step2" and
                       np.array_equal(restored["params"],
                                      state_v1["params"]))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    emit({"phase": "chaos-ckpt", "save_retry_ok": retried_ok,
          "fallback_ok": bool(fallback_ok),
          "sites": ["ckpt.write", "ckpt.read"]})

    emit({"phase": "chaos-summary", "seed": seed,
          "n_requests": n_requests, "runs": len(chaos),
          "deterministic": deterministic,
          "event_digest": digests[0],
          "invariants_ok": all(c.ok for c in chaos),
          "violations": sum((c.violations for c in chaos), []),
          "invariants": r.invariants,
          "fault_summary": r.fault_summary,
          "counters": r.metrics["counters"],
          "failures": r.metrics["failures"],
          "rejected": r.metrics["rejected"]})
    if fh is not None:
        fh.close()
    if not all(c.ok for c in chaos):
        raise RuntimeError(
            f"chaos invariants violated: "
            f"{sum((c.violations for c in chaos), [])}")
    if not deterministic:
        raise RuntimeError(
            f"chaos determinism gate failed: digests {digests}")
    if not fallback_ok:
        raise RuntimeError("checkpoint fallback-to-previous failed")
    return results


def run_fleet_serve(seed=0, n_replicas=3, n_requests=48, runs=2,
                    out="FLEET_SERVE.jsonl", **chaos_kw):
    """Fleet serving mode: the N-replica router + latent-migration
    stack under seeded replica crash/hang/partition faults on the
    shared virtual clock (``resilience.chaos.run_fleet_chaos``). The
    first run is traced so the migration/decode overlap ratio in the
    artifact is SPAN-derived (``fleet.step`` spans carry both sides of
    the pair) and must agree with the fleet's counters; ``runs``
    identical-seed replays gate byte-identical event digests. Emits
    per-replica occupancy rows, per-migration rows, and a summary the
    perf registry indexes. Raises on any invariant violation — the
    artifact IS the acceptance evidence."""
    from ..resilience import run_fleet_chaos
    from ..telemetry.tracer import get_tracer

    results = []
    fh = open(out, "w") if out else None

    def emit(row):
        results.append(row)
        line = json.dumps(row)
        print(line, flush=True)
        if fh is not None:
            fh.write(line + "\n")
            fh.flush()

    # every run traced: the crossover model mines the span buffer when
    # the tracer is on, so mixing traced/untraced runs would change
    # calibration (and the digest) between them
    tracer = get_tracer()
    was = tracer.enabled
    tracer.configure(enabled=True)
    chaos = []
    span_events = None
    try:
        for _ in range(max(1, runs)):
            tracer.clear()
            chaos.append(run_fleet_chaos(
                seed=seed, n_replicas=n_replicas,
                n_requests=n_requests, **chaos_kw))
            if span_events is None:
                span_events = tracer.events()
    finally:
        tracer.configure(enabled=was)
    r = chaos[0]
    digests = [c.event_digest for c in chaos]
    deterministic = len(set(digests)) == 1

    # span-derived migration/decode overlap: each fleet.step span
    # carries (in_transit, decode_lanes); the ratio read off the spans
    # must equal the counter-derived one in the summary
    steps = [e for e in span_events
             if e.get("ph") == "X" and e.get("name") == "fleet.step"]
    transit = [e for e in steps
               if (e.get("args") or {}).get("in_transit", 0) > 0]
    overlapped = [e for e in transit
                  if (e.get("args") or {}).get("decode_lanes", 0) > 0]
    span_ratio = len(overlapped) / len(transit) if transit else 0.0
    counter_ratio = r.invariants["migration_overlap_ratio"]
    spans_agree = abs(span_ratio - counter_ratio) < 1e-9

    emit({"phase": "fleet-plan", "seed": seed,
          "n_replicas": n_replicas, "n_requests": n_requests,
          "plan": r.plan})
    for rid, rep in sorted(r.fleet_summary["replicas"].items()):
        emit({"phase": "fleet-replica", "replica": int(rid),
              "state": rep["state"], "steps": rep["steps"],
              "mean_occupancy": rep["mean_occupancy"],
              "kv_util_peak": rep["kv_util_peak"],
              "free_blocks": rep["free_blocks"],
              "initial_free_blocks": rep["initial_free_blocks"],
              "done": rep["done"],
              "preemptions": rep["counters"]["preemptions"],
              "restores": rep["counters"]["restores"],
              "recompute_reentries":
                  rep["counters"]["recompute_reentries"]})
    for m in r.migrations:
        emit({"phase": "fleet-migration", **m})
    for req in r.requests:
        emit({"phase": "fleet-request", **req})
    c = r.invariants["counters"]
    emit({"phase": "fleet-summary", "seed": seed,
          "n_replicas": n_replicas, "n_requests": n_requests,
          "runs": len(chaos),
          "deterministic": deterministic,
          "event_digest": digests[0],
          "invariants_ok": all(x.ok for x in chaos),
          "violations": sum((x.violations for x in chaos), []),
          "migration_balance_ok":
              r.invariants["migration_balance_ok"],
          "evictions": c["evictions"], "landings": c["landings"],
          "recompute_landings": c["recompute_landings"],
          "expired_in_transit": c["expired_in_transit"],
          "replica_crashes": c["replica_crashes"],
          "replica_hangs": c["replica_hangs"],
          "replica_partitions": c["replica_partitions"],
          "migration_overlap_ratio": counter_ratio,
          "span_overlap_ratio": round(span_ratio, 6),
          "span_counter_agreement": spans_agree,
          "replica_states": r.invariants["replica_states"],
          "router": r.fleet_summary["router"]})

    # regression sentinel self-compare vs the committed trajectory
    # (non-fatal: the artifact records verdicts; `perf check` gates)
    from ..perf import self_check_rows
    emit(self_check_rows(out or "FLEET_SERVE.jsonl", results))
    if fh is not None:
        fh.close()
    if not all(x.ok for x in chaos):
        raise RuntimeError(
            f"fleet chaos invariants violated: "
            f"{sum((x.violations for x in chaos), [])}")
    if not deterministic:
        raise RuntimeError(
            f"fleet determinism gate failed: digests {digests}")
    if not spans_agree:
        raise RuntimeError(
            f"span-derived overlap {span_ratio} != counter ratio "
            f"{counter_ratio}")
    return results


def run_disagg_serve(seed=0, n_prefill=1, n_decode=3, runs=2,
                     out="DISAGG_SERVE.jsonl", **compare_kw):
    """Disaggregated prefill/decode serving mode: the tier coordinator
    (``serving/disagg.py``) vs an equal-replica colocated fleet on one
    seeded mixed long-prompt + chatty trace, on the shared virtual
    clock. The acceptance gates run inline and the artifact records
    them: decode-tier TPOT p99 strictly better than the colocated
    baseline, bitwise disagg-vs-colocated token-stream parity, a
    span-derived handoff/decode overlap ratio (> 0, counter-agreeing),
    and byte-identical event digests across ``runs`` same-seed runs.
    Also emits an int8-latent-wire phase (wire-bytes attribution +
    stream parity vs the full-width wire), a chunked-prefill phase
    (chunk accounting on the prefill tier), and a tier-chaos phase
    (``resilience.chaos.run_disagg_chaos`` invariants + two-run
    determinism). Raises on any gate failure — the artifact IS the
    acceptance evidence."""
    from ..comm.comms_logging import get_comms_logger
    from ..resilience import run_disagg_chaos
    from ..serving import DisaggConfig, compare_disagg_vs_colocated

    results = []
    fh = open(out, "w") if out else None

    def emit(row):
        results.append(row)
        line = json.dumps(row)
        print(line, flush=True)
        if fh is not None:
            fh.write(line + "\n")
            fh.flush()

    r = compare_disagg_vs_colocated(seed=seed, n_prefill=n_prefill,
                                    n_decode=n_decode, runs=runs,
                                    **compare_kw)
    emit({"phase": "disagg-plan", "seed": seed,
          "n_prefill": n_prefill, "n_decode": n_decode,
          "runs": runs, "trace": r.trace_kw})
    for tier, t in sorted(r.tier_summary.items()):
        emit({"phase": "disagg-tier", "tier": tier, **t})
    for row in r.requests:
        emit({"phase": "disagg-request", **row})
    for h in r.handoffs:
        emit({"phase": "disagg-handoff", **h})

    m = r.metrics
    c = r.summary["counters"]
    emit({"phase": "disagg-summary", "seed": seed,
          "n_prefill": n_prefill, "n_decode": n_decode,
          "runs": runs,
          "deterministic": r.deterministic,
          "event_digest": r.disagg_digests[0],
          "colocated_digest": r.colocated_digest,
          "stream_parity": r.stream_parity,
          "invariants_ok": r.ok,
          "violations": r.violations,
          "handoffs": c["handoffs"],
          "handoff_landings": c["handoff_landings"],
          "colocated_decodes": c["colocated_decodes"],
          "handoff_overlap_ratio":
              r.summary["handoff_overlap_ratio"],
          "span_handoff_ratio": round(r.span_handoff_ratio, 6),
          "span_counter_agreement": r.span_counter_agreement,
          "decode_tier_tpot_p95":
              m["disagg"]["decode_tier_tpot_p95"],
          "decode_tier_tpot_p99":
              m["disagg"]["decode_tier_tpot_p99"],
          "colocated_tpot_p95": m["colocated"]["tpot_p95"],
          "colocated_tpot_p99": m["colocated"]["tpot_p99"],
          "disagg_tpot_p99": m["disagg"]["tpot_p99"],
          "disagg_ttft_p99": m["disagg"]["ttft_p99"],
          "colocated_ttft_p99": m["colocated"]["ttft_p99"],
          "handoff_transit_p99":
              m["disagg"]["handoff_transit_p99"],
          "metrics": m})

    # int8 latent wire: same comparison with the quantized handoff
    # payload; the streams must stay bitwise-equal to the full-width
    # run and the wire bytes must be attributed as a matched pair
    logger = get_comms_logger()
    logger_was = logger.enabled
    logger.configure(enabled=True)
    logger.reset()
    try:
        r8 = compare_disagg_vs_colocated(
            seed=seed, n_prefill=n_prefill, n_decode=n_decode,
            runs=runs,
            disagg=DisaggConfig(n_prefill=n_prefill,
                                n_decode=n_decode,
                                handoff_amortization=2.0,
                                handoff_wire_bits=8),
            **compare_kw)
        wire = logger.wire_savings_summary().get("latent_handoff", {})
    finally:
        logger.reset()
        logger.configure(enabled=logger_was)
    int8_parity = all(a["tokens"] == b["tokens"]
                      for a, b in zip(r.requests, r8.requests))
    emit({"phase": "disagg-int8-wire", "seed": seed,
          "invariants_ok": r8.ok, "violations": r8.violations,
          "deterministic": r8.deterministic,
          "stream_parity_vs_fullwidth": int8_parity,
          "wire_bytes": wire.get("wire_bytes"),
          "unquantized_equiv_bytes":
              wire.get("unquantized_equiv_bytes"),
          "wire_fraction": wire.get("fraction"),
          "op_kind": wire.get("op_kind")})

    # chunked prefill on the prefill tier (ROADMAP item 4, first
    # slice): same comparison with scheduler-grain chunking — chunk
    # accounting must be non-zero and every gate must still hold
    rc = compare_disagg_vs_colocated(
        seed=seed, n_prefill=n_prefill, n_decode=n_decode, runs=runs,
        prefill_chunk=16, **compare_kw)
    chunks = sum(
        rep["counters"]["prefill_chunks"]
        for rep in rc.summary["replicas"].values())
    emit({"phase": "disagg-chunked-prefill", "seed": seed,
          "prefill_chunk": 16,
          "invariants_ok": rc.ok, "violations": rc.violations,
          "deterministic": rc.deterministic,
          "stream_parity": rc.stream_parity,
          "prefill_chunks": chunks,
          "decode_tier_tpot_p99":
              rc.metrics["disagg"]["decode_tier_tpot_p99"],
          "colocated_tpot_p99":
              rc.metrics["colocated"]["tpot_p99"]})

    # tier-scoped chaos: prefill + decode replica crashes mid-trace,
    # never-dropped semantics, two-run digest determinism
    chaos = [run_disagg_chaos(seed=seed) for _ in range(max(1, runs))]
    cdigests = [x.event_digest for x in chaos]
    emit({"phase": "disagg-chaos", "seed": seed,
          "runs": len(chaos),
          "deterministic": len(set(cdigests)) == 1,
          "event_digest": cdigests[0],
          "invariants_ok": all(x.ok for x in chaos),
          "violations": sum((x.violations for x in chaos), []),
          "crashed_tiers": chaos[0].invariants["crashed_tiers"],
          "replica_states": chaos[0].invariants["replica_states"],
          "counters": chaos[0].invariants["counters"]})

    from ..perf import self_check_rows
    emit(self_check_rows(out or "DISAGG_SERVE.jsonl", results))
    if fh is not None:
        fh.close()
    failures = []
    if not r.ok:
        failures.append(f"disagg gates: {r.violations}")
    if not r8.ok or not int8_parity:
        failures.append(f"int8 wire: {r8.violations} "
                        f"parity={int8_parity}")
    if not rc.ok or not chunks:
        failures.append(f"chunked prefill: {rc.violations} "
                        f"chunks={chunks}")
    if not all(x.ok for x in chaos) or len(set(cdigests)) != 1:
        failures.append("tier chaos invariants/determinism")
    if failures:
        raise RuntimeError(f"disagg-serve gates failed: {failures}")
    return results


def _spec_digest(events) -> str:
    import hashlib
    payload = json.dumps(events, sort_keys=True,
                         separators=(",", ":")).encode()
    return hashlib.sha256(payload).hexdigest()


def run_spec_serve(seed=0, runs=2, out="SPEC_SERVE.jsonl"):
    """``--spec-serve``: CPU-deterministic audit of scheduler-
    dispatched speculative decoding + fleet-wide radix prefix reuse
    with latent prefix broadcast (docs/serving.md), on the shared
    virtual clock. Four phases, each gated inline — the artifact IS
    the acceptance evidence:

    * ``spec-lookup`` — lookup-friendly trace on one replica:
      speculative vs non-speculative scheduler, gating bitwise stream
      parity, accepted-tokens/step > 1.3 and a virtual-clock speedup;
    * ``spec-mixed`` — chatty + agent-swarm shared-prefix +
      long-prompt mix on a 3-replica fleet, speculation + prefix
      reuse + broadcast ON vs the affinity-only non-speculative
      fleet: stream parity, TTFT/TPOT p99s, leak/terminal invariants;
    * ``spec-prefix`` — the affinity-vs-load conflict trace: the warm
      replica is pinned hot so the router places sharers cold and the
      fleet must broadcast the common prefix ONCE over the latent
      wire; gates broadcasts >= 1, landings == planned terminal, and
      re-prefill savings (prompt tokens restored instead of
      re-prefilled) > 0;
    * ``spec-slo`` — an unmeetable TTFT objective drives the
      SLO-aware ladder (speculation off => chunked prefill => shed);
      gates that it escalated and that the trace still drained.

    Every phase runs ``runs`` times with one seed and gates
    byte-identical event digests. Self-compares against the committed
    perf trajectory before writing. Never touches the TPU relay."""
    from ..inference.config import RaggedInferenceEngineConfig
    from ..serving import (FleetConfig, PrefixReuseConfig, Request,
                           RouterConfig, ServerConfig, ServingFleet,
                           ServingServer, SimulatedEngine,
                           SLOModeConfig, SpeculationConfig,
                           VirtualClock)
    from ..serving.metrics import ServingMetrics
    from ..telemetry.slo import SLOObjective, SLOTracker

    results = []
    fh = open(out, "w") if out else None

    def emit(row):
        results.append(row)
        line = json.dumps(row)
        print(line, flush=True)
        if fh is not None:
            fh.write(line + "\n")
            fh.flush()

    SPEC = SpeculationConfig(ngram=2, max_draft=4, window=64)
    violations = []

    def make_engine(num_blocks=64, lanes=6, tracked=10,
                    max_context=160, vocab=16):
        return SimulatedEngine(RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": tracked,
                           "max_ragged_batch_size": 256,
                           "max_ragged_sequence_count": lanes,
                           "max_context": max_context},
            kv_cache={"block_size": 8, "num_blocks": num_blocks},
            hcache={"enable_latents": True}), vocab_size=vocab)

    # ---------------- phase 1: spec-lookup ------------------------- #
    def lookup_trace():
        rng = np.random.default_rng([seed, 0x51EC])
        return [Request(uid=i,
                        prompt=[int(t) for t in
                                rng.integers(1, 14, (8,))],
                        max_new_tokens=48,
                        arrival_time=0.01 * i) for i in range(12)]

    def run_single(speculation):
        server = ServingServer(
            make_engine(), clock=VirtualClock(),
            config=ServerConfig(max_queue_depth=64,
                                kv_demand_fraction=float("inf"),
                                speculation=speculation))
        reqs = lookup_trace()
        server.run_trace(reqs)
        return (server, reqs,
                _spec_digest([list(e)
                              for e in server.scheduler.events]))

    base_srv, base_reqs, _ = run_single(None)
    spec_runs = [run_single(SPEC) for _ in range(max(1, runs))]
    spec_srv, spec_reqs, _ = spec_runs[0]
    spec_digests = [d for _, _, d in spec_runs]
    lookup_parity = ({r.uid: r.tokens_out for r in base_reqs} ==
                     {r.uid: r.tokens_out for r in spec_reqs})
    accepted_per_step = spec_srv.metrics.gauges[
        "spec_accepted_tokens_per_step"]
    lookup_speedup = base_srv.clock.now() / max(spec_srv.clock.now(),
                                                1e-12)
    if not lookup_parity:
        violations.append("spec-lookup: stream parity broken")
    if accepted_per_step <= 1.3:
        violations.append(
            f"spec-lookup: accepted_tokens_per_step "
            f"{accepted_per_step:.3f} <= 1.3")
    emit({"phase": "spec-lookup", "seed": seed,
          "requests": len(base_reqs),
          "stream_parity": lookup_parity,
          "accepted_tokens_per_step": round(accepted_per_step, 6),
          "virtual_speedup": round(lookup_speedup, 6),
          "spec_counters": {
              k: spec_srv.metrics.counters[k]
              for k in ("spec_steps", "spec_lane_steps",
                        "spec_drafted", "spec_accepted",
                        "spec_emitted", "spec_rollback_tokens")},
          "baseline_virtual_s": round(base_srv.clock.now(), 6),
          "spec_virtual_s": round(spec_srv.clock.now(), 6),
          "deterministic": len(set(spec_digests)) == 1,
          "event_digest": spec_digests[0]})

    # ---------------- phase 2: spec-mixed fleet -------------------- #
    def mixed_trace():
        rng = np.random.default_rng([seed, 0x513D])
        reqs = []
        uid = 0
        shared = [int(t) for t in rng.integers(1, 14, (20,))]
        for i in range(10):          # chatty
            reqs.append(Request(
                uid=uid, prompt=[int(t) for t in
                                 rng.integers(1, 14, (6,))],
                max_new_tokens=6,
                arrival_time=float(i) * 0.01))
            uid += 1
        for i in range(12):          # agent swarm: shared prefix
            reqs.append(Request(
                uid=uid, prompt=shared + [i % 7 + 1, i % 5 + 1],
                max_new_tokens=10,
                arrival_time=0.05 + 0.008 * i))
            uid += 1
        for i in range(4):           # long prompt, long decode
            reqs.append(Request(
                uid=uid, prompt=[int(t) for t in
                                 rng.integers(1, 14, (40,))],
                max_new_tokens=40,
                arrival_time=0.02 + 0.03 * i))
            uid += 1
        return reqs

    def run_fleet(speculation, prefix, trace_fn, n_replicas=3,
                  prefix_weight=0.30):
        fleet = ServingFleet(
            engines=[make_engine(num_blocks=48, lanes=4, tracked=8)
                     for _ in range(n_replicas)],
            clock=VirtualClock(),
            config=FleetConfig(
                n_replicas=n_replicas,
                server=ServerConfig(max_queue_depth=128,
                                    kv_demand_fraction=float("inf"),
                                    speculation=speculation),
                router=RouterConfig(prefix_weight=prefix_weight),
                prefix=prefix))
        reqs = trace_fn()
        fleet.run_trace(reqs)
        return fleet, reqs, _spec_digest(fleet.event_log())

    def fleet_invariants(tag, fleet, reqs):
        terminal = {"DONE", "REJECTED", "FAILED"}
        for r in reqs:
            if r.state.name not in terminal:
                violations.append(
                    f"{tag}: request {r.uid} non-terminal")
            holders = sum(1 for rep in fleet.replicas
                          if r.uid in rep.scheduler.done)
            holders += 1 if r.uid in fleet.done else 0
            if holders != 1:
                violations.append(
                    f"{tag}: request {r.uid} terminal in "
                    f"{holders} places")
        for rep in fleet.replicas:
            if rep.engine.state.free_blocks != \
                    rep.initial_free_blocks:
                violations.append(f"{tag}: replica {rep.id} leaked")
            if rep.engine.state.n_tracked_sequences:
                violations.append(
                    f"{tag}: replica {rep.id} still tracking")
        if not fleet.migration_balance_ok:
            violations.append(f"{tag}: migration imbalance")

    def p99(fleet, which):
        vals = []
        for rep in fleet.replicas:
            hist = getattr(rep.server.metrics, which)
            v = hist.percentile(99)
            if v is not None:
                vals.append(v)
        return max(vals) if vals else None

    prefix_cfg = PrefixReuseConfig(min_adopt_tokens=6,
                                   min_broadcast_tokens=6)
    base_fleet, base_mreqs, _ = run_fleet(None, None, mixed_trace)
    mixed_runs = [run_fleet(SPEC, prefix_cfg, mixed_trace)
                  for _ in range(max(1, runs))]
    mix_fleet, mix_reqs, _ = mixed_runs[0]
    mix_digests = [d for _, _, d in mixed_runs]
    mixed_parity = ({r.uid: r.tokens_out for r in base_mreqs} ==
                    {r.uid: r.tokens_out for r in mix_reqs})
    if not mixed_parity:
        violations.append("spec-mixed: stream parity broken")
    fleet_invariants("spec-mixed", mix_fleet, mix_reqs)
    mixed_row = {
        "phase": "spec-mixed", "seed": seed,
        "requests": len(mix_reqs),
        "stream_parity": mixed_parity,
        "deterministic": len(set(mix_digests)) == 1,
        "event_digest": mix_digests[0],
        "baseline_virtual_s": round(base_fleet.clock.now(), 6),
        "spec_virtual_s": round(mix_fleet.clock.now(), 6),
        "virtual_speedup": round(
            base_fleet.clock.now() /
            max(mix_fleet.clock.now(), 1e-12), 6),
        "ttft_p99_baseline": p99(base_fleet, "ttft"),
        "ttft_p99_spec": p99(mix_fleet, "ttft"),
        "tpot_p99_baseline": p99(base_fleet, "tpot"),
        "tpot_p99_spec": p99(mix_fleet, "tpot"),
        "spec_lane_steps": sum(
            rep.server.metrics.counters["spec_lane_steps"]
            for rep in mix_fleet.replicas),
        "prefix_adoptions": sum(
            rep.server.metrics.counters["prefix_adoptions"]
            for rep in mix_fleet.replicas),
    }
    emit(mixed_row)

    # ---------------- phase 3: spec-prefix broadcast --------------- #
    def conflict_trace():
        """One sharer warms a replica; affinity-pinned long decodes
        then saturate it, so later sharers route cold and the fleet
        must broadcast the prefix once instead of re-prefilling it."""
        shared = [(7 * j) % 13 + 1 for j in range(16)]
        reqs = [Request(uid=0, prompt=shared + [9, 9],
                        max_new_tokens=4, arrival_time=0.0)]
        for i in range(1, 5):
            reqs.append(Request(uid=i, prompt=shared + [i],
                                max_new_tokens=60,
                                arrival_time=0.03 + 0.001 * i))
        for i in range(5, 14):
            reqs.append(Request(uid=i, prompt=shared + [i % 7 + 1,
                                                        i % 5 + 1],
                                max_new_tokens=6,
                                arrival_time=0.06 + 0.004 * i))
        return reqs

    def run_conflict(prefix):
        return run_fleet(SPEC, prefix, conflict_trace, n_replicas=2,
                         prefix_weight=0.05)

    aff_fleet, aff_reqs, _ = run_conflict(None)
    pfx_runs = [run_conflict(prefix_cfg) for _ in range(max(1, runs))]
    pfx_fleet, pfx_reqs, _ = pfx_runs[0]
    pfx_digests = [d for _, _, d in pfx_runs]
    pfx_parity = ({r.uid: r.tokens_out for r in aff_reqs} ==
                  {r.uid: r.tokens_out for r in pfx_reqs})
    fleet_invariants("spec-prefix", pfx_fleet, pfx_reqs)
    reused = sum(rep.server.metrics.counters["prefix_tokens_reused"]
                 for rep in pfx_fleet.replicas)
    aff_prefill = sum(rep.server.metrics.counters["prefill_tokens"]
                      for rep in aff_fleet.replicas)
    pfx_prefill = sum(rep.server.metrics.counters["prefill_tokens"]
                      for rep in pfx_fleet.replicas)
    savings = (aff_prefill - pfx_prefill) / max(aff_prefill, 1)
    broadcasts = pfx_fleet.counters["prefix_broadcasts"]
    landings = pfx_fleet.counters["prefix_broadcast_landings"]
    failed_bc = pfx_fleet.counters["prefix_broadcast_failed"]
    if not pfx_parity:
        violations.append("spec-prefix: stream parity broken")
    if broadcasts < 1:
        violations.append("spec-prefix: no prefix broadcast fired")
    if landings + failed_bc != broadcasts:
        violations.append(
            f"spec-prefix: broadcast imbalance ({broadcasts} sent, "
            f"{landings} landed, {failed_bc} failed)")
    if reused <= 0 or savings <= 0:
        violations.append(
            f"spec-prefix: no re-prefill savings (reused={reused}, "
            f"savings={savings:.4f})")
    emit({"phase": "spec-prefix", "seed": seed,
          "requests": len(pfx_reqs),
          "stream_parity": pfx_parity,
          "deterministic": len(set(pfx_digests)) == 1,
          "event_digest": pfx_digests[0],
          "prefix_broadcasts": broadcasts,
          "prefix_broadcast_landings": landings,
          "prefix_broadcast_failed": failed_bc,
          "prefix_adoptions": sum(
              rep.server.metrics.counters["prefix_adoptions"]
              for rep in pfx_fleet.replicas),
          "prefix_tokens_reused": reused,
          "affinity_prefill_tokens": aff_prefill,
          "reuse_prefill_tokens": pfx_prefill,
          "reprefill_savings": round(savings, 6),
          "affinity_virtual_s": round(aff_fleet.clock.now(), 6),
          "reuse_virtual_s": round(pfx_fleet.clock.now(), 6),
          "router": {k: v for k, v
                     in pfx_fleet.router.summary().items()
                     if "prefix" in k or "reuse" in k}})

    # ---------------- phase 4: SLO-aware ladder -------------------- #
    def run_slo():
        slo = SLOTracker(objectives=[
            SLOObjective("ttft", target=0.95, threshold_s=1e-9,
                         window_s=60.0)])
        server = ServingServer(
            make_engine(), clock=VirtualClock(),
            metrics=ServingMetrics(slo=slo),
            config=ServerConfig(
                max_queue_depth=128,
                kv_demand_fraction=float("inf"),
                speculation=SPEC,
                slo_mode=SLOModeConfig(ttft_burn_threshold=1.0,
                                       tpot_burn_threshold=1e9,
                                       hot_steps=2, calm_steps=1000,
                                       chunked_prefill_tokens=4)))
        rng = np.random.default_rng([seed, 0x510])
        reqs = [Request(uid=i,
                        prompt=[int(t) for t in
                                rng.integers(1, 14, (10,))],
                        max_new_tokens=12,
                        arrival_time=0.002 * i) for i in range(24)]
        server.run_trace(reqs)
        return (server, reqs,
                _spec_digest([list(e)
                              for e in server.scheduler.events]))

    slo_runs = [run_slo() for _ in range(max(1, runs))]
    slo_srv, slo_reqs, _ = slo_runs[0]
    slo_digests = [d for _, _, d in slo_runs]
    slo_level = slo_srv.scheduler.slo.level
    slo_degraded = slo_srv.metrics.counters["slo_degraded_steps"]
    if slo_degraded <= 0 or slo_level < 1:
        violations.append(
            f"spec-slo: ladder never escalated (level={slo_level}, "
            f"degraded_steps={slo_degraded})")
    if any(not r.finished for r in slo_reqs):
        violations.append("spec-slo: trace did not drain")
    emit({"phase": "spec-slo", "seed": seed,
          "requests": len(slo_reqs),
          "final_level": int(slo_level),
          "slo_degraded_steps": slo_degraded,
          "shed": slo_srv.metrics.counters["shed"],
          "rejected": dict(slo_srv.metrics.rejected),
          "prefill_chunks":
              slo_srv.metrics.counters["prefill_chunks"],
          "deterministic": len(set(slo_digests)) == 1,
          "event_digest": slo_digests[0]})

    # ---------------- summary + self-compare ----------------------- #
    deterministic = (len(set(spec_digests)) == 1 and
                     len(set(mix_digests)) == 1 and
                     len(set(pfx_digests)) == 1 and
                     len(set(slo_digests)) == 1)
    if not deterministic:
        violations.append("determinism gate failed")
    emit({"phase": "spec-serve-summary", "seed": seed,
          "runs": max(1, runs),
          "accepted_tokens_per_step": round(accepted_per_step, 6),
          "lookup_virtual_speedup": round(lookup_speedup, 6),
          "mixed_virtual_speedup": mixed_row["virtual_speedup"],
          "reprefill_savings": round(savings, 6),
          "prefix_broadcasts": broadcasts,
          "prefix_tokens_reused": reused,
          "stream_parity": bool(lookup_parity and mixed_parity and
                                pfx_parity),
          "deterministic": deterministic,
          "slo_final_level": int(slo_level),
          "invariants_ok": not violations,
          "violations": violations})

    from ..perf import self_check_rows
    emit(self_check_rows(out or "SPEC_SERVE.jsonl", results))
    if fh is not None:
        fh.close()
    if violations:
        raise RuntimeError(f"spec-serve gates failed: {violations}")
    return results


def run_fabric_serve(seed=0, n_replicas=3, n_requests=24, runs=2,
                     out="FABRIC_SERVE.jsonl"):
    """``--fabric``: deployment-fabric audit — the same seeded
    migration-heavy trace served through BOTH replica transports
    (docs/fabric.md), plus the literal kill-a-process chaos leg. The
    artifact IS the acceptance evidence; gates run inline:

    * ``fabric-parity`` — one fleet per transport on one seed. The
      in-memory twin runs ``runs`` times gating byte-identical event
      digests; the process fleet (one spawned worker per replica,
      migrations crossing real sockets as int8-framable latent frames
      + versioned trace wire dicts) must produce the SAME digest and
      bitwise-identical per-request token streams — the transport
      moves bytes, never outcomes. Gates at least one two-hop
      (src worker -> dst worker) crossing, measured wall-clock wire
      throughput recorded beside the priced ``link_bytes_per_s``
      (``FleetRouter.observe_wire`` calibration), and at least one
      request whose trace context counts >= 2 wire hops — real
      process boundaries in the causal DAG, which must stay connected;
    * ``fabric-chaos`` — ``resilience.run_fabric_chaos``: the busiest
      worker is SIGKILLed mid-trace and the fleet recovers with
      never-dropped accounting (exactly one terminal state per
      request, zero survivor leaks, migration balance, >= 1 request
      finished after the kill, zero bootstrap digest mismatches).

    CPU-only, never touches the TPU relay. Wall-clock readings appear
    ONLY in measured-wire fields — every gate the digests depend on is
    virtual-clock deterministic."""
    from ..fabric import (InMemoryTransport, ProcessTransport,
                          canonical_digest)
    from ..resilience import run_fabric_chaos
    from ..resilience.chaos import (_trace_gates, _trace_row,
                                    build_chaos_trace)
    from ..serving import (FleetConfig, RouterConfig, ServerConfig,
                           ServingFleet, SimulatedEngine, VirtualClock)
    from .config import RaggedInferenceEngineConfig

    results = []
    fh = open(out, "w") if out else None

    def emit(row):
        results.append(row)
        line = json.dumps(row)
        print(line, flush=True)
        if fh is not None:
            fh.write(line + "\n")
            fh.flush()

    violations = []

    def make_engine():
        # deliberately tight KV budget: pressure evictions make the
        # trace migration-heavy, so bytes actually cross the fabric
        return SimulatedEngine(RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8,
                           "max_ragged_batch_size": 256,
                           "max_ragged_sequence_count": 4,
                           "max_context": 64},
            kv_cache={"block_size": 8, "num_blocks": 12},
            hcache={"enable_latents": True}))

    def drive(transport):
        """One full kill-free serve of the seeded trace."""
        fleet = ServingFleet(
            engines=[make_engine() for _ in range(n_replicas)],
            clock=VirtualClock(),
            config=FleetConfig(
                n_replicas=n_replicas,
                server=ServerConfig(max_queue_depth=n_requests + 1,
                                    kv_demand_fraction=float("inf")),
                router=RouterConfig(),
                transport=transport))
        reqs = build_chaos_trace(
            seed, n_requests, fleet.replicas[0].engine.vocab_size,
            max_new=10, rps=400.0, prompt_hi=24)
        with fleet.transport:
            arrivals = sorted(reqs,
                              key=lambda r: (r.arrival_time, r.uid))
            steps = 0
            while arrivals or fleet.has_work:
                now = fleet.clock.now()
                while arrivals and arrivals[0].arrival_time <= now:
                    fleet.submit(request=arrivals.pop(0))
                if not fleet.has_work and arrivals:
                    fleet.clock.advance_to(arrivals[0].arrival_time)
                    continue
                fleet.step()
                steps += 1
                if steps > 1_000_000:
                    raise RuntimeError("fabric serve livelock:\n"
                                       + fleet.snapshot())
        return fleet, reqs, canonical_digest(fleet.event_log())

    # ------------- phase 1: cross-transport parity ----------------- #
    mem_runs = [drive(InMemoryTransport())
                for _ in range(max(1, runs))]
    mem_digests = [d for _, _, d in mem_runs]
    deterministic = len(set(mem_digests)) == 1
    mem_fleet, mem_reqs, mem_digest = mem_runs[0]
    proc_fleet, proc_reqs, proc_digest = drive(ProcessTransport())
    wire = proc_fleet.transport.wire_stats()
    stream_parity = ({r.uid: r.tokens_out for r in mem_reqs} ==
                     {r.uid: r.tokens_out for r in proc_reqs})
    digest_invariant = proc_digest == mem_digest
    max_hops = max((getattr(r.trace, "hops", 0) or 0)
                   for r in proc_reqs)
    trace_inv = _trace_gates(proc_reqs, violations)
    measured_link = proc_fleet.summary()["router"].get(
        "measured_link")
    if not deterministic:
        violations.append(
            f"fabric-parity: in-memory twin digests diverged across "
            f"{len(mem_digests)} runs")
    if not stream_parity:
        violations.append(
            "fabric-parity: process-vs-in-memory token streams differ")
    if not digest_invariant:
        violations.append(
            "fabric-parity: event digest depends on the transport "
            f"({proc_digest[:12]} != {mem_digest[:12]})")
    if wire["shipped"] < 1 or wire["deliveries"] < 1:
        violations.append(
            f"fabric-parity: no bytes crossed the fabric ({wire})")
    if wire["two_hop_deliveries"] < 1:
        violations.append(
            "fabric-parity: no two-hop (worker-to-worker) crossing")
    if wire["measured_wire_bytes_per_s"] <= 0:
        violations.append(
            "fabric-parity: measured wire throughput missing")
    if measured_link is None or measured_link["samples"] < 1:
        violations.append(
            "fabric-parity: router measured-link calibration absent")
    if max_hops < 2:
        violations.append(
            f"fabric-parity: max trace hops {max_hops} < 2 — no trace "
            "crossed a real process boundary")
    if wire["bootstrap_mismatches"]:
        violations.append(
            f"fabric-parity: {wire['bootstrap_mismatches']} bootstrap "
            "digest mismatches")
    for r in proc_reqs:
        emit({"phase": "fabric-request", "uid": r.uid,
              "state": r.state.name, "tokens": len(r.tokens_out),
              "migrations": r.n_migrations, **_trace_row(r)})
    emit({"phase": "fabric-parity", "seed": seed,
          "n_replicas": n_replicas, "n_requests": n_requests,
          "runs": len(mem_runs),
          "deterministic": deterministic,
          "event_digest": mem_digest,
          "process_digest": proc_digest,
          "digest_transport_invariant": digest_invariant,
          "stream_parity": stream_parity,
          "transports": [mem_fleet.transport.name,
                         proc_fleet.transport.name],
          "wire": wire,
          "priced_link_bytes_per_s":
              proc_fleet.config.link_bytes_per_s,
          "measured_link": measured_link,
          "max_trace_hops": max_hops,
          "trace": trace_inv})

    # ------------- phase 2: literal kill-a-process ----------------- #
    chaos = run_fabric_chaos(seed=seed, n_replicas=n_replicas)
    violations.extend(f"fabric-chaos: {v}" for v in chaos.violations)
    emit({"phase": "fabric-chaos", "seed": seed,
          "victim": chaos.victim,
          "event_digest": chaos.event_digest,
          "ok": chaos.ok,
          "wire": chaos.wire,
          "invariants": chaos.invariants})

    c = chaos.invariants["counters"]
    emit({"phase": "fabric-summary", "seed": seed,
          "n_replicas": n_replicas, "n_requests": n_requests,
          "runs": len(mem_runs),
          "deterministic": deterministic,
          "event_digest": mem_digest,
          "digest_transport_invariant": digest_invariant,
          "stream_parity": stream_parity,
          "two_hop_deliveries": wire["two_hop_deliveries"],
          "wire_bytes": wire["wire_bytes"],
          "measured_wire_bytes_per_s":
              wire["measured_wire_bytes_per_s"],
          "priced_link_bytes_per_s":
              proc_fleet.config.link_bytes_per_s,
          "max_trace_hops": max_hops,
          "trace_connected": trace_inv["connected"],
          "chaos_ok": chaos.ok,
          "chaos_kills": chaos.wire["kills"],
          "replica_crashes": c["replica_crashes"],
          "done_after_kill": chaos.invariants["done_after"],
          "bootstrap_mismatches":
              wire["bootstrap_mismatches"] +
              chaos.wire["bootstrap_mismatches"],
          "invariants_ok": not violations,
          "violations": violations})

    from ..perf import self_check_rows
    emit(self_check_rows(out or "FABRIC_SERVE.jsonl", results))
    if fh is not None:
        fh.close()
    if violations:
        raise RuntimeError(
            f"fabric serve gates violated: {violations}")
    return results


def run_fabric_obs(seed=0, n_replicas=3, n_requests=24, runs=2,
                   out="FABRIC_OBS.jsonl"):
    """``--fabric-obs``: cross-process telemetry-plane audit
    (docs/observability.md). The fabric's observability must be
    *free* where it matters — the serving core's committed digests —
    and *real* where humans look. Gates run inline:

    * ``obs-invariance`` — the seeded kill-free trace served through
      the process fleet with harvest ON (``runs`` times, gating
      2-run digest determinism), harvest OFF, and the in-memory twin:
      all event digests must be byte-identical (the telemetry plane
      is digest-invisible), and the measured harvest overhead
      (``transport.harvest_seconds`` against the fabric leg's wall
      time) must stay <= 5%;
    * ``obs-timeline`` — the fabric chaos run traced end-to-end; the
      assembled cross-process timeline must be Perfetto-validator
      clean with one real process row per worker carrying harvested
      spans and >= 1 migration flow arrow spanning two actual worker
      processes;
    * ``obs-postmortem`` — the SIGKILL's ``worker_kill``
      flight-recorder bundle must carry the victim's last-harvested
      telemetry (spans + counters) as wall-clock attachments;
    * per-link wire percentiles (p50/p99 latency and bytes/s from the
      router's quantile sketches) are recorded as informational
      trajectory — wall-clock readings on whatever host ran this.

    CPU-only, never touches the TPU relay."""
    from ..fabric import (InMemoryTransport, ProcessTransport,
                          canonical_digest)
    from ..resilience import run_fabric_chaos
    from ..resilience.chaos import build_chaos_trace
    from ..serving import (FleetConfig, RouterConfig, ServerConfig,
                           ServingFleet, SimulatedEngine, VirtualClock)
    from ..telemetry import get_flight_recorder, get_tracer
    from ..telemetry.assemble import (WORKER_PID_BASE,
                                      assemble_process_fleet_trace)
    from ..telemetry.export import validate_trace
    from .config import RaggedInferenceEngineConfig

    results = []
    fh = open(out, "w") if out else None

    def emit(row):
        results.append(row)
        line = json.dumps(row)
        print(line, flush=True)
        if fh is not None:
            fh.write(line + "\n")
            fh.flush()

    violations = []

    def make_engine():
        return SimulatedEngine(RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8,
                           "max_ragged_batch_size": 256,
                           "max_ragged_sequence_count": 4,
                           "max_context": 64},
            kv_cache={"block_size": 8, "num_blocks": 12},
            hcache={"enable_latents": True}))

    def drive(transport):
        """One full kill-free serve; returns the fleet, the event
        digest, and the leg's wall time (overhead denominator)."""
        fleet = ServingFleet(
            engines=[make_engine() for _ in range(n_replicas)],
            clock=VirtualClock(),
            config=FleetConfig(
                n_replicas=n_replicas,
                server=ServerConfig(max_queue_depth=n_requests + 1,
                                    kv_demand_fraction=float("inf")),
                router=RouterConfig(),
                transport=transport))
        reqs = build_chaos_trace(
            seed, n_requests, fleet.replicas[0].engine.vocab_size,
            max_new=10, rps=400.0, prompt_hi=24)
        t0 = time.perf_counter()
        with fleet.transport:
            arrivals = sorted(reqs,
                              key=lambda r: (r.arrival_time, r.uid))
            steps = 0
            while arrivals or fleet.has_work:
                now = fleet.clock.now()
                while arrivals and arrivals[0].arrival_time <= now:
                    fleet.submit(request=arrivals.pop(0))
                if not fleet.has_work and arrivals:
                    fleet.clock.advance_to(arrivals[0].arrival_time)
                    continue
                fleet.step()
                steps += 1
                if steps > 1_000_000:
                    raise RuntimeError("fabric obs livelock:\n"
                                       + fleet.snapshot())
        wall = time.perf_counter() - t0
        return fleet, canonical_digest(fleet.event_log()), wall

    # ------------- phase 1: harvest digest invariance -------------- #
    _, mem_digest, _ = drive(InMemoryTransport())
    on_runs = [drive(ProcessTransport()) for _ in range(max(1, runs))]
    on_digests = [d for _, d, _ in on_runs]
    _, off_digest, _ = drive(ProcessTransport(harvest_telemetry=False))
    deterministic = len(set(on_digests)) == 1
    harvest_digest_invariant = (
        deterministic and on_digests[0] == off_digest ==
        mem_digest)
    on_fleet, _, on_wall = on_runs[0]
    tr = on_fleet.transport
    overhead = (tr.harvest_seconds / on_wall) if on_wall > 0 else 0.0
    if not deterministic:
        violations.append(
            f"obs-invariance: harvest-on digests diverged across "
            f"{len(on_digests)} runs")
    if not harvest_digest_invariant:
        violations.append(
            "obs-invariance: telemetry harvest is digest-VISIBLE "
            f"(on {on_digests[0][:12]} / off {off_digest[:12]} / "
            f"mem {mem_digest[:12]})")
    if tr.harvests < 1:
        violations.append(
            "obs-invariance: harvest plane never harvested (the "
            "invariance gate tested nothing)")
    if overhead > 0.05:
        violations.append(
            f"obs-invariance: harvest overhead {overhead:.4f} of "
            "fabric-leg wall time exceeds the 5% budget")
    measured_link = on_fleet.summary()["router"].get(
        "measured_link") or {}
    links = measured_link.get("links", {})
    busiest = max(sorted(links),
                  key=lambda k: links[k]["latency_s"]["count"]) \
        if links else ""
    if not links:
        violations.append(
            "obs-invariance: no per-link wire sketches recorded")
    emit({"phase": "obs-invariance", "seed": seed,
          "runs": len(on_runs),
          "deterministic": deterministic,
          "harvest_digest_invariant": harvest_digest_invariant,
          "event_digest": mem_digest,
          "harvest_on_digest": on_digests[0],
          "harvest_off_digest": off_digest,
          "harvests": tr.harvests,
          "harvest_failures": tr.harvest_failures,
          "harvest_seconds": round(tr.harvest_seconds, 6),
          "leg_wall_seconds": round(on_wall, 6),
          "harvest_overhead_fraction": round(overhead, 6),
          "worker_telemetry": tr.telemetry_stats()})
    emit({"phase": "obs-wire", "seed": seed,
          "links": links, "busiest_link": busiest,
          "priced_link_bytes_per_s":
              on_fleet.config.link_bytes_per_s})

    # ------------- phase 2: assembled cross-process timeline ------- #
    tracer = get_tracer()
    was = tracer.enabled
    tracer.configure(enabled=True)
    tracer.clear()
    flight = get_flight_recorder()
    flight.clear()
    try:
        chaos = run_fabric_chaos(seed=seed, n_replicas=n_replicas)
        parent_events = tracer.events()
        parent_dropped = tracer.dropped
    finally:
        tracer.configure(enabled=was)
    violations.extend(f"obs-chaos: {v}" for v in chaos.violations)
    workers = chaos.telemetry.get("workers", {})
    assembled, warnings = assemble_process_fleet_trace(
        parent_events, workers, dropped=parent_dropped)
    timeline_valid = True
    timeline_error = ""
    try:
        stats = validate_trace(assembled)
    except ValueError as exc:
        timeline_valid = False
        timeline_error = str(exc)
        stats = {"events": len(assembled), "spans": 0, "pairs": 0}
        violations.append(
            f"obs-timeline: assembled trace invalid: {exc}")
    worker_rows = sum(
        1 for e in assembled
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and e.get("pid", 0) >= WORKER_PID_BASE)
    worker_spans = sum(
        1 for e in assembled
        if e.get("pid", 0) >= WORKER_PID_BASE and
        e.get("ph") in ("X", "B", "i"))
    cross_worker_arrows = sum(
        1 for e in assembled
        if e.get("ph") == "s" and e.get("cat") == "fabric")
    if worker_rows < n_replicas:
        violations.append(
            f"obs-timeline: only {worker_rows} worker process rows "
            f"for {n_replicas} workers")
    if worker_spans < 1:
        violations.append(
            "obs-timeline: no harvested spans landed on any worker "
            "row")
    if cross_worker_arrows < 1:
        violations.append(
            "obs-timeline: no migration flow arrow spans two real "
            "worker processes")
    emit({"phase": "obs-timeline", "seed": seed,
          "timeline_valid": timeline_valid,
          "timeline_error": timeline_error,
          "events": stats["events"], "spans": stats["spans"],
          "worker_rows": worker_rows,
          "worker_spans": worker_spans,
          "cross_worker_arrows": cross_worker_arrows,
          "assembly_warnings": warnings,
          "chaos_ok": chaos.ok,
          "chaos_digest": chaos.event_digest,
          "harvest": chaos.telemetry.get("harvest", {})})

    # ------------- phase 3: SIGKILL postmortem bundle -------------- #
    kill_bundles = [b for b in list(flight.bundles)
                    if b["trigger"] == "worker_kill"]
    bundle = kill_bundles[0] if kill_bundles else {}
    attach = bundle.get("attachments", {})
    postmortem_has_telemetry = bool(
        kill_bundles and
        bundle.get("snapshot", {}).get("victim") == chaos.victim and
        attach.get("counters") and
        attach.get("harvests", 0) >= 1)
    if not kill_bundles:
        violations.append(
            "obs-postmortem: no worker_kill flight bundle recorded")
    elif not postmortem_has_telemetry:
        violations.append(
            "obs-postmortem: worker_kill bundle lacks the victim's "
            "last-harvested telemetry")
    emit({"phase": "obs-postmortem", "seed": seed,
          "bundles": len(kill_bundles),
          "victim": chaos.victim,
          "postmortem_has_telemetry": postmortem_has_telemetry,
          "bundle_digest": bundle.get("digest", ""),
          "bundle_spans": len(bundle.get("spans", [])),
          "attachment_counters":
              sorted(attach.get("counters", {})),
          "attachment_harvests": attach.get("harvests", 0)})

    # ------------- summary ----------------------------------------- #
    blink = links.get(busiest, {})
    emit({"phase": "fabric-obs-summary", "seed": seed,
          "n_replicas": n_replicas, "n_requests": n_requests,
          "runs": len(on_runs),
          "deterministic": deterministic,
          "harvest_digest_invariant": harvest_digest_invariant,
          "event_digest": mem_digest,
          "harvests": tr.harvests,
          "harvest_failures": tr.harvest_failures,
          "harvest_overhead_fraction": round(overhead, 6),
          "timeline_valid": timeline_valid,
          "worker_rows": worker_rows,
          "worker_spans": worker_spans,
          "cross_worker_arrows": cross_worker_arrows,
          "postmortem_has_telemetry": postmortem_has_telemetry,
          "chaos_ok": chaos.ok,
          "busiest_link": busiest,
          "wire_latency_p50_s":
              blink.get("latency_s", {}).get("p50"),
          "wire_latency_p99_s":
              blink.get("latency_s", {}).get("p99"),
          "wire_bytes_per_s_p50":
              blink.get("bytes_per_s", {}).get("p50"),
          "wire_bytes_per_s_p99":
              blink.get("bytes_per_s", {}).get("p99"),
          "invariants_ok": not violations,
          "violations": violations})

    from ..perf import self_check_rows
    emit(self_check_rows(out or "FABRIC_OBS.jsonl", results))
    if fh is not None:
        fh.close()
    if violations:
        raise RuntimeError(
            f"fabric obs gates violated: {violations}")
    return results


def run_request_trace(seed=0, runs=2, out="REQUEST_TRACE.jsonl",
                      closure_tol=0.01):
    """Causal request-tracing audit (``bench.py --request-trace``):
    replay the committed chaos workloads — the single-engine storm,
    the fleet crash/hang/partition run, and the disaggregated tier
    run — and gate, per leg and fleet-wide:

    * **connected span DAGs** — every terminal request's TraceContext
      chain tiles its timeline with no orphan spans, across >=1 crash
      evacuation and >=1 prefill→decode handoff;
    * **attribution closure** — per-request critical-path attribution
      sums to the measured E2E latency within ``closure_tol`` (1%);
    * **determinism** — same-seed event digests byte-identical across
      ``runs`` replays;
    * **flight recorder** — each leg's anomaly triggers (breaker
      trips, SLO burn) produce the same bundle count with pairwise
      byte-identical bundle digests across same-seed runs.

    The summary row carries the headline p99-TTFT attribution profile
    (which stage owns the TTFT tail). Raises on any gate failure —
    the artifact IS the acceptance evidence. Pure CPU/virtual-clock.
    """
    from ..resilience.chaos import (run_chaos, run_disagg_chaos,
                                    run_fleet_chaos)
    from ..telemetry.flight import get_flight_recorder

    results = []
    fh = open(out, "w") if out else None

    def emit(row):
        results.append(row)
        line = json.dumps(row)
        print(line, flush=True)
        if fh is not None:
            fh.write(line + "\n")
            fh.flush()

    emit({"phase": "request-trace-plan", "seed": seed, "runs": runs,
          "closure_tol": closure_tol,
          "legs": ["chaos", "fleet", "disagg"]})

    recorder = get_flight_recorder()
    legs = (("chaos", lambda: run_chaos(seed=seed)),
            ("fleet", lambda: run_fleet_chaos(seed=seed)),
            ("disagg", lambda: run_disagg_chaos(seed=seed)))
    violations, leg_results = [], {}
    ttft_attrs, flight_total = [], 0
    flight_det_all, det_all, connected_all, closure_ok = \
        True, True, True, True
    max_residual = 0.0
    for name, fn in legs:
        digests, flight_digests, first = [], [], None
        for _ in range(max(1, runs)):
            recorder.clear()
            res = fn()
            digests.append(res.event_digest)
            flight_digests.append(recorder.digests())
            if first is None:
                first = res
        leg_results[name] = first
        if not first.ok:
            violations.append(f"{name}: invariants failed: "
                              f"{first.violations[:4]}")
        tr = first.invariants.get("trace", {})
        if not tr.get("connected", False):
            connected_all = False
            violations.append(f"{name}: span DAG not connected")
        res_max = float(tr.get("max_closure_residual", 1.0))
        max_residual = max(max_residual, res_max)
        if res_max > closure_tol:
            closure_ok = False
            violations.append(
                f"{name}: closure residual {res_max} > {closure_tol}")
        deterministic = len(set(digests)) == 1
        det_all = det_all and deterministic
        if not deterministic:
            violations.append(f"{name}: digests diverged {digests}")
        flight_det = len({tuple(d) for d in flight_digests}) == 1
        flight_det_all = flight_det_all and flight_det
        if not flight_det:
            violations.append(
                f"{name}: flight bundles diverged across same-seed "
                f"runs ({[len(d) for d in flight_digests]})")
        flight_total += len(flight_digests[0])
        for row in first.requests:
            if row.get("ttft_attr"):
                ttft_attrs.append(row["ttft_attr"])
        emit({"phase": "request-trace-leg", "leg": name,
              "runs": len(digests),
              "event_digest": digests[0],
              "deterministic": deterministic,
              "connected": tr.get("connected", False),
              "traced_requests": tr.get("traced_requests", 0),
              "max_closure_residual": res_max,
              "flight_bundles": len(flight_digests[0]),
              "flight_triggers": sorted(
                  {b["trigger"] for b in recorder.bundles}),
              "flight_digests": flight_digests[0],
              "flight_deterministic": flight_det})
        for row in first.requests:
            emit({"phase": "request-trace-request", "leg": name,
                  **row})

    # the coverage floor: the legs must actually exercise the wire —
    # a crash evacuation (fleet) and a tier handoff (disagg)
    fleet_c = leg_results["fleet"].invariants["counters"]
    disagg_c = leg_results["disagg"].invariants["counters"]
    if not fleet_c.get("replica_crashes"):
        violations.append("fleet leg had no crash evacuation")
    if not disagg_c.get("handoffs"):
        violations.append("disagg leg had no handoffs")
    if not flight_total:
        violations.append("no flight-recorder bundle was triggered")

    # headline p99-TTFT attribution across the fleet+disagg requests:
    # absent phases count 0.0 so percentiles compare like-for-like
    phases = sorted({p for a in ttft_attrs for p in a})
    ttft_p99 = {p: round(float(np.percentile(
        np.asarray([a.get(p, 0.0) for a in ttft_attrs]), 99)), 9)
        for p in phases} if ttft_attrs else {}
    ttft_totals = [sum(a.values()) for a in ttft_attrs]
    summary = {
        "phase": "request-trace-summary", "seed": seed,
        "runs": runs, "closure_tol": closure_tol,
        "dag_connected": connected_all,
        "closure_ok": closure_ok,
        "closure_max_residual": round(max_residual, 9),
        "deterministic": det_all,
        "flight_deterministic": flight_det_all,
        "flight_bundles": flight_total,
        "traced_requests": sum(
            r.invariants["trace"]["traced_requests"]
            for r in leg_results.values()),
        "crash_evacuations": fleet_c.get("replica_crashes", 0),
        "handoffs": disagg_c.get("handoffs", 0),
        "ttft_p99_s": round(float(np.percentile(
            np.asarray(ttft_totals), 99)), 9) if ttft_totals else None,
        "ttft_attr_p99_s": ttft_p99,
        "violations": violations,
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    emit(summary)

    from ..perf import self_check_rows
    emit(self_check_rows(out or "REQUEST_TRACE.jsonl", results))
    if fh is not None:
        fh.close()
    if violations:
        raise RuntimeError(
            f"request-trace gates failed: {violations}")
    return results


def run_autoscale_serve(seed=7, n_requests=800, horizon_s=20.0,
                        runs=2, out="AUTOSCALE_SERVE.jsonl"):
    """``--autoscale``: SLO-driven elastic autoscaling audit — the
    hysteresis control loop (``serving/autoscale.py``) over the bursty
    diurnal multi-tenant trace, with scale events treated as a
    first-class failure domain. The artifact IS the acceptance
    evidence; gates run inline:

    * ``autoscale-main`` — the autoscaled fleet serves the seeded
      trace ``runs`` times gating byte-identical event digests. Every
      scale event must be span-verified through the causal trace DAG:
      each ``fleet.scale_up`` / ``fleet.retire`` async span opened by
      the fleet must close with a terminal status, and the span counts
      must equal the fleet's scale counters. Per-request trace DAGs
      stay connected across migrations caused by drain-retirement.
    * ``autoscale-static`` — the SAME trace through static fleets at
      the start size and at the autoscaler's peak size. Gates: SLO
      attainment (TTFT <= threshold over DONE requests) >= the best
      static fleet of equal peak size, at strictly lower cost
      (replica-steps actually consumed).
    * ``autoscale-chaos`` — ``resilience.run_autoscale_chaos`` twice:
      scale-up killed mid-bootstrap, replica crashed mid-drain, faulted
      pre-warm; identical digests + all invariants.
    * ``autoscale-process`` — ProcessTransport leg: a REAL worker
      process is spawned by scale-up with the first spawn killed by an
      injected ``scale.spawn`` fault (supervised retry recovers), and
      the retired replica's worker is reaped only after its drain
      lands. Zero requests lost.

    CPU-only, virtual-clock deterministic in every gated field."""
    from ..fabric import ProcessTransport, canonical_digest
    from ..resilience import (FaultPlan, FaultRule, injected,
                              run_autoscale_chaos)
    from ..resilience.chaos import _trace_gates
    from ..serving import (AutoscaleConfig, Autoscaler, FleetConfig,
                           PrefixReuseConfig, RequestState,
                           ServerConfig, ServingFleet,
                           SimulatedEngine, VirtualClock,
                           build_autoscale_trace)
    from ..serving.spec import SLOModeConfig
    from ..telemetry.tracer import get_tracer
    from .config import RaggedInferenceEngineConfig

    results = []
    fh = open(out, "w") if out else None

    def emit(row):
        results.append(row)
        line = json.dumps(row)
        print(line, flush=True)
        if fh is not None:
            fh.write(line + "\n")
            fh.flush()

    violations = []

    def make_engine():
        return SimulatedEngine(RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8,
                           "max_ragged_batch_size": 256,
                           "max_ragged_sequence_count": 4,
                           "max_context": 64},
            kv_cache={"block_size": 8, "num_blocks": 16},
            hcache={"enable_latents": True}))

    slo_ttft_s = 1.0
    start_replicas, peak_replicas = 2, 4

    def make_fleet(n):
        return ServingFleet(
            engine_factory=make_engine,
            clock=VirtualClock(),
            config=FleetConfig(
                n_replicas=n,
                server=ServerConfig(max_queue_depth=n_requests + 1,
                                    kv_demand_fraction=float("inf"),
                                    slo_mode=SLOModeConfig()),
                prefix=PrefixReuseConfig(broadcast=True,
                                         min_adopt_tokens=4)))

    def make_trace():
        return build_autoscale_trace(seed=seed, n_requests=n_requests,
                                     horizon_s=horizon_s,
                                     new_tokens=(8, 16))

    def score(fleet, reqs):
        done = [r for r in reqs if r.state is RequestState.DONE]
        attained = [r for r in done
                    if r.ttft() is not None
                    and r.ttft() <= slo_ttft_s]
        cost = sum(rep.steps for rep in fleet.replicas)
        return {"done": len(done),
                "slo_attainment": round(len(attained)
                                        / max(1, len(reqs)), 6),
                "cost_replica_steps": cost}

    def drive_auto():
        fleet = make_fleet(start_replicas)
        asc = Autoscaler(fleet, AutoscaleConfig(
            min_replicas=1, max_replicas=peak_replicas,
            hot_steps=2, calm_steps=60, cooldown_steps=40,
            flap_window_steps=60))
        reqs = make_trace()
        summary = asc.run(reqs)
        return fleet, asc, reqs, summary, \
            canonical_digest(fleet.event_log())

    # ------------- phase 1: autoscaled serve + spans --------------- #
    # every run traced (the crossover model mines the span buffer when
    # the tracer is on; mixing traced/untraced runs would change the
    # digest) at a capacity that cannot displace scale-event spans
    tracer = get_tracer()
    was = tracer.enabled
    cap_was = tracer._capacity
    tracer.configure(enabled=True, capacity=1 << 20)
    auto_runs = []
    span_events = None
    try:
        for _ in range(max(1, runs)):
            tracer.clear()
            auto_runs.append(drive_auto())
            if span_events is None:
                span_events = tracer.events()
        digests = [d for *_, d in auto_runs]
        deterministic = len(set(digests)) == 1
        fleet, asc, reqs, summary, digest = auto_runs[0]

        # span-verify every scale event through the trace DAG: each
        # fleet.scale_up / fleet.retire async begin pairs with exactly
        # one terminal-status end, and span counts match the counters
        def _async(names):
            by = {}
            for e in span_events:
                if e.get("name") in names and e.get("ph") in ("b", "e"):
                    key = (e["name"], e.get("cat"), e.get("id"))
                    by.setdefault(key, []).append(e)
            return by
        spans = _async({"fleet.scale_up", "fleet.retire"})
        # the same replica id may scale up / retire repeatedly, so a
        # key holds an interleaved history — it must strictly
        # alternate b, e, b, e, ... and close
        unpaired = sorted(
            k[0] + ":" + str(k[2]) for k, evs in spans.items()
            if [x["ph"] for x in evs]
            != ["b", "e"] * (len(evs) // 2) or len(evs) % 2)
        statuses = sorted(
            (e.get("args") or {}).get("status", "?")
            for evs in spans.values() for e in evs
            if e["ph"] == "e")
        c = fleet.counters
        n_up_spans = sum(
            1 for k, evs in spans.items() for e in evs
            if k[0] == "fleet.scale_up" and e["ph"] == "b")
        n_ret_spans = sum(
            1 for k, evs in spans.items() for e in evs
            if k[0] == "fleet.retire" and e["ph"] == "b")
        span_counts_agree = (
            n_up_spans == c["scale_ups"] + c["scale_up_aborts"]
            and n_ret_spans == c["retires"])
        scale_events_span_verified = (
            not unpaired and span_counts_agree
            and n_up_spans >= 1 and n_ret_spans >= 1
            and all(s in ("ready", "aborted", "completed", "crashed")
                    for s in statuses))
        if tracer.dropped:
            violations.append(
                f"autoscale-main: tracer displaced {tracer.dropped} "
                "events — span verification is not trustworthy")
    finally:
        tracer.configure(enabled=was, capacity=cap_was)

    trace_inv = _trace_gates(reqs, violations)
    auto_score = score(fleet, reqs)
    if not deterministic:
        violations.append(
            f"autoscale-main: digests diverged across "
            f"{len(digests)} runs")
    if unpaired:
        violations.append(
            f"autoscale-main: unpaired scale spans {unpaired}")
    if not span_counts_agree:
        violations.append(
            f"autoscale-main: scale spans ({n_up_spans} up, "
            f"{n_ret_spans} retire) disagree with counters "
            f"(ups {c['scale_ups']}+{c['scale_up_aborts']} aborted, "
            f"retires {c['retires']})")
    if not scale_events_span_verified:
        violations.append(
            "autoscale-main: scale events not span-verified "
            f"(statuses {statuses})")
    if c["scale_ups"] < 1 or c["retires_completed"] < 1:
        violations.append(
            "autoscale-main: the trace never exercised a full "
            f"scale-up + drain-retirement cycle ({dict(c)})")
    if asc.flaps > asc.config.max_flaps:
        violations.append(
            f"autoscale-main: flap bound {asc.flaps} > "
            f"{asc.config.max_flaps}")
    for step, action, detail in asc.decisions:
        emit({"phase": "autoscale-decision", "step": step,
              "action": action, "detail": detail})
    emit({"phase": "autoscale-main", "seed": seed,
          "n_requests": n_requests, "runs": len(auto_runs),
          "deterministic": deterministic,
          "event_digest": digest,
          "scale_ups": c["scale_ups"],
          "scale_up_aborts": c["scale_up_aborts"],
          "retires": c["retires"],
          "retires_completed": c["retires_completed"],
          "reroles": c["reroles"],
          "prewarm_broadcasts": c["prewarm_broadcasts"],
          "flaps": asc.flaps,
          "replicas_final": len(fleet.replicas),
          "replicas_live": fleet.live_replicas,
          "scale_events_span_verified": scale_events_span_verified,
          "span_statuses": statuses,
          "trace": trace_inv,
          **auto_score})

    # ------------- phase 2: vs static fleets ----------------------- #
    statics = {}
    for n in (start_replicas, peak_replicas):
        sfleet = make_fleet(n)
        sreqs = make_trace()
        sfleet.run_trace(sreqs)
        statics[n] = score(sfleet, sreqs)
        emit({"phase": "autoscale-static", "seed": seed,
              "n_replicas": n, **statics[n]})
    peak = statics[peak_replicas]
    slo_vs_static_ok = (auto_score["slo_attainment"]
                        >= peak["slo_attainment"])
    cost_vs_static_ok = (auto_score["cost_replica_steps"]
                         < peak["cost_replica_steps"])
    savings = 1.0 - (auto_score["cost_replica_steps"]
                     / max(1, peak["cost_replica_steps"]))
    if not slo_vs_static_ok:
        violations.append(
            f"autoscale-static: attainment "
            f"{auto_score['slo_attainment']} < static-{peak_replicas}"
            f" {peak['slo_attainment']}")
    if not cost_vs_static_ok:
        violations.append(
            f"autoscale-static: cost "
            f"{auto_score['cost_replica_steps']} not strictly below "
            f"static-{peak_replicas} {peak['cost_replica_steps']}")

    # ------------- phase 3: scale-event chaos ---------------------- #
    chaos = [run_autoscale_chaos(seed=seed)
             for _ in range(max(1, runs))]
    chaos_det = len({x.event_digest for x in chaos}) == 1
    violations.extend(f"autoscale-chaos: {v}"
                      for x in chaos for v in x.violations)
    if not chaos_det:
        violations.append(
            "autoscale-chaos: digests diverged across runs")
    emit({"phase": "autoscale-chaos", "seed": seed,
          "runs": len(chaos),
          "deterministic": chaos_det,
          "event_digest": chaos[0].event_digest,
          "ok": all(x.ok for x in chaos),
          "fault_fired": chaos[0].invariants["fault_fired"],
          "invariants": chaos[0].invariants})

    # ------------- phase 4: process-mode scale lifecycle ----------- #
    pfleet = ServingFleet(
        engine_factory=make_engine,
        clock=VirtualClock(),
        config=FleetConfig(
            n_replicas=start_replicas,
            server=ServerConfig(max_queue_depth=n_requests + 1,
                                kv_demand_fraction=float("inf")),
            prefix=PrefixReuseConfig(broadcast=True,
                                     min_adopt_tokens=4),
            transport=ProcessTransport()))
    preqs = build_autoscale_trace(seed=seed, n_requests=48,
                                  horizon_s=3.0, new_tokens=(6, 10))
    spawn_kill = FaultPlan(seed=seed, rules=[
        FaultRule("scale.spawn", at_hits=(1,), max_faults=1)])
    with injected(spawn_kill) as inj:
        with pfleet.transport:
            arrivals = sorted(preqs,
                              key=lambda r: (r.arrival_time, r.uid))
            steps = 0
            new_rid = None
            while arrivals or pfleet.has_work:
                now = pfleet.clock.now()
                while arrivals and arrivals[0].arrival_time <= now:
                    pfleet.submit(request=arrivals.pop(0))
                if not pfleet.has_work and arrivals:
                    pfleet.clock.advance_to(arrivals[0].arrival_time)
                    continue
                pfleet.step()
                steps += 1
                if steps == 4:
                    # scale-up mid-trace: first spawn is killed by the
                    # injected fault, the supervisor must retry
                    new_rid = pfleet.add_replica()
                if steps == 12 and new_rid is not None:
                    pfleet.retire_replica(new_rid)
                if steps > 1_000_000:
                    raise RuntimeError("autoscale process livelock:\n"
                                       + pfleet.snapshot())
            pwire = pfleet.transport.wire_stats()
        spawn_fired = dict(inj.fired)
    terminal = {"DONE", "REJECTED", "FAILED"}
    lost = [r.uid for r in preqs if r.state.name not in terminal]
    process_ok = True
    if new_rid is None:
        process_ok = False
        violations.append("autoscale-process: scale-up never ran")
    if spawn_fired.get("scale.spawn", 0) < 1 \
            or pwire["scale_spawn_failures"] < 1:
        process_ok = False
        violations.append(
            "autoscale-process: the mid-scale-up kill never fired "
            f"({spawn_fired}, {pwire['scale_spawn_failures']} spawn "
            "failures)")
    if pwire["scale_spawns"] < 1:
        process_ok = False
        violations.append(
            "autoscale-process: no worker spawned by scale-up")
    if pwire["scale_retired"] < 1:
        process_ok = False
        violations.append(
            "autoscale-process: retired worker never reaped")
    if lost:
        process_ok = False
        violations.append(
            f"autoscale-process: requests lost {lost}")
    if not pfleet.migration_balance_ok or pfleet.in_transit:
        process_ok = False
        violations.append(
            "autoscale-process: migration imbalance "
            f"({dict(pfleet.counters)})")
    emit({"phase": "autoscale-process", "seed": seed,
          "n_requests": len(preqs),
          "new_replica": new_rid,
          "process_ok": process_ok,
          "scale_spawns": pwire["scale_spawns"],
          "scale_spawn_failures": pwire["scale_spawn_failures"],
          "scale_retired": pwire["scale_retired"],
          "io_timeouts": pwire["io_timeouts"],
          "fault_fired": spawn_fired,
          "counters": dict(pfleet.counters)})

    emit({"phase": "autoscale-summary", "seed": seed,
          "n_requests": n_requests, "runs": len(auto_runs),
          "deterministic": deterministic,
          "event_digest": digest,
          "slo_attainment": auto_score["slo_attainment"],
          "cost_replica_steps": auto_score["cost_replica_steps"],
          "static_peak_attainment": peak["slo_attainment"],
          "static_peak_cost": peak["cost_replica_steps"],
          "slo_vs_static_ok": slo_vs_static_ok,
          "cost_vs_static_ok": cost_vs_static_ok,
          "cost_savings_fraction": round(savings, 6),
          "scale_ups": c["scale_ups"],
          "retires_completed": c["retires_completed"],
          "flaps": asc.flaps,
          "scale_events_span_verified": scale_events_span_verified,
          "chaos_deterministic": chaos_det,
          "chaos_invariants_ok": all(x.ok for x in chaos),
          "process_ok": process_ok,
          "trace_connected": trace_inv["connected"],
          "invariants_ok": not violations,
          "violations": violations})

    from ..perf import self_check_rows
    emit(self_check_rows(out or "AUTOSCALE_SERVE.jsonl", results))
    if fh is not None:
        fh.close()
    if violations:
        raise RuntimeError(
            f"autoscale serve gates violated: {violations}")
    return results


def run(model_size="tiny", max_context=512, prompt_len=128,
        decode_steps=64, batches=(1, 4, 8), quantize="",
        prefill_chunk=0, fused=False, lookup=False):
    """ONE engine (sized for the largest batch) serves every measurement:
    engine-per-config both re-casts the weights each time and, at 1B+
    sizes, OOMs the pool while two engines overlap. Rows print as they
    are produced so a crash keeps partial results."""
    results = []
    emit = functools.partial(_emit, results)

    rng = np.random.default_rng(0)
    cfg, eng = _engine(model_size, max_context, max(batches),
                       quantize=quantize, prefill_chunk=prefill_chunk)
    for batch in batches:
        prompts = [list(rng.integers(0, cfg.vocab_size, (prompt_len,)))
                   for _ in range(batch)]
        uids = list(range(batch))

        # warm the prefill program off-clock (at 7B through the tunnel
        # the compile alone is ~20 min; timing it as "prefill" reported
        # 0.4 tok/s for what is a ~ms dispatch), then time the real rate
        warm_uids = [10 ** 7 + u for u in uids]
        eng.put(warm_uids, prompts)
        for u in warm_uids:
            eng.flush(u)
        t0 = time.perf_counter()
        logits, _ = eng.put(uids, prompts)   # returns host arrays (sync)
        prefill_s = time.perf_counter() - t0
        emit({"phase": "prefill", "batch": batch,
              "prompt_len": prompt_len,
              "tokens_per_sec": round(batch * prompt_len / prefill_s, 1)})

        ctx0 = prompt_len + 1
        if lookup:
            # speculative decoding: same greedy stream, fewer dispatches.
            # A repetitive prompt half models the system-prompt/code
            # workloads PLD targets; the random half keeps it honest.
            for u in uids:
                eng.flush(u)
            cyc = [int(x) for x in rng.integers(0, cfg.vocab_size, (4,))]
            spec_prompts = [(cyc * prompt_len)[:prompt_len // 2] +
                            p[:prompt_len - prompt_len // 2]
                            for p in prompts]
            eng.generate_lookup(spec_prompts,
                                max_new_tokens=decode_steps + 1)  # warm
            t0 = time.perf_counter()
            _, stats = eng.generate_lookup(
                spec_prompts, max_new_tokens=decode_steps + 1)
            dt = time.perf_counter() - t0
            emit({"phase": "decode-lookup", "batch": batch,
                  "context": [ctx0, ctx0 + decode_steps],
                  "note": "includes one prefill; repetitive-half prompts",
                  "tokens_per_sec": round(batch * decode_steps / dt, 1),
                  "dispatches": stats["dispatches"],
                  "drafted": stats["drafted"],
                  "accepted": stats["accepted"],
                  "tokens_per_dispatch": round(
                      batch * decode_steps / max(stats["dispatches"], 1),
                      2)})
            # fully fused variant: same workload, one host sync total
            eng.generate_lookup_fused(spec_prompts,
                                      max_new_tokens=decode_steps + 1)
            t0 = time.perf_counter()
            _, fstats = eng.generate_lookup_fused(
                spec_prompts, max_new_tokens=decode_steps + 1)
            dt = time.perf_counter() - t0
            emit({"phase": "decode-lookup-fused", "batch": batch,
                  "context": [ctx0, ctx0 + decode_steps],
                  "note": "includes one prefill; repetitive-half prompts",
                  "tokens_per_sec": round(batch * decode_steps / dt, 1),
                  "device_steps": fstats["dispatches"],
                  "accepted": fstats["accepted"],
                  "tokens_per_device_step": round(
                      batch * decode_steps /
                      max(fstats["dispatches"], 1), 2)})
        elif fused:
            # on-device decode loop: one program for the whole stretch
            for u in uids:
                eng.flush(u)
            # warm with the SAME length: n_steps is a static arg, a
            # different value would recompile inside the timed region
            try:
                eng.generate_fused(prompts, max_new_tokens=decode_steps + 1)
            except Exception as e:  # noqa: BLE001 — XLA OOM surfaces as
                # a backend-specific RuntimeError subclass; at 7B bf16
                # the fused program's stacked-QKV layout copies exceed a
                # 16 GB chip (docs/inference.md). A dead stage loses the
                # whole chip-session slot — fall back to the host-driven
                # loop and say so in the artifact instead.
                if "RESOURCE_EXHAUSTED" not in str(e) \
                        and "Resource" not in type(e).__name__:
                    raise
                detail = (str(e) or type(e).__name__).splitlines()[0]
                emit({"phase": "decode-fused", "batch": batch,
                      "error": "fused decode program OOM; falling back "
                               "to host-driven decode",
                      "detail": detail[:300]})
                # generate_fused flushes its own uids in a finally, so
                # the engine is clean: re-prefill and host-step. The
                # host path spends one extra token on its warm step, so
                # clamp to the context budget (the fused call accepts
                # prompt+steps == max_context exactly).
                fb_steps = min(decode_steps,
                               max_context - prompt_len - 1)
                if fb_steps < 1:
                    # prompt fills the context minus the fused budget's
                    # last token; nothing left for warm + timed steps
                    continue
                logits, _ = eng.put(uids, prompts)
                nxt = [int(np.argmax(l)) for l in logits]
                logits, _ = eng.put(uids, [[t] for t in nxt])
                t0 = time.perf_counter()
                for _ in range(fb_steps):
                    nxt = [int(np.argmax(l)) for l in logits]
                    logits, _ = eng.put(uids, [[t] for t in nxt])
                dt = time.perf_counter() - t0
                emit({"phase": "decode", "batch": batch,
                      "note": "host-driven fallback after fused OOM",
                      "context": [ctx0, ctx0 + fb_steps],
                      "tokens_per_sec": round(batch * fb_steps / dt, 1),
                      "ms_per_step": round(dt / fb_steps * 1000, 2)})
            else:
                t0 = time.perf_counter()
                eng.generate_fused(prompts,
                                   max_new_tokens=decode_steps + 1)
                dt = time.perf_counter() - t0
                emit({"phase": "decode-fused", "batch": batch,
                      "context": [ctx0, ctx0 + decode_steps],
                      "note": "includes one prefill",
                      "tokens_per_sec": round(batch * decode_steps / dt, 1),
                      "ms_per_step": round(dt / decode_steps * 1000, 2)})
        else:
            # warm the decode dispatch, then steady-state loop
            nxt = [int(np.argmax(l)) for l in logits]
            logits, _ = eng.put(uids, [[t] for t in nxt])
            t0 = time.perf_counter()
            for _ in range(decode_steps):
                nxt = [int(np.argmax(l)) for l in logits]
                logits, _ = eng.put(uids, [[t] for t in nxt])
            dt = time.perf_counter() - t0
            emit({"phase": "decode", "batch": batch,
                  "context": [ctx0, ctx0 + decode_steps],
                  "tokens_per_sec": round(batch * decode_steps / dt, 1),
                  "ms_per_step": round(dt / decode_steps * 1000, 2)})
        for u in uids:
            if eng.state.get_sequence(u) is not None:
                eng.flush(u)

    # context scaling: decode step latency must track tokens-in-cache
    # (the paged kernel reads valid blocks only), not max_context
    batch = batches[0]
    for ctx in (max_context // 4, max_context // 2,
                max_context - decode_steps - 1):
        if ctx < 8:
            continue
        prompts = [list(rng.integers(0, cfg.vocab_size, (ctx,)))
                   for _ in range(batch)]
        uids = list(range(batch))
        logits, _ = eng.put(uids, prompts)
        nxt = [int(np.argmax(l)) for l in logits]
        logits, _ = eng.put(uids, [[t] for t in nxt])   # warm decode
        t0 = time.perf_counter()
        for _ in range(decode_steps):
            nxt = [int(np.argmax(l)) for l in logits]
            logits, _ = eng.put(uids, [[t] for t in nxt])
        dt = time.perf_counter() - t0
        emit({"phase": "decode-context-scaling", "batch": batch,
              "context": ctx,
              "ms_per_step": round(dt / decode_steps * 1000, 2)})
        for u in uids:
            eng.flush(u)
    return results


def _main_serve_loop(argv):
    p = argparse.ArgumentParser(
        "hds_serve_bench serve_loop",
        description="continuous-batching serving loop over a Poisson "
                    "trace (the serving/ subsystem end-to-end)")
    p.add_argument("--model", default="tiny",
                   choices=("tiny", "1b", "7b"))
    p.add_argument("--max-context", type=int, default=128)
    p.add_argument("--prompt-len", type=int, default=48)
    p.add_argument("--max-new", type=int, default=24)
    p.add_argument("--rps", type=float, default=50.0)
    p.add_argument("--n-requests", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--num-blocks", type=int, default=10,
                   help="KV pool size; small on purpose so preemption "
                        "cycles occur mid-trace")
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--max-lanes", type=int, default=4,
                   help="max sequences per ragged forward")
    p.add_argument("--virtual-clock", action="store_true",
                   help="replay on the deterministic simulated "
                        "timeline instead of wall time")
    p.add_argument("--chaos", action="store_true",
                   help="chaos mode: seeded fault injection over the "
                        "virtual-clock simulation, invariant + "
                        "determinism gates, CHAOS_SERVE.jsonl artifact")
    p.add_argument("--chaos-runs", type=int, default=2,
                   help="identical-seed replays for the determinism "
                        "gate (chaos/fleet modes)")
    p.add_argument("--fleet", action="store_true",
                   help="fleet mode: N-replica router + latent "
                        "migration under replica crash/hang/partition "
                        "chaos on the shared virtual clock, "
                        "FLEET_SERVE.jsonl artifact")
    p.add_argument("--n-replicas", type=int, default=3,
                   help="engine replicas in fleet mode")
    p.add_argument("--disagg", action="store_true",
                   help="disaggregated mode: N-prefill + M-decode "
                        "tiers with latent-wire handoff vs an "
                        "equal-replica colocated baseline on the "
                        "shared virtual clock, DISAGG_SERVE.jsonl "
                        "artifact")
    p.add_argument("--n-prefill", type=int, default=1,
                   help="prefill-tier replicas in disagg mode")
    p.add_argument("--n-decode", type=int, default=3,
                   help="decode-tier replicas in disagg mode")
    p.add_argument("--request-trace", action="store_true",
                   help="causal-tracing mode: connected cross-replica "
                        "span DAGs + attribution closure + flight-"
                        "recorder determinism over the chaos/fleet/"
                        "disagg legs, REQUEST_TRACE.jsonl artifact")
    p.add_argument("--out", default="SERVE_LOOP.jsonl",
                   help="also append rows to this jsonl file "
                        "('' = stdout only)")
    args = p.parse_args(argv)
    if args.request_trace:
        out = args.out if args.out != "SERVE_LOOP.jsonl" \
            else "REQUEST_TRACE.jsonl"
        run_request_trace(seed=args.seed, runs=args.chaos_runs,
                          out=out)
        return 0
    if args.disagg:
        out = args.out if args.out != "SERVE_LOOP.jsonl" \
            else "DISAGG_SERVE.jsonl"
        run_disagg_serve(seed=args.seed, n_prefill=args.n_prefill,
                         n_decode=args.n_decode,
                         runs=args.chaos_runs, out=out)
        return 0
    if args.fleet:
        out = args.out if args.out != "SERVE_LOOP.jsonl" \
            else "FLEET_SERVE.jsonl"
        run_fleet_serve(seed=args.seed, n_replicas=args.n_replicas,
                        n_requests=args.n_requests,
                        runs=args.chaos_runs, out=out)
        return 0
    if args.chaos:
        out = args.out if args.out != "SERVE_LOOP.jsonl" \
            else "CHAOS_SERVE.jsonl"
        run_chaos_serve(seed=args.seed, n_requests=args.n_requests,
                        runs=args.chaos_runs, out=out)
        return 0
    run_serve_loop(args.model, args.max_context, args.prompt_len,
                   max_new=args.max_new, rps=args.rps,
                   n_requests=args.n_requests, seed=args.seed,
                   num_blocks=args.num_blocks,
                   block_size=args.block_size,
                   max_lanes=args.max_lanes,
                   virtual_clock=args.virtual_clock, out=args.out)
    return 0


def main(argv=None):
    if argv is None:
        import sys
        argv = sys.argv[1:]
    if argv and argv[0] == "serve_loop":
        return _main_serve_loop(argv[1:])
    p = argparse.ArgumentParser("hds_serve_bench")
    p.add_argument("--model", default="tiny", choices=("tiny", "1b", "7b"))
    p.add_argument("--max-context", type=int, default=512)
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--decode-steps", type=int, default=64)
    p.add_argument("--batches", type=int, nargs="+", default=[1, 4, 8])
    p.add_argument("--quantize", default="", choices=("", "int8", "fused"),
                   help="weight-only int8 serving; 'fused' routes through "
                        "the int8-weight Pallas kernel")
    p.add_argument("--prefill-chunk", type=int, default=0,
                   help="Dynamic-SplitFuse chunk size (0 = off)")
    p.add_argument("--prefix-caching", action="store_true",
                   help="sweep with a shared system prefix and prefix "
                        "caching on")
    p.add_argument("--sweep", action="store_true",
                   help="throughput-latency curve under Poisson "
                        "arrivals (FastGen benchmark shape)")
    p.add_argument("--rps", type=float, nargs="+",
                   default=[1.0, 2.0, 4.0],
                   help="offered request rates for --sweep")
    p.add_argument("--max-new", type=int, default=32,
                   help="tokens generated per request in --sweep")
    p.add_argument("--n-requests", type=int, default=16)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--latent-dtype", default="",
                   help="HCache latent capture dtype (e.g. "
                        "float8_e4m3fn halves host-link bytes)")
    p.add_argument("--restore", action="store_true",
                   help="HCache mode: restore_kv vs full-prefill "
                        "time-to-cache-ready")
    p.add_argument("--restore-marginal", action="store_true",
                   help="HCache marginal-cost mode: chained dispatches "
                        "split device replay cost from host-link ship "
                        "cost (for high-latency relays)")
    p.add_argument("--restore-crossover", action="store_true",
                   help="restore-vs-recompute crossover curve across "
                        "prompt lengths + the analytic model's verdicts "
                        "(JSONL artifact)")
    p.add_argument("--prompt-lens", type=int, nargs="+",
                   default=[32, 64, 128, 256],
                   help="prompt lengths for --restore-crossover")
    p.add_argument("--crossover-out", default="RESTORE_CROSSOVER.jsonl",
                   help="JSONL file for --restore-crossover rows "
                        "('' = stdout only)")
    p.add_argument("--fused-decode", action="store_true",
                   help="measure the on-device generate_fused loop "
                        "instead of host-driven per-step decode")
    p.add_argument("--lookup-decode", action="store_true",
                   help="measure prompt-lookup speculative decoding "
                        "(greedy-exact; reports acceptance + "
                        "tokens/dispatch)")
    args = p.parse_args(argv)
    # persistent local compilation cache: a program compiled once on the
    # chip stays runnable across remote-compile-service wedges and
    # process restarts (harmless no-op if the PJRT client can't
    # serialize executables)
    import jax

    from .. import default_compile_cache_dir
    jax.config.update("jax_compilation_cache_dir",
                      default_compile_cache_dir())
    # rows print as produced (partial results survive an OOM/crash)
    if args.sweep and args.fused_decode:
        if args.prefix_caching:
            raise SystemExit("--prefix-caching requires the host-driven "
                             "sweep (fused waves reserve whole stretches)")
        run_sweep_fused(args.model, args.max_context, args.prompt_len,
                        max_new=args.max_new, rates=tuple(args.rps),
                        n_requests=args.n_requests,
                        max_batch=args.max_batch, quantize=args.quantize,
                        prefill_chunk=args.prefill_chunk)
    elif args.sweep:
        run_sweep(args.model, args.max_context, args.prompt_len,
                  max_new=args.max_new, rates=tuple(args.rps),
                  n_requests=args.n_requests, max_batch=args.max_batch,
                  quantize=args.quantize,
                  prefill_chunk=args.prefill_chunk,
                  prefix_caching=args.prefix_caching)
    elif args.restore_crossover:
        run_restore_crossover(args.model, args.max_context,
                              tuple(args.prompt_lens),
                              batch=min(args.batches),
                              quantize=args.quantize,
                              latent_dtype=args.latent_dtype,
                              out=args.crossover_out)
    elif args.restore_marginal:
        run_restore_marginal(args.model, args.max_context,
                             args.prompt_len, tuple(args.batches),
                             quantize=args.quantize,
                             latent_dtype=args.latent_dtype)
    elif args.restore:
        run_restore(args.model, args.max_context, args.prompt_len,
                    tuple(args.batches), quantize=args.quantize,
                    prefill_chunk=args.prefill_chunk,
                    latent_dtype=args.latent_dtype)
    else:
        run(args.model, args.max_context, args.prompt_len,
            args.decode_steps, tuple(args.batches),
            quantize=args.quantize, prefill_chunk=args.prefill_chunk,
            fused=args.fused_decode, lookup=args.lookup_decode)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
