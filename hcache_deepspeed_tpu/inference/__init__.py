"""Inference stack (reference: ``deepspeed/inference/v2/``) — ragged
batching over a paged KV cache + the fork's HCache restore path."""

from .config import (HCacheConfig, KVCacheConfig,  # noqa: F401
                     RaggedInferenceEngineConfig, StateManagerConfig)
from .engine_v2 import InferenceEngineV2  # noqa: F401
from .factory import build_engine, build_hf_engine  # noqa: F401
from .scheduling import SchedulingError, SchedulingResult  # noqa: F401
