"""Paged-KV serving for the OPT family.

Reference analog: the opt policy in
``deepspeed/inference/v2/engine_factory.py:69`` +
``model_implementations/opt/`` (and v1's
``module_inject/containers/opt.py``). Reuses the GPT-2 paged trunk
(LayerNorm + learned positions, no RoPE); OPT differs in separate
biased q/k/v projections, a ReLU fc1/fc2 MLP, and the +2 position
offset — the offset is baked in by slicing the first two rows off the
position table at load time, so the trunk's ``wpe[positions]`` lookup
stays untouched.

Consumes ``models.opt.OPTForCausalLM`` training params directly.
"""

import jax
import jax.numpy as jnp

from ..models.opt import POSITION_OFFSET, OPTConfig
from .model import stack_layer_params
from .model_gpt2 import PagedGPT2Model


class PagedOPTModel(PagedGPT2Model):
    def __init__(self, cfg: OPTConfig, params, **kw):
        if not isinstance(cfg, OPTConfig):
            raise TypeError("PagedOPTModel needs an OPTConfig")
        super().__init__(cfg, params, **kw)

    def load_params(self, params):
        """Map HF-layout OPT names onto the gpt2 serving layout where the
        semantics coincide (ln_1 := self_attn_layer_norm, ln_2 :=
        per-layer final_layer_norm); attention/MLP weights keep their
        OPT names and are consumed by the overridden hooks below."""
        from .model import maybe_quantize_serving_params
        layers = stack_layer_params(params, self.cfg.n_layer,
                                    prefix="layers_")
        self.params = maybe_quantize_serving_params({
            "wte": params["embed_tokens"]["embedding"],
            # slice the reserved rows: trunk positions index from 0
            "wpe": params["embed_positions"]["embedding"][POSITION_OFFSET:],
            "ln_f": {k: params["final_layer_norm"][k]
                     for k in ("scale", "bias")},
            "layers": {
                "ln_1": layers["self_attn_layer_norm"],
                "ln_2": layers["final_layer_norm"],
                "attn": layers["self_attn"],
                "mlp": {"fc1": layers["fc1"], "fc2": layers["fc2"]},
            },
        }, self.quantization)

    def _qkv(self, lp, h):
        cfg = self.cfg
        B, T, C = h.shape
        H, D = cfg.n_head, cfg.head_dim
        a = lp["attn"]
        q = h @ a["q_proj"]["kernel"] + a["q_proj"]["bias"]
        k = h @ a["k_proj"]["kernel"] + a["k_proj"]["bias"]
        v = h @ a["v_proj"]["kernel"] + a["v_proj"]["bias"]
        return (q.reshape(B, T, H, D), k.reshape(B, T, H, D),
                v.reshape(B, T, H, D))

    def _attn_proj(self, lp, attn):
        o = lp["attn"]["out_proj"]
        return attn @ o["kernel"] + o["bias"]

    def _mlp_out(self, lp, h2):
        m = lp["mlp"]
        ff = jax.nn.relu(h2 @ m["fc1"]["kernel"] + m["fc1"]["bias"])
        return ff @ m["fc2"]["kernel"] + m["fc2"]["bias"]
