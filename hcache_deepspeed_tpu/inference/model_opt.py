"""Paged-KV serving for the OPT family.

Reference analog: the opt policy in
``deepspeed/inference/v2/engine_factory.py:69`` +
``model_implementations/opt/`` (and v1's
``module_inject/containers/opt.py``). Reuses the GPT-2 paged trunk
(LayerNorm + learned positions, no RoPE, bias-after-psum TP); OPT
differs in separate (already-unfused) q/k/v projections, a ReLU
fc1/fc2 MLP, and the +2 position offset — baked in by slicing the
first two rows off the position table at load time.

Consumes ``models.opt.OPTForCausalLM`` training params directly.
"""

import jax

from ..models.opt import POSITION_OFFSET, OPTConfig
from .model import stack_layer_params
from .model_gpt2 import PagedGPT2Model


class PagedOPTModel(PagedGPT2Model):
    _COL_NAMES = ("q_proj", "k_proj", "v_proj", "fc1")
    _ROW_NAMES = ("out_proj", "fc2")
    _ROW_BIAS_OK = True

    def __init__(self, cfg: OPTConfig, params, **kw):
        if not isinstance(cfg, OPTConfig):
            raise TypeError("PagedOPTModel needs an OPTConfig")
        # skip PagedGPT2Model's GPT2Config check
        super(PagedGPT2Model, self).__init__(cfg, params, **kw)

    def _validate_tp(self):
        cfg, tp = self.cfg, self.tp
        for name, val in (("n_head", cfg.n_head),
                          ("ffn_dim", cfg.ffn_dim),
                          ("vocab_size", cfg.vocab_size)):
            if val % tp:
                raise ValueError(f"{name}={val} not divisible by "
                                 f"tensor parallel degree {tp}")

    def load_params(self, params):
        """HF-layout OPT names onto the gpt2 serving layout (ln_1 :=
        self_attn_layer_norm, ln_2 := per-layer final_layer_norm; the
        attention projections are already separate)."""
        layers = stack_layer_params(params, self.cfg.n_layer,
                                    prefix="layers_")
        new = {
            "embed": params["embed_tokens"]["embedding"],
            # slice the reserved rows: trunk positions index from 0
            "wpe": params["embed_positions"]["embedding"][POSITION_OFFSET:],
            "norm": {k: params["final_layer_norm"][k]
                     for k in ("scale", "bias")},
            "layers": {
                "ln_1": layers["self_attn_layer_norm"],
                "ln_2": layers["final_layer_norm"],
                "attn": layers["self_attn"],
                "mlp": {"fc1": layers["fc1"], "fc2": layers["fc2"]},
            },
        }
        self.params = self._finalize_params(new)

    def _attn_out_parts(self, lp, attn):
        p = lp["attn"]["out_proj"]
        return self._mm(attn, p["kernel"]), p["bias"]

    def _mlp_out_parts(self, lp, h2):
        m = lp["mlp"]
        ff = jax.nn.relu(self._mm(h2, m["fc1"]["kernel"]) +
                         m["fc1"]["bias"])
        return self._mm(ff, m["fc2"]["kernel"]), m["fc2"]["bias"]
