"""Scheduling results for the ragged inference engine.

Reference analog: ``deepspeed/inference/v2/scheduling_utils.py`` —
``SchedulingResult`` / ``SchedulingError`` returned by
``InferenceEngineV2.can_schedule`` (engine_v2.py:217-264).
"""

from enum import Enum


class SchedulingResult(Enum):
    Success = 0
    EngineSequenceLimitExceeded = 1
    BatchSequenceLimitExceeded = 2
    BatchTokenLimitExceeded = 3
    KVCacheLimitExceeded = 4
    SequenceTokenLimitExceeded = 5


class SchedulingError(RuntimeError):
    def __init__(self, result: SchedulingResult) -> None:
        self.result = result
        super().__init__(f"Batch scheduling failed with result {result}")
