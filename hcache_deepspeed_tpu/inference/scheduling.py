"""Scheduling results for the ragged inference engine.

Reference analog: ``deepspeed/inference/v2/scheduling_utils.py`` —
``SchedulingResult`` / ``SchedulingError`` returned by
``InferenceEngineV2.can_schedule`` (engine_v2.py:217-264).

The backpressure mapping below is consumed by the continuous-batching
scheduler (``serving/scheduler.py``): every non-Success verdict names
the ONE corrective action that can actually clear it, so the serving
loop never retries a permanent failure or rejects a transient one.
"""

from enum import Enum


class SchedulingResult(Enum):
    Success = 0
    EngineSequenceLimitExceeded = 1
    BatchSequenceLimitExceeded = 2
    BatchTokenLimitExceeded = 3
    KVCacheLimitExceeded = 4
    SequenceTokenLimitExceeded = 5


class BackpressureAction(Enum):
    """What a serving scheduler should do about one can_schedule verdict.

    Each rejection maps to a distinct action because each names a
    different exhausted resource with a different release schedule:
    """
    #: Success — admit the request into this step's ragged batch.
    ADMIT = 0
    #: EngineSequenceLimitExceeded — every tracked-sequence slot is
    #: held; slots free when a sequence finishes (or, in latent-preempt
    #: mode, is evicted wholesale), so the request waits in queue.
    WAIT_TRACKED_SLOT = 1
    #: BatchSequenceLimitExceeded — THIS forward's lane budget is full;
    #: nothing is wrong with the request, stop admitting and retry at
    #: the next step.
    NEXT_STEP = 2
    #: BatchTokenLimitExceeded — this candidate's prompt overflows the
    #: per-forward token budget; a shorter queued prompt may still fit,
    #: so skip the candidate but keep scanning the queue.
    SKIP_CANDIDATE = 3
    #: KVCacheLimitExceeded — block-pool pressure; the scheduler can
    #: manufacture free blocks by suspending victims to host.
    PREEMPT = 4
    #: SequenceTokenLimitExceeded — prompt + generation exceeds
    #: max_context; no amount of waiting or preemption fixes it.
    REJECT = 5


#: SchedulingResult -> the distinct backpressure action that clears it.
BACKPRESSURE_ACTION = {
    SchedulingResult.Success: BackpressureAction.ADMIT,
    SchedulingResult.EngineSequenceLimitExceeded:
        BackpressureAction.WAIT_TRACKED_SLOT,
    SchedulingResult.BatchSequenceLimitExceeded:
        BackpressureAction.NEXT_STEP,
    SchedulingResult.BatchTokenLimitExceeded:
        BackpressureAction.SKIP_CANDIDATE,
    SchedulingResult.KVCacheLimitExceeded: BackpressureAction.PREEMPT,
    SchedulingResult.SequenceTokenLimitExceeded:
        BackpressureAction.REJECT,
}


class SchedulingError(RuntimeError):
    def __init__(self, result: SchedulingResult) -> None:
        self.result = result
        super().__init__(f"Batch scheduling failed with result {result}")
