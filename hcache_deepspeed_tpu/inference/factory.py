"""Engine factory.

Reference analog: ``deepspeed/inference/v2/engine_factory.py:69
build_hf_engine`` — maps a model family name/config to its policy (llama,
mistral, mixtral, opt, falcon, phi, qwen...). Here the family table maps to
our training-model configs whose param trees the paged inference model
consumes directly.
"""

from typing import Any, Dict, Optional

from ..models.llama import LlamaConfig
from .config import RaggedInferenceEngineConfig
from .engine_v2 import InferenceEngineV2


def _llama_like(hf: Dict[str, Any]) -> LlamaConfig:
    # HF Qwen2 carries q/k/v biases; its config spells llama-style keys.
    # (Qwen-v1 does NOT map here — it uses seq_length/layer_norm_epsilon
    # and a fused c_attn, so mapping it would mis-read the config.)
    bias_default = hf.get("model_type") == "qwen2"
    return LlamaConfig(
        attention_bias=hf.get("attention_bias",
                              hf.get("qkv_bias", bias_default)),
        vocab_size=hf.get("vocab_size", 32000),
        hidden_size=hf.get("hidden_size", 4096),
        intermediate_size=hf.get("intermediate_size", 11008),
        n_layer=hf.get("num_hidden_layers", 32),
        n_head=hf.get("num_attention_heads", 32),
        n_kv_head=hf.get("num_key_value_heads",
                         hf.get("num_attention_heads", 32)),
        max_positions=hf.get("max_position_embeddings", 4096),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
        rope_theta=hf.get("rope_theta", 10000.0),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
        dtype=hf.get("torch_dtype") or "bfloat16",
    )


def _gpt2_like(hf: Dict[str, Any]):
    from ..models.gpt2 import GPT2Config
    return GPT2Config(
        vocab_size=hf.get("vocab_size", 50257),
        n_positions=hf.get("n_positions", hf.get("n_ctx", 1024)),
        n_embd=hf.get("n_embd", 768),
        n_layer=hf.get("n_layer", 12),
        n_head=hf.get("n_head", 12),
        layer_norm_epsilon=hf.get("layer_norm_epsilon", 1e-5),
        dtype=hf.get("torch_dtype") or "float32",
    )


def _opt_like(hf: Dict[str, Any]):
    from ..models.opt import OPTConfig
    return OPTConfig(
        vocab_size=hf.get("vocab_size", 50272),
        hidden_size=hf.get("hidden_size", 768),
        ffn_dim=hf.get("ffn_dim", 3072),
        n_layer=hf.get("num_hidden_layers", 12),
        n_head=hf.get("num_attention_heads", 12),
        max_positions=hf.get("max_position_embeddings", 2048),
        dtype=hf.get("torch_dtype") or "float32",
    )


def _falcon_like(hf: Dict[str, Any]):
    from ..models.falcon import FalconConfig
    n_head = hf.get("num_attention_heads", hf.get("n_head", 71))
    if hf.get("new_decoder_architecture", False):
        kv = hf.get("num_kv_heads", 8)
    else:
        kv = n_head if not hf.get("multi_query", True) else 1
    return FalconConfig(
        vocab_size=hf.get("vocab_size", 65024),
        hidden_size=hf.get("hidden_size", 4544),
        n_layer=hf.get("num_hidden_layers", hf.get("n_layer", 32)),
        n_head=n_head,
        n_kv_head=kv,
        max_positions=hf.get("max_position_embeddings", 2048),
        layer_norm_epsilon=hf.get("layer_norm_epsilon", 1e-5),
        rope_theta=hf.get("rope_theta", 10000.0),
        tie_word_embeddings=hf.get("tie_word_embeddings", True),
        dtype=hf.get("torch_dtype") or "bfloat16",
    )


def _phi_like(hf: Dict[str, Any]):
    from ..models.phi import PhiConfig
    return PhiConfig(
        vocab_size=hf.get("vocab_size", 51200),
        hidden_size=hf.get("hidden_size", 2560),
        intermediate_size=hf.get("intermediate_size", 10240),
        n_layer=hf.get("num_hidden_layers", 32),
        n_head=hf.get("num_attention_heads", 32),
        max_positions=hf.get("max_position_embeddings", 2048),
        layer_norm_epsilon=hf.get("layer_norm_eps", 1e-5),
        rope_theta=hf.get("rope_theta", 10000.0),
        partial_rotary_factor=hf.get("partial_rotary_factor", 0.4),
        dtype=hf.get("torch_dtype") or "float32",
    )


def _mixtral_like(hf: Dict[str, Any]):
    from ..models.mixtral import MixtralConfig
    return MixtralConfig(
        vocab_size=hf.get("vocab_size", 32000),
        hidden_size=hf.get("hidden_size", 4096),
        intermediate_size=hf.get("intermediate_size", 14336),
        n_layer=hf.get("num_hidden_layers", 32),
        n_head=hf.get("num_attention_heads", 32),
        n_kv_head=hf.get("num_key_value_heads", 8),
        max_positions=hf.get("max_position_embeddings", 8192),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
        rope_theta=hf.get("rope_theta", 1e6),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
        num_experts=hf.get("num_local_experts", hf.get("num_experts", 8)),
        top_k=hf.get("num_experts_per_tok", 2),
        dtype=hf.get("torch_dtype") or "bfloat16",
    )


def _qwen_v1_like(hf: Dict[str, Any]) -> LlamaConfig:
    """Qwen (v1) spells its config in its own keys — ``seq_length`` for the
    context window, ``layer_norm_epsilon`` for the RMSNorm eps, an
    ``intermediate_size`` that is TWICE the SwiGLU branch width (the HF
    module builds w1/w2 at intermediate_size // 2), qkv bias always on, and
    ``rotary_emb_base``. Architecturally it is the llama block layout
    (RMSNorm + rope + SwiGLU, MHA, untied head), so it maps onto our llama
    trunk once those keys are translated."""
    return LlamaConfig(
        attention_bias=True,
        vocab_size=hf.get("vocab_size", 151936),
        hidden_size=hf.get("hidden_size", 4096),
        intermediate_size=hf.get("intermediate_size", 22016) // 2,
        n_layer=hf.get("num_hidden_layers", 32),
        n_head=hf.get("num_attention_heads", 32),
        n_kv_head=hf.get("num_attention_heads", 32),  # MHA: no GQA in v1
        max_positions=hf.get("seq_length", 8192),
        rms_norm_eps=hf.get("layer_norm_epsilon", 1e-6),
        rope_theta=hf.get("rotary_emb_base", 10000.0),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
        dtype=hf.get("torch_dtype") or "bfloat16",
    )


def _qwen2_moe_like(hf: Dict[str, Any]):
    from ..models.mixtral import Qwen2MoeConfig
    return Qwen2MoeConfig(
        vocab_size=hf.get("vocab_size", 151936),
        hidden_size=hf.get("hidden_size", 3584),
        # expert FFN width is moe_intermediate_size (the dense
        # intermediate_size key refers to layers qwen2-moe doesn't use)
        intermediate_size=hf.get("moe_intermediate_size", 2560),
        shared_expert_intermediate_size=hf.get(
            "shared_expert_intermediate_size", 20480),
        n_layer=hf.get("num_hidden_layers", 28),
        n_head=hf.get("num_attention_heads", 28),
        n_kv_head=hf.get("num_key_value_heads", 4),
        max_positions=hf.get("max_position_embeddings", 32768),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
        rope_theta=hf.get("rope_theta", 1e6),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
        num_experts=hf.get("num_experts", 64),
        top_k=hf.get("num_experts_per_tok", 8),
        norm_topk_prob=hf.get("norm_topk_prob", False),
        attention_bias=hf.get("attention_bias",
                              hf.get("qkv_bias", True)),
        dtype=hf.get("torch_dtype") or "bfloat16",
    )


#: model_type -> config adapter (reference: the policy map in
#: engine_factory.py:69 — llama/mistral/qwen2/phi3 share the llama block
#: layout; mixtral/qwen2_moe route through the MoE paged model
#: (model_moe.py: dropless grouped GEMM, and for qwen2_moe the shared
#: expert + raw top-k gate mass); gpt2/opt/falcon/phi have their own
#: paged trunks; qwen (v1) translates its idiosyncratic config keys
#: onto the llama trunk (_qwen_v1_like).
MODEL_FAMILIES = {
    "llama": _llama_like,
    "mistral": _llama_like,
    "qwen": _qwen_v1_like,
    "qwen2": _llama_like,
    "phi3": _llama_like,
    "gpt2": _gpt2_like,
    "opt": _opt_like,
    "falcon": _falcon_like,
    "phi": _phi_like,
    "mixtral": _mixtral_like,
    "qwen2_moe": _qwen2_moe_like,
}


def build_engine(model=None, config=None, *, model_config=None, params=None,
                 engine_config: Optional[RaggedInferenceEngineConfig] = None,
                 **kw) -> InferenceEngineV2:
    """``hcache_deepspeed_tpu.init_inference`` backend. Accepts either a
    ready ``(model_config, params)`` pair or an HF-style config dict via
    ``model``."""
    if engine_config is None and isinstance(config, dict):
        engine_config = RaggedInferenceEngineConfig(**config)
    if model_config is None:
        from ..models.falcon import FalconConfig
        from ..models.gpt2 import GPT2Config
        from ..models.opt import OPTConfig
        from ..models.phi import PhiConfig
        if isinstance(model, (LlamaConfig, GPT2Config, OPTConfig,
                              FalconConfig, PhiConfig)):
            model_config = model
        elif isinstance(model, dict):
            family = model.get("model_type", "llama")
            if family not in MODEL_FAMILIES:
                raise ValueError(
                    f"unsupported model family {family!r}; known: "
                    f"{sorted(MODEL_FAMILIES)}")
            model_config = MODEL_FAMILIES[family](model)
        else:
            raise TypeError("build_engine needs model_config+params, a "
                            "model-family config (LlamaConfig/GPT2Config/"
                            "OPTConfig/FalconConfig/PhiConfig/"
                            "MixtralConfig), or an HF config dict")
    if params is None:
        raise ValueError("build_engine requires params (a trained "
                         "LlamaForCausalLM param tree)")
    return InferenceEngineV2(model_config, params,
                             config=engine_config)


def build_hf_engine(hf_config: Dict[str, Any], params,
                    engine_config=None) -> InferenceEngineV2:
    return build_engine(model=hf_config, params=params,
                        engine_config=engine_config)
