"""Paged-KV inference model for the Llama/GPT family.

Reference analogs:
* ``deepspeed/inference/v2/model_implementations/llama_v2/model.py`` —
  per-layer forward producing logits **and latents** (:203-220, the HCache
  fork delta) and ``restore_kv`` (:222-252),
* ``deepspeed/inference/v2/modules/implementations/attention/
  dense_blocked_attention.py`` — blocked flash attention + the
  cache-write-only ``restore_kv`` hook (:182),
* the ragged kernel set (``kernels/ragged_ops/``): here each of
  atom-builder/blocked-flash/kv-rotary collapses into a single jitted
  gather/scatter + attention program.

TPU-native design
-----------------
One compiled function family, bucketed on static shapes:

``forward_chunk(params, cache, tokens[B,T], start[B], tables[B,NB], len[B])``
    processes T new tokens for each of B sequences against the paged cache
    (T=1 ⇒ ragged decode batch; B=1, T=bucket ⇒ prefill, including chunked
    continuation since ``start`` offsets positions). Writes KV via one flat
    scatter (invalid lanes dropped), reads via one flat gather per layer,
    layers run under ``lax.scan`` over stacked params with the cache
    threaded as scan xs/ys so XLA updates it in place (donated).

``restore_layer(layer_params, latents[B,T,H], ...)``
    the HCache delta: replay ONLY the K/V projection + RoPE + cache write
    from saved latents — one layer per dispatch so the engine can overlap
    host→HBM latent copies with compute (the reference's dual-stream
    io_stream/compute pattern, engine-side).

Latents = post-input_layernorm hidden states (the exact tensor the
reference snapshots at llama_v2/model.py:211).
"""

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..models.llama import LlamaConfig
from ..ops.paged_attention import paged_attention
from ..ops.rms_norm import rms_norm
from ..ops.rope import apply_rope, rope_frequencies


def stack_layer_params(params: Dict[str, Any], n_layers: int,
                       prefix: str = "layers_"):
    """[per-layer dicts] -> one pytree with leading layer dim (scan xs)."""
    layers = [params[f"{prefix}{i}"] for i in range(n_layers)]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layers)


class PagedInferenceModel:
    """Functional paged-attention transformer consuming *training* params
    from ``models.llama.LlamaForCausalLM`` (same names/shapes — a trained
    checkpoint drops in directly, the analog of the reference's checkpoint
    loading into inference containers)."""

    def __init__(self, cfg: LlamaConfig, params, *, block_size: int,
                 max_blocks_per_seq: int, capture_latents: bool = True):
        self.cfg = cfg
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.capture_latents = capture_latents
        self.n_layers = cfg.n_layer

        self.embed = params["embed_tokens"]["embedding"]
        self.norm_w = params["norm"]["weight"]
        if cfg.tie_word_embeddings:
            self.lm_head = self.embed.T
        else:
            self.lm_head = params["lm_head"]["kernel"]
        self.layer_params = stack_layer_params(params, cfg.n_layer)
        self.cos, self.sin = rope_frequencies(cfg.head_dim,
                                              cfg.max_positions,
                                              cfg.rope_theta)
        self._fwd = jax.jit(self._forward_chunk, donate_argnums=(0, 1))
        self._restore = jax.jit(self._restore_layer, donate_argnums=(0, 1))

    # -------------------------------------------------------------- #
    # Layer math (mirrors models/llama.py LlamaBlock exactly)
    # -------------------------------------------------------------- #
    def _qkv(self, lp, h, positions):
        """h: [B, T, H]; returns q [B,T,Hq,D], k/v [B,T,KV,D] (roped)."""
        cfg = self.cfg
        B, T, _ = h.shape
        q = (h @ lp["self_attn"]["q_proj"]["kernel"]).reshape(
            B, T, cfg.n_head, cfg.head_dim)
        k = (h @ lp["self_attn"]["k_proj"]["kernel"]).reshape(
            B, T, cfg.n_kv_head, cfg.head_dim)
        v = (h @ lp["self_attn"]["v_proj"]["kernel"]).reshape(
            B, T, cfg.n_kv_head, cfg.head_dim)
        q = apply_rope(q, self.cos, self.sin, positions)
        k = apply_rope(k, self.cos, self.sin, positions)
        return q, k, v

    def _scatter_kv(self, ck, cv, k, v, flat_idx):
        """ck/cv: [P, KV, D]; k/v: [B, T, KV, D]; flat_idx: [B, T] (OOB ⇒
        dropped — padded lanes use an index past the pool end)."""
        kv_shape = (-1,) + k.shape[2:]
        ck = ck.at[flat_idx.reshape(-1)].set(
            k.reshape(kv_shape).astype(ck.dtype), mode="drop")
        cv = cv.at[flat_idx.reshape(-1)].set(
            v.reshape(kv_shape).astype(cv.dtype), mode="drop")
        return ck, cv

    def _paged_attention(self, q, ck, cv, tables, q_positions, kv_len):
        """q: [B, T, Hq, D]; ck/cv: [P, KV, D]; tables: [B, NB];
        q_positions: [B, T] absolute; kv_len: [B] valid cache length.
        Returns [B, T, Hq*D].

        Dispatches to the Pallas ragged paged-attention kernel
        (``ops/paged_attention.py`` — the blocked_flash analog): block-
        table-indexed flash over valid blocks only, no dense [B, S_max]
        gather, no GQA repeat."""
        B, T, Hq, D = q.shape
        start = q_positions[:, 0]  # chunk rows are consecutive positions
        out = paged_attention(q, ck, cv, tables, start, kv_len,
                              self.block_size)
        return out.reshape(B, T, Hq * D)

    def _layer_step(self, x, lp, ck, cv, tables, positions, flat_idx,
                    kv_len):
        cfg = self.cfg
        # fp32 norm weights promote under standard dtype rules — pin the
        # residual stream to the compute dtype
        h = rms_norm(x, lp["input_layernorm"]["weight"],
                     eps=cfg.rms_norm_eps).astype(cfg.compute_dtype)
        latent = h if self.capture_latents else jnp.zeros(
            (x.shape[0], x.shape[1], 0), h.dtype)
        q, k, v = self._qkv(lp, h, positions)
        ck, cv = self._scatter_kv(ck, cv, k, v, flat_idx)
        attn = self._paged_attention(q, ck, cv, tables, positions, kv_len)
        x = x + attn @ lp["self_attn"]["o_proj"]["kernel"]
        h2 = rms_norm(x, lp["post_attention_layernorm"]["weight"],
                      eps=cfg.rms_norm_eps).astype(cfg.compute_dtype)
        gate = h2 @ lp["mlp"]["gate_proj"]["kernel"]
        up = h2 @ lp["mlp"]["up_proj"]["kernel"]
        x = x + (jax.nn.silu(gate) * up) @ lp["mlp"]["down_proj"]["kernel"]
        return x.astype(cfg.compute_dtype), ck, cv, latent

    # -------------------------------------------------------------- #
    # forward_chunk: the one compiled family (prefill & ragged decode)
    # -------------------------------------------------------------- #
    def _forward_chunk(self, cache_k, cache_v, tokens, start,
                       tables, t_len):
        """tokens: [B, T] int32; start: [B] first absolute position;
        tables: [B, NB]; t_len: [B] valid new tokens (≤ T).
        Returns (cache_k', cache_v', logits [B, V], latents [L, B, T, H])."""
        B, T = tokens.shape
        BS = self.block_size
        P = cache_k.shape[1]
        x = self.embed[tokens].astype(self.cfg.compute_dtype)

        offs = jnp.arange(T)
        positions = start[:, None] + offs[None, :]              # [B, T]
        token_valid = offs[None, :] < t_len[:, None]
        local_blk = positions // BS                             # in-table idx
        flat_idx = tables[jnp.arange(B)[:, None], local_blk] * BS + \
            positions % BS
        flat_idx = jnp.where(token_valid, flat_idx, P)          # drop pads
        kv_len = start + t_len

        def step(x, xs):
            lp, ck, cv = xs
            x, ck, cv, latent = self._layer_step(
                x, lp, ck, cv, tables, positions, flat_idx, kv_len)
            return x, (ck, cv, latent)

        x, (cache_k, cache_v, latents) = jax.lax.scan(
            step, x, (self.layer_params, cache_k, cache_v))

        x = rms_norm(x, self.norm_w, eps=self.cfg.rms_norm_eps)
        last = jnp.take_along_axis(
            x, jnp.maximum(t_len - 1, 0)[:, None, None], axis=1)[:, 0]
        logits = (last @ self.lm_head).astype(jnp.float32)
        return cache_k, cache_v, logits, latents

    def forward_chunk(self, cache, tokens, start, tables, t_len):
        ck, cv, logits, latents = self._fwd(
            cache.k, cache.v, jnp.asarray(tokens, jnp.int32),
            jnp.asarray(start, jnp.int32), jnp.asarray(tables, jnp.int32),
            jnp.asarray(t_len, jnp.int32))
        cache.replace(ck, cv)
        return logits, latents

    # -------------------------------------------------------------- #
    # HCache restore (the fork's flagship delta)
    # -------------------------------------------------------------- #
    def _restore_layer(self, cache_k, cache_v, layer, latent, start,
                       tables, t_len):
        """Replay K/V projection + RoPE + blocked cache write for ONE layer
        from saved latents (reference: llama_v2/model.py:222-252 +
        dense_blocked_attention.py:182 — QKV GEMM + kv-rotary cache write,
        no attention, no MLP). The full cache is donated, so each dispatch
        updates layer ``layer`` in place; the layer's weights are sliced
        from the stacked tree *inside* the compiled program (no per-call
        host-side slicing)."""
        lp = jax.tree.map(lambda p: p[layer], self.layer_params)
        B, T, _ = latent.shape
        BS = self.block_size
        P = cache_k.shape[1]
        offs = jnp.arange(T)
        positions = start[:, None] + offs[None, :]
        token_valid = offs[None, :] < t_len[:, None]
        local_blk = positions // BS
        flat_idx = tables[jnp.arange(B)[:, None], local_blk] * BS + \
            positions % BS
        flat_idx = jnp.where(token_valid, flat_idx, P).reshape(-1)
        _, k, v = self._qkv(lp, latent.astype(self.cfg.compute_dtype),
                            positions)
        kv_shape = (-1,) + k.shape[2:]
        cache_k = cache_k.at[layer, flat_idx].set(
            k.reshape(kv_shape).astype(cache_k.dtype), mode="drop")
        cache_v = cache_v.at[layer, flat_idx].set(
            v.reshape(kv_shape).astype(cache_v.dtype), mode="drop")
        return cache_k, cache_v

    def restore_kv(self, cache, latents, start, tables, t_len):
        """latents: host array [L, B, T, H] (numpy). Per-layer dispatch with
        the next layer's host→HBM copy issued before this layer's compute —
        JAX's async dispatch gives the reference's dual-stream overlap
        (io_stream copy / compute wait-event chain, llama_v2/model.py:229)."""
        start = jnp.asarray(start, jnp.int32)
        tables = jnp.asarray(tables, jnp.int32)
        t_len = jnp.asarray(t_len, jnp.int32)
        ck, cv = cache.k, cache.v
        dev = list(ck.devices())[0]
        buf = jax.device_put(np.asarray(latents[0]), dev)  # layer-0 H2D
        for l in range(self.n_layers):
            cur = buf
            if l + 1 < self.n_layers:  # double buffer: prefetch next layer
                buf = jax.device_put(np.asarray(latents[l + 1]), dev)
            ck, cv = self._restore(ck, cv, jnp.int32(l), cur, start,
                                   tables, t_len)
        cache.replace(ck, cv)
