"""Paged-KV inference model for the Llama/GPT family.

Reference analogs:
* ``deepspeed/inference/v2/model_implementations/llama_v2/model.py`` —
  per-layer forward producing logits **and latents** (:203-220, the HCache
  fork delta) and ``restore_kv`` (:222-252),
* ``deepspeed/inference/v2/modules/implementations/attention/
  dense_blocked_attention.py`` — blocked flash attention + the
  cache-write-only ``restore_kv`` hook (:182),
* the ragged kernel set (``kernels/ragged_ops/``): here each of
  atom-builder/blocked-flash/kv-rotary collapses into a single jitted
  gather/scatter + attention program.

TPU-native design
-----------------
One compiled function family, bucketed on static shapes:

``forward_chunk(params, cache, tokens[B,T], start[B], tables[B,NB], len[B])``
    processes T new tokens for each of B sequences against the paged cache
    (T=1 ⇒ ragged decode batch; B=1, T=bucket ⇒ prefill, including chunked
    continuation since ``start`` offsets positions). Writes KV via one flat
    scatter (invalid lanes dropped), reads via one flat gather per layer,
    layers run under ``lax.scan`` over stacked params with the cache
    threaded as scan xs/ys so XLA updates it in place (donated).

``restore_layer(layer_params, latents[B,T,H], ...)``
    the HCache delta: replay ONLY the K/V projection + RoPE + cache write
    from saved latents — one layer per dispatch so the engine can overlap
    host→HBM latent copies with compute (the reference's dual-stream
    io_stream/compute pattern, engine-side).

Latents = post-input_layernorm hidden states (the exact tensor the
reference snapshots at llama_v2/model.py:211).
"""

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..models.llama import LlamaConfig
from ..ops.paged_attention import paged_attention
from ..ops.rms_norm import rms_norm
from ..ops.rope import apply_rope, rope_frequencies
from ..parallel.topology import TENSOR_AXIS


def join_path(path):
    """Stable "a/b/c" rendering of a pytree key path (DictKey.key for
    mappings, str(entry) otherwise) — the one place path-key handling
    lives for quantization skip-lists and TP name rules."""
    return "/".join(str(getattr(k, "key", k)) for k in path)


def maybe_quantize_serving_params(tree, quantization, skip_paths=()):
    """Weight-only int quantization of a serving param tree (reference:
    ``deepspeed/inference/quantization`` — v1's int8 QuantLinear).
    Routers and embedding tables keep full precision (the embedding
    doubles as the tied LM head; the fp32 router picks experts). The
    stacked per-layer weights quantize with layer-aligned groups so the
    compiled layer loop dequantizes ONE layer at a time — resident
    weights stay int8. ``skip_paths``: joined paths that must stay full
    precision (trunk leaves the fused k-major layout could not cover —
    the flat-layout dequant fallback would be SLOWER than dense bf16 at
    decode, 81 vs 18 ms/token measured at 7B)."""
    if not quantization:
        return tree
    from ..ops.quantizer import quantize_tree

    def skip(path):
        joined = join_path(path)
        return joined in skip_paths \
            or "wg" in joined or "embed" in joined or "wte" in joined \
            or "wpe" in joined

    def batched(path):
        s = join_path(path).split("/")
        return bool(s[0]) and s[0] == "layers"
    return quantize_tree(tree, group_size=quantization.group_size,
                         num_bits=quantization.bits,
                         min_size=quantization.min_size, skip=skip,
                         batched=batched)


def stack_layer_params(params: Dict[str, Any], n_layers: int,
                       prefix: str = "layers_"):
    """[per-layer dicts] -> one pytree with leading layer dim (scan xs).

    Host (numpy) inputs stack on HOST: a 7B model's stacked leaves are
    ~13.5 GB bf16 — jnp.stack would enqueue that as device compute
    before quantization/cast can shrink it (the serving OOM mode)."""
    layers = [params[f"{prefix}{i}"] for i in range(n_layers)]

    def stack(*xs):
        if all(not isinstance(x, jax.Array) for x in xs):
            return np.stack([np.asarray(x) for x in xs])
        return jnp.stack(xs)

    return jax.tree.map(stack, *layers)


class PagedInferenceModel:
    """Functional paged-attention transformer consuming *training* params
    from ``models.llama.LlamaForCausalLM`` (same names/shapes — a trained
    checkpoint drops in directly, the analog of the reference's checkpoint
    loading into inference containers)."""

    def __init__(self, cfg: LlamaConfig, params, *, block_size: int,
                 max_blocks_per_seq: int, capture_latents: bool = True,
                 topology=None, quantization=None,
                 restore_chunk_layers: int = 0,
                 restore_chunk_bytes: int = 64 * 1024 * 1024,
                 latent_dtype=""):
        self.cfg = cfg
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.capture_latents = capture_latents
        self.restore_chunk_layers = restore_chunk_layers
        self.restore_chunk_bytes = restore_chunk_bytes
        # "" ⇒ capture in the compute dtype (bit-exact restore)
        self.latent_dtype = jnp.dtype(latent_dtype) if latent_dtype \
            else jnp.dtype(cfg.compute_dtype)
        self.n_layers = cfg.n_layer
        self.topology = topology
        self.tp = topology.tensor_size if topology is not None else 1
        self.quantization = quantization if (
            quantization is not None and quantization.enabled) else None
        # TP + quantization works in both int8 modes: trunk kernels use
        # the k-major MatmulQuantizedTensor layout whose groups run down
        # K per column, so col/row shards stay group-pure (the former
        # flat-layout TP rejection no longer applies).

        self.tied = cfg.tie_word_embeddings
        if self.tp > 1:
            self._validate_tp()
        self.load_params(params)
        theta = getattr(cfg, "rope_theta", None)
        self.cos = self.sin = None
        if theta is not None:
            self.cos, self.sin = rope_frequencies(cfg.head_dim,
                                                  cfg.max_positions,
                                                  theta)
        fwd, restore = self._forward_chunk, self._restore_chunk
        if self.tp > 1:
            fwd, restore = self._wrap_tp(fwd, restore)
        self._fwd_inner = fwd
        self._fwd = jax.jit(fwd, donate_argnums=(1, 2))
        self._restore = jax.jit(restore, donate_argnums=(1, 2))
        self._fwd_tail_cache = {}
        self._fwd_tail_lat_cache = {}
        self._fwd_tail_inner_cache = {}
        self._lookup_loop_jit = jax.jit(
            self._lookup_decode_loop,
            static_argnums=(10, 11, 12, 13, 14),
            donate_argnums=(1, 2))
        self._decode_loop_jit = jax.jit(self._decode_loop,
                                        static_argnums=(11, 12, 13, 14,
                                                        15, 16),
                                        donate_argnums=(1, 2))

    def load_params(self, params):
        """(Re)load training-layout parameters into the serving layout —
        stacked layers, sharded when TP. Called at construction and by the
        hybrid engine after each training phase (reference:
        runtime/hybrid_engine.py — inference containers refreshed from
        ZeRO training params). Shapes are unchanged, so the compiled
        forward/restore functions are reused without retracing."""
        new = {
            "embed": params["embed_tokens"]["embedding"],
            "norm": params["norm"]["weight"],
            "layers": stack_layer_params(params, self.cfg.n_layer),
        }
        if not self.tied:
            new["lm_head"] = params["lm_head"]["kernel"]
        self.params = self._finalize_params(new)

    def _finalize_params(self, new):
        """Shared load_params tail for every family: dtype cast (with
        the `_keep_fp32` exemptions), optional weight quantization, TP
        placement.

        When the incoming tree is host-resident (numpy — checkpoint
        loads, the serving bench) the cast runs on HOST and only the
        FINAL representation is shipped: for an int8-quantized 7B that
        is ~7 GB instead of 13.5 GB bf16 (or 27 GB fp32) of deferred
        device compute whose materialization OOMs a 16 GB chip. Device
        inputs (hybrid-engine refresh from live training params) keep
        the all-device path — no D2H round trip."""
        on_host = all(not isinstance(x, jax.Array)
                      for x in jax.tree.leaves(new))

        def cast(path, p):
            if on_host:
                p = np.asarray(p)
                if not jnp.issubdtype(p.dtype, jnp.floating):
                    return p
                target = (jnp.float32 if self._keep_fp32(path)
                          else self.cfg.compute_dtype)
                return p.astype(jnp.dtype(target))   # ml_dtypes bf16 ok
            p = jnp.asarray(p)
            if not jnp.issubdtype(p.dtype, jnp.floating):
                return p
            if self._keep_fp32(path):
                return p.astype(jnp.float32)
            return p.astype(self.cfg.compute_dtype)

        new = jax.tree_util.tree_map_with_path(cast, new)
        new = self._maybe_quantize(new)
        if self.tp > 1:
            new = jax.device_put(new, self._param_shardings_for(new))
        elif on_host:
            # one explicit transfer of the final (possibly int8) tree
            new = jax.device_put(new)
        return new

    def _maybe_quantize(self, tree):
        qc = self.quantization
        if not qc:
            return maybe_quantize_serving_params(tree, qc)
        # Stacked [L, K, N] projection kernels become
        # MatmulQuantizedTensor in BOTH int8 modes (consumed by _mm:
        # the fused Pallas kernel, or a k-major grouped-view dequant
        # XLA fuses into the dot; NOT dequantized by the scan step).
        # The flat-layout QuantizedTensor dequant lowers to a
        # reshape/slice chain that materializes full-precision copies —
        # measured 41.7 vs 3.1 ms/token at 1B decode. Non-trunk leaves
        # (embed/head) follow the flat dequant-on-use path.
        from ..ops.quantized_matmul import MatmulQuantizedTensor

        names = self._COL_NAMES + self._ROW_NAMES
        skipped = []   # trunk leaves that LOOK quantizable but are not

        def fused(path, leaf):
            # shape checks on the leaf as-is: a host (numpy) leaf must
            # NOT be shipped whole — make_batched streams it to the
            # device one layer at a time (a 7B stacked leaf's one-shot
            # fp32 group view OOMs a 16 GB chip)
            joined = join_path(path)
            is_trunk = (path and str(getattr(path[0], "key",
                                             path[0])) == "layers"
                        and getattr(leaf, "ndim", 0) == 3
                        and any(n in joined for n in names)
                        and joined.endswith("kernel")
                        and leaf.size >= qc.min_size)
            # untied LM head [H, V]: k-major too (tp==1; under TP the
            # early return keeps it full precision). The flat layout
            # dequantizes the WHOLE head every step inside _trunk —
            # ~0.4 GB of bf16 materialized per decoded token at 7B;
            # k-major streams it int8 through _mm like the trunk.
            is_head = (not self.tied and self.tp == 1
                       and joined in ("lm_head", "lm_head/kernel")
                       and getattr(leaf, "ndim", 0) == 2
                       and leaf.size >= qc.min_size)
            if is_head:
                if leaf.shape[-2] % qc.group_size:
                    # same misalignment as the trunk case below: the
                    # head silently staying dense would skew quantized
                    # decode measurements (the head is the single
                    # largest matmul per decoded token) — record it so
                    # the warning fires and the flat-layout fallback
                    # can't quietly re-quantize it either
                    skipped.append((joined, tuple(leaf.shape)))
                    return leaf
                return MatmulQuantizedTensor.make(
                    jnp.asarray(leaf), group_k=qc.group_size,
                    num_bits=qc.bits)
            if is_trunk and leaf.shape[-2] % qc.group_size:
                # K not a group multiple: the leaf stays full precision.
                # Record it — a silently-dense trunk matmul skews any
                # quantized measurement (e.g. group_size 512 leaves the
                # 7B down projection, 25% of weight bytes, bf16).
                skipped.append((joined, tuple(leaf.shape)))
                return leaf
            if not is_trunk:
                return leaf
            if self.tp > 1:
                # shard-alignment: col shards split N (scales follow);
                # row shards split K and its group dim, so the local K
                # must stay a group multiple. Misaligned leaves stay
                # full precision (sharded by the name rules as usual).
                K, N = leaf.shape[-2], leaf.shape[-1]
                if any(n in joined for n in self._ROW_NAMES):
                    if K % self.tp or (K // self.tp) % qc.group_size:
                        return leaf
                elif N % self.tp:
                    return leaf
            return MatmulQuantizedTensor.make_batched(
                leaf, group_k=qc.group_size, num_bits=qc.bits)
        tree = jax.tree_util.tree_map_with_path(fused, tree)
        if skipped:
            from ..utils.logging import log_dist
            log_dist(
                "quantization: %d trunk/head leaves stay full precision "
                "(K %% group_size=%d != 0): %s"
                % (len(skipped), qc.group_size,
                   ", ".join(f"{p}{s}" for p, s in skipped[:4])),
                level=30)   # WARNING — measurements must not read dense
        if self.tp > 1:
            # non-layer leaves (untied head) would quantize in the FLAT
            # layout whose groups straddle the vocab shard — they stay
            # full precision under TP
            return tree
        return maybe_quantize_serving_params(
            tree, qc, skip_paths=frozenset(p for p, _ in skipped))

    def _mm(self, x, w):
        """Matmul that transparently routes k-major-quantized weights:
        through the int8 Pallas kernel (``use_fused_kernel``), or the
        grouped-view dequant that XLA fuses into the dot (plain int8 —
        measured at the int8 bandwidth floor, unlike the flat-layout
        reshape chain ``QuantizedTensor.dequantize`` lowers to)."""
        from ..ops.quantized_matmul import (MatmulQuantizedTensor,
                                            reference_quantized_matmul)
        if isinstance(w, MatmulQuantizedTensor):
            if self.quantization and self.quantization.use_fused_kernel:
                return w.matmul(x)
            lead = x.shape[:-1]
            out = reference_quantized_matmul(
                x.reshape(-1, x.shape[-1]), w.q, w.scale,
                group_k=w.group_k)
            return out.reshape(*lead, w.q.shape[-1])
        return x @ w

    @staticmethod
    def _keep_fp32(path) -> bool:
        """Leaves that must stay fp32 regardless of compute dtype (the MoE
        family pins its router here — near-tie routing logits flip expert
        selection under bf16 rounding)."""
        return False

    # -------------------------------------------------------------- #
    # Tensor parallelism (reference: per-layer allreduce + sharded heads,
    # inference/v2/model_implementations/llama_v2/model.py:160,169 and
    # the sharding framework model_implementations/sharding/)
    # -------------------------------------------------------------- #
    def _validate_tp(self):
        cfg, tp = self.cfg, self.tp
        for name, val in (("n_head", cfg.n_head),
                          ("n_kv_head", cfg.n_kv_head),
                          ("intermediate_size", cfg.intermediate_size),
                          ("vocab_size", cfg.vocab_size)):
            if val % tp:
                raise ValueError(f"{name}={val} not divisible by "
                                 f"tensor parallel degree {tp}")

    #: per-family projection name tables for the TP spec builder: names
    #: matched as substrings of the param path. Subclasses override
    #: (falcon: dense_h_to_4h/dense_4h_to_h; phi: fc1/dense/fc2).
    _COL_NAMES = ("q_proj", "k_proj", "v_proj", "gate_proj", "up_proj")
    _ROW_NAMES = ("o_proj", "down_proj")
    #: a row-parallel projection bias is only legal when the family's
    #: layer math adds it AFTER the psum (phi does; llama has none)
    _ROW_BIAS_OK = False

    def _layer_leaf_spec(self, path, leaf):
        from jax.sharding import PartitionSpec as P
        joined = join_path(path)
        if any(n in joined for n in self._COL_NAMES):
            # stacked kernel [L, in, out] -> col; stacked bias [L, out]
            # follows its column shards
            return P(None, None, TENSOR_AXIS) if leaf.ndim == 3 \
                else P(None, TENSOR_AXIS)
        if any(n in joined for n in self._ROW_NAMES):
            if leaf.ndim != 3:
                if self._ROW_BIAS_OK:
                    return P()   # replicated, added once after the psum
                raise NotImplementedError(
                    "bias on a row-parallel projection would be "
                    "added once per shard before the psum")
            return P(None, TENSOR_AXIS, None)
        return P()

    def _top_leaf_spec(self, key, path, leaf):
        """Specs for the non-layer entries (embed / norm / lm_head)."""
        from jax.sharding import PartitionSpec as P
        if key == "embed":
            # tied: ONE vocab-row-sharded table serves embed + LM head
            # (the reference's vocab-parallel embedding); untied: embed
            # replicated, head column-sharded
            return P(TENSOR_AXIS, None) if self.tied else P()
        if key == "lm_head":
            names = [str(getattr(k, "key", k)) for k in path]
            if names and names[-1] == "bias":
                return P(TENSOR_AXIS)      # vocab-sharded head bias
            return P(None, TENSOR_AXIS)
        return P()                         # norms etc. replicate

    def _param_spec_tree(self, params=None):
        import functools
        params = params if params is not None else self.params
        specs = {}
        for key, sub in params.items():
            if key == "layers":
                specs[key] = jax.tree_util.tree_map_with_path(
                    self._layer_leaf_spec, sub)
            else:
                specs[key] = jax.tree_util.tree_map_with_path(
                    functools.partial(self._top_leaf_spec, key), sub)
        return specs

    def _param_shardings_for(self, params):
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = self.topology.mesh
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            self._param_spec_tree(params),
            is_leaf=lambda x: isinstance(x, PartitionSpec))

    def cache_sharding(self):
        """Sharding for the [L, KV, P, D] block pool: KV heads split over
        ``tensor``. None on single chip."""
        if self.tp == 1:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.topology.mesh,
                             P(None, TENSOR_AXIS, None, None))

    def _wrap_tp(self, fwd, restore):
        from jax.sharding import PartitionSpec as P
        mesh = self.topology.mesh
        pspecs = self._param_spec_tree()
        cache_spec = P(None, TENSOR_AXIS, None, None)  # [L, KV, P, D]
        rep = P()

        fwd_m = jax.shard_map(
            fwd, mesh=mesh, axis_names={TENSOR_AXIS},
            in_specs=(pspecs, cache_spec, cache_spec, rep, rep, rep, rep),
            out_specs=(cache_spec, cache_spec, rep, rep),
            check_vma=False)
        restore_m = jax.shard_map(
            restore, mesh=mesh, axis_names={TENSOR_AXIS},
            in_specs=(pspecs, cache_spec, cache_spec, rep, rep, rep, rep,
                      rep),
            out_specs=(cache_spec, cache_spec),
            check_vma=False)
        return fwd_m, restore_m

    # -------------------------------------------------------------- #
    # Layer math (mirrors models/llama.py LlamaBlock exactly)
    # -------------------------------------------------------------- #
    def _qkv(self, lp, h, positions):
        """h: [B, T, H]; returns q [B,T,Hq,D], k/v [B,T,KV,D] (roped).
        Head counts come from the kernel widths so the same code runs on
        the full model or a tensor-parallel shard (H/tp local heads)."""
        cfg = self.cfg
        B, T, _ = h.shape
        D = cfg.head_dim
        def proj(p, x):
            y = self._mm(x, p["kernel"])
            if "bias" in p:   # qwen-style attention biases
                y = y + p["bias"]
            return y
        qk = lp["self_attn"]["q_proj"]
        kk = lp["self_attn"]["k_proj"]
        vk = lp["self_attn"]["v_proj"]
        q = proj(qk, h)
        k = proj(kk, h)
        v = proj(vk, h)
        q = q.reshape(B, T, q.shape[-1] // D, D)
        k = k.reshape(B, T, k.shape[-1] // D, D)
        v = v.reshape(B, T, v.shape[-1] // D, D)
        q = apply_rope(q, self.cos, self.sin, positions)
        k = apply_rope(k, self.cos, self.sin, positions)
        return q, k, v

    def _scatter_kv(self, ck, cv, k, v, flat_idx):
        """ck/cv: [KV, P, D]; k/v: [B, T, KV, D]; flat_idx: [B, T] (OOB ⇒
        dropped — padded lanes use an index past the pool end)."""
        KV = k.shape[2]
        kt = k.reshape(-1, KV, k.shape[-1]).swapaxes(0, 1)   # [KV, N, D]
        vt = v.reshape(-1, KV, v.shape[-1]).swapaxes(0, 1)
        idx = flat_idx.reshape(-1)
        ck = ck.at[:, idx].set(kt.astype(ck.dtype), mode="drop")
        cv = cv.at[:, idx].set(vt.astype(cv.dtype), mode="drop")
        return ck, cv

    def _paged_attention(self, q, ck, cv, tables, q_positions, kv_len):
        """q: [B, T, Hq, D]; ck/cv: [KV, P, D]; tables: [B, NB];
        q_positions: [B, T] absolute; kv_len: [B] valid cache length.
        Returns [B, T, Hq*D].

        Dispatches to the Pallas ragged paged-attention kernel
        (``ops/paged_attention.py`` — the blocked_flash analog): block-
        table-indexed flash over valid blocks only, no dense [B, S_max]
        gather, no GQA repeat."""
        B, T, Hq, D = q.shape
        start = q_positions[:, 0]  # chunk rows are consecutive positions
        out = paged_attention(q, ck, cv, tables, start, kv_len,
                              self.block_size)
        return out.reshape(B, T, Hq * D)

    def _layer_step(self, x, lp, ck, cv, tables, positions, flat_idx,
                    kv_len):
        cfg = self.cfg
        # fp32 norm weights promote under standard dtype rules — pin the
        # residual stream to the compute dtype
        h = rms_norm(x, lp["input_layernorm"]["weight"],
                     eps=cfg.rms_norm_eps).astype(cfg.compute_dtype)
        latent = h.astype(self.latent_dtype) \
            if self.capture_latents else jnp.zeros(
            (x.shape[0], x.shape[1], 0), h.dtype)
        q, k, v = self._qkv(lp, h, positions)
        ck, cv = self._scatter_kv(ck, cv, k, v, flat_idx)
        attn = self._paged_attention(q, ck, cv, tables, positions, kv_len)
        proj = self._mm(attn, lp["self_attn"]["o_proj"]["kernel"])
        if self.tp > 1:   # row-parallel partial sum (reference :160)
            proj = jax.lax.psum(proj, TENSOR_AXIS)
        x = x + proj
        h2 = rms_norm(x, lp["post_attention_layernorm"]["weight"],
                      eps=cfg.rms_norm_eps).astype(cfg.compute_dtype)
        x = x + self._mlp_out(lp, h2)
        return x.astype(cfg.compute_dtype), ck, cv, latent

    def _mlp_out(self, lp, h2):
        """SwiGLU MLP on the post-attention hidden states. Overridden by
        the MoE family (model_moe.py) with routed grouped-GEMM experts."""
        gate = self._mm(h2, lp["mlp"]["gate_proj"]["kernel"])
        up = self._mm(h2, lp["mlp"]["up_proj"]["kernel"])
        mlp = self._mm(jax.nn.silu(gate) * up,
                       lp["mlp"]["down_proj"]["kernel"])
        if self.tp > 1:   # (reference :169)
            mlp = jax.lax.psum(mlp, TENSOR_AXIS)
        return mlp

    # -------------------------------------------------------------- #
    # forward_chunk: the one compiled family (prefill & ragged decode)
    # -------------------------------------------------------------- #
    def _trunk(self, params, cache_k, cache_v, tokens, start, tables,
               t_len):
        """Embed → layer scan → final norm: the shared body of the
        chunk forwards. Returns (params', cache_k', cache_v',
        x [B, T, H] normed hidden states, latents)."""
        from ..ops.quantizer import dequantize_tree
        # non-layer leaves (head) dequantize here; the stacked layers stay
        # int8 and dequantize ONE layer at a time inside the scan step —
        # resident HBM holds int8 weights + one bf16 layer, not L of them
        params = {k: (v if k == "layers" else dequantize_tree(v))
                  for k, v in params.items()}
        B, T = tokens.shape
        BS = self.block_size
        P = cache_k.shape[2]   # [L, KV, P, D]
        offs = jnp.arange(T)
        positions = start[:, None] + offs[None, :]              # [B, T]
        x = self._embed_lookup(params["embed"], tokens) + \
            self._embed_extra(params, positions)
        token_valid = offs[None, :] < t_len[:, None]
        local_blk = positions // BS                             # in-table idx
        flat_idx = tables[jnp.arange(B)[:, None], local_blk] * BS + \
            positions % BS
        flat_idx = jnp.where(token_valid, flat_idx, P)          # drop pads
        kv_len = start + t_len

        def step(x, xs):
            lp, ck, cv = xs
            lp = dequantize_tree(lp)   # one layer's weights only
            x, ck, cv, latent = self._layer_step(
                x, lp, ck, cv, tables, positions, flat_idx, kv_len)
            return x, (ck, cv, latent)

        x, (cache_k, cache_v, latents) = jax.lax.scan(
            step, x, (params["layers"], cache_k, cache_v))

        x = self._final_norm(params, x)
        return params, cache_k, cache_v, x, latents

    def _forward_chunk(self, params, cache_k, cache_v, tokens, start,
                       tables, t_len):
        """tokens: [B, T] int32; start: [B] first absolute position;
        tables: [B, NB]; t_len: [B] valid new tokens (≤ T).
        Returns (cache_k', cache_v', logits [B, V], latents [L, B, T, H])."""
        params, cache_k, cache_v, x, latents = self._trunk(
            params, cache_k, cache_v, tokens, start, tables, t_len)
        last = jnp.take_along_axis(
            x, jnp.maximum(t_len - 1, 0)[:, None, None], axis=1)[:, 0]
        logits = self._head_logits(params, last)
        if self.tp > 1:
            # vocab is sharded either way (tied: rows of the table;
            # untied: head columns) — gather the full logits row
            # (reference: allgather logits if tp>1, llama_v2/model.py:181)
            logits = jax.lax.all_gather(logits, TENSOR_AXIS, axis=1,
                                        tiled=True)
        return cache_k, cache_v, logits, latents

    def _forward_chunk_tail(self, params, cache_k, cache_v, tokens,
                            start, tables, t_len, tail):
        """Like ``_forward_chunk`` but projects the LAST ``tail`` valid
        positions through the LM head — the verification forward of
        speculative decoding (a drafted stretch needs target logits at
        every drafted position, not just the final one). Returns
        (cache_k', cache_v', logits [B, tail, V]); positions before a
        short sequence's first valid slot clamp to 0 and the caller
        masks by its own accept arithmetic."""
        params, cache_k, cache_v, x, _latents = self._trunk(
            params, cache_k, cache_v, tokens, start, tables, t_len)
        idx = jnp.maximum(
            t_len[:, None] - tail + jnp.arange(tail)[None, :], 0)  # [B,tail]
        xt = jnp.take_along_axis(x, idx[..., None], axis=1)   # [B,tail,H]
        logits = self._head_logits(params, xt)                # [B,tail,V]
        if self.tp > 1:
            logits = jax.lax.all_gather(logits, TENSOR_AXIS, axis=2,
                                        tiled=True)
        return cache_k, cache_v, logits

    def _forward_chunk_tail_lat(self, params, cache_k, cache_v,
                                tokens, start, tables, t_len, tail):
        """``_forward_chunk_tail`` that also returns the trunk's
        captured latents [L, B, T, H] — the verification forward of
        speculative decoding under latent preemption: the caller keeps
        the accepted span's latents (columns ``:acc+1`` of each lane)
        and discards the rolled-back tail. A separate compiled family
        (``_fwd_tail_lat_cache``): engines running exact-KV suspension
        never pay for the latent output."""
        params, cache_k, cache_v, x, latents = self._trunk(
            params, cache_k, cache_v, tokens, start, tables, t_len)
        idx = jnp.maximum(
            t_len[:, None] - tail + jnp.arange(tail)[None, :], 0)
        xt = jnp.take_along_axis(x, idx[..., None], axis=1)
        logits = self._head_logits(params, xt)
        if self.tp > 1:
            logits = jax.lax.all_gather(logits, TENSOR_AXIS, axis=2,
                                        tiled=True)
        return cache_k, cache_v, logits, latents

    def _final_norm(self, params, x):
        """Final RMSNorm; LayerNorm families (falcon) override."""
        return rms_norm(x, params["norm"], eps=self.cfg.rms_norm_eps)

    def _head_logits(self, params, last):
        """LM head on the last valid position; biased-head families
        (phi) override. ``_mm`` routes a k-major-quantized untied head
        through the fused int8 kernel."""
        if self.tied:
            return (last @ params["embed"].T).astype(jnp.float32)
        return self._mm(last, params["lm_head"]).astype(jnp.float32)

    def _embed_extra(self, params, positions):
        """Additive embedding term (learned positions in the gpt2/opt
        trunk); rope families add nothing here."""
        return jnp.zeros((), self.cfg.compute_dtype)

    def _embed_lookup(self, table, tokens):
        """Embedding lookup. Under TP with tied embeddings the table is
        vocab-row-sharded: mask out-of-range ids locally and psum (the
        reference's vocab-parallel embedding)."""
        if self.tp > 1 and self.tied:
            vshard = table.shape[0]
            vstart = jax.lax.axis_index(TENSOR_AXIS) * vshard
            rel = tokens - vstart
            ok = (rel >= 0) & (rel < vshard)
            x = table[jnp.clip(rel, 0, vshard - 1)]
            x = jnp.where(ok[..., None], x, 0)
            x = jax.lax.psum(x, TENSOR_AXIS)
        else:
            x = table[tokens]
        return x.astype(self.cfg.compute_dtype)

    def forward_chunk(self, cache, tokens, start, tables, t_len):
        ck, cv, logits, latents = self._fwd(
            self.params, cache.k, cache.v, jnp.asarray(tokens, jnp.int32),
            jnp.asarray(start, jnp.int32), jnp.asarray(tables, jnp.int32),
            jnp.asarray(t_len, jnp.int32))
        cache.replace(ck, cv)
        return logits, latents

    def _fwd_tail_for(self, tail: int):
        """Per-``tail`` compiled verification forward (tail is a trace
        constant: one program per (tail, batch-bucket, T-pad) triple,
        all reused across a generation)."""
        fn = self._fwd_tail_cache.get(tail)
        if fn is None:
            def fwd_tail(params, ck, cv, tokens, start, tables, t_len):
                return self._forward_chunk_tail(
                    params, ck, cv, tokens, start, tables, t_len, tail)
            if self.tp > 1:
                from jax.sharding import PartitionSpec as P
                cache_spec = P(None, TENSOR_AXIS, None, None)
                rep = P()
                fwd_tail = jax.shard_map(
                    fwd_tail, mesh=self.topology.mesh,
                    axis_names={TENSOR_AXIS},
                    in_specs=(self._param_spec_tree(), cache_spec,
                              cache_spec, rep, rep, rep, rep),
                    out_specs=(cache_spec, cache_spec, rep),
                    check_vma=False)
            fn = jax.jit(fwd_tail, donate_argnums=(1, 2))
            self._fwd_tail_cache[tail] = fn
        return fn

    def forward_chunk_tail(self, cache, tokens, start, tables, t_len,
                           tail: int):
        """Verification forward: head logits for the last ``tail``
        positions of each lane (speculative decoding)."""
        ck, cv, logits = self._fwd_tail_for(tail)(
            self.params, cache.k, cache.v, jnp.asarray(tokens, jnp.int32),
            jnp.asarray(start, jnp.int32), jnp.asarray(tables, jnp.int32),
            jnp.asarray(t_len, jnp.int32))
        cache.replace(ck, cv)
        return logits

    def _fwd_tail_lat_for(self, tail: int):
        """Latent-capturing sibling of :meth:`_fwd_tail_for` (its own
        program cache — the exact-KV tail forward never retraces when
        a latent engine shares the process)."""
        fn = self._fwd_tail_lat_cache.get(tail)
        if fn is None:
            def fwd_tail(params, ck, cv, tokens, start, tables, t_len):
                return self._forward_chunk_tail_lat(
                    params, ck, cv, tokens, start, tables, t_len, tail)
            if self.tp > 1:
                from jax.sharding import PartitionSpec as P
                cache_spec = P(None, TENSOR_AXIS, None, None)
                rep = P()
                fwd_tail = jax.shard_map(
                    fwd_tail, mesh=self.topology.mesh,
                    axis_names={TENSOR_AXIS},
                    in_specs=(self._param_spec_tree(), cache_spec,
                              cache_spec, rep, rep, rep, rep),
                    out_specs=(cache_spec, cache_spec, rep, rep),
                    check_vma=False)
            fn = jax.jit(fwd_tail, donate_argnums=(1, 2))
            self._fwd_tail_lat_cache[tail] = fn
        return fn

    def forward_chunk_tail_lat(self, cache, tokens, start, tables,
                               t_len, tail: int):
        """Verification forward that also captures latents: the
        speculative verify step under latent preemption. Returns
        ``(logits [B, tail, V], latents [L, B, T, H])`` — latent
        columns align with ``tokens`` columns (left-aligned feeds), so
        a lane's accepted span is ``latents[:, j, :acc+1]``."""
        ck, cv, logits, latents = self._fwd_tail_lat_for(tail)(
            self.params, cache.k, cache.v, jnp.asarray(tokens, jnp.int32),
            jnp.asarray(start, jnp.int32), jnp.asarray(tables, jnp.int32),
            jnp.asarray(t_len, jnp.int32))
        cache.replace(ck, cv)
        return logits, latents

    # -------------------------------------------------------------- #
    # HCache restore (the fork's flagship delta)
    # -------------------------------------------------------------- #
    def _restore_layer(self, params, cache_k, cache_v, layer, latent,
                       start, tables, t_len):
        """Replay K/V projection + RoPE + blocked cache write for ONE layer
        from saved latents (reference: llama_v2/model.py:222-252 +
        dense_blocked_attention.py:182 — QKV GEMM + kv-rotary cache write,
        no attention, no MLP). The full cache is donated, so each dispatch
        updates layer ``layer`` in place; the layer's weights are sliced
        from the stacked tree *inside* the compiled program (no per-call
        host-side slicing)."""
        from ..ops.quantizer import dequantize_tree
        # slice THEN dequantize: batched QuantizedTensors slice their
        # leading dim through tree.map, so only this layer's weights are
        # ever materialized full-precision
        lp = jax.tree.map(lambda p: p[layer], params["layers"])
        lp = dequantize_tree(lp)
        B, T, _ = latent.shape
        BS = self.block_size
        P = cache_k.shape[2]   # [L, KV, P, D]
        offs = jnp.arange(T)
        positions = start[:, None] + offs[None, :]
        token_valid = offs[None, :] < t_len[:, None]
        local_blk = positions // BS
        flat_idx = tables[jnp.arange(B)[:, None], local_blk] * BS + \
            positions % BS
        flat_idx = jnp.where(token_valid, flat_idx, P).reshape(-1)
        _, k, v = self._qkv(lp, latent.astype(self.cfg.compute_dtype),
                            positions)
        # mixed indexing (int, :, array) puts the scattered dim FIRST, so
        # the update values keep the natural [N, KV, D] token-major shape
        KV = k.shape[2]
        kv_shape = (-1, KV, k.shape[-1])
        cache_k = cache_k.at[layer, :, flat_idx].set(
            k.reshape(kv_shape).astype(cache_k.dtype), mode="drop")
        cache_v = cache_v.at[layer, :, flat_idx].set(
            v.reshape(kv_shape).astype(cache_v.dtype), mode="drop")
        return cache_k, cache_v

    # -------------------------------------------------------------- #
    # Fused decode loop: N greedy steps in ONE device program
    # -------------------------------------------------------------- #
    @staticmethod
    def _sample_logits(logits, key, temperature, top_p, greedy, top_k,
                       use_top_p):
        """On-device sampling — the device-side mirror of the host
        sampler (``engine_v2._sample_host``). ``greedy``/``top_k``/
        ``use_top_p`` are static (they shape the program); ``temperature``
        and ``top_p`` are traced scalars so per-request values don't
        recompile the decode stretch."""
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        l = logits.astype(jnp.float32) / temperature
        k = min(top_k, l.shape[-1])
        if k > 0:
            kth = jax.lax.top_k(l, k)[0][..., -1:]
            l = jnp.where(l < kth, -jnp.inf, l)
        if use_top_p:
            # nucleus: keep the smallest prob-sorted set with mass>=top_p
            # (count-based keep scattered back through the sort order —
            # a probability threshold would keep every boundary TIE and
            # diverge from the host sampler)
            p = jax.nn.softmax(l, axis=-1)
            order = jnp.argsort(p, axis=-1,
                                descending=True)            # [B, V]
            sp = jnp.take_along_axis(p, order, axis=-1)
            keep_sorted = (jnp.cumsum(sp, axis=-1) - sp) < top_p
            rows = jnp.arange(l.shape[0])[:, None]
            keep = jnp.zeros(l.shape, bool).at[rows, order].set(
                keep_sorted)
            l = jnp.where(keep, l, -jnp.inf)
        return jax.random.categorical(key, l, axis=-1).astype(jnp.int32)

    def _step_sample(self, params, ck, cv, toks, pos, tables, t_step, key,
                     temperature, top_p, greedy, top_k, use_top_p,
                     want_logprobs):
        """One decode forward + sample; shared by the scan and
        while_loop bodies. Returns (ck, cv, nxt, latents, lp)."""
        ck, cv, logits, latents = self._fwd_inner(
            params, ck, cv, toks[:, None], pos, tables, t_step)
        nxt = self._sample_logits(logits, key, temperature, top_p,
                                  greedy, top_k, use_top_p)
        lp = None
        if want_logprobs:
            # raw-model logprob of the chosen token (RLHF consumers)
            lsm = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            lp = jnp.take_along_axis(lsm, nxt[:, None], axis=-1)[:, 0]
        return ck, cv, nxt, latents, lp

    def _decode_loop(self, params, cache_k, cache_v, tokens, start, tables,
                     t_len, rng_key, temperature, top_p, eos_id, n_steps,
                     greedy, top_k, use_top_p, want_logprobs, has_eos):
        """``n_steps`` single-token forwards with the sampled token fed
        back on device — no host round-trip per generated token. The
        reference's engine (like every GPU serving stack) pays a host
        sync per step to route the next batch; on TPU the idiomatic
        serving shape compiles the whole decode stretch so the chip
        never waits on the host.

        Without an EOS the stretch is a ``lax.scan`` (static trip count
        — XLA pipelines it best). With ``has_eos`` it becomes a
        ``lax.while_loop`` that exits once every live lane has sampled
        ``eos_id``: lanes that finish stop feeding (their ``t_len``
        drops to 0 — no cache writes) and a batch whose generations all
        end early doesn't pay for the remaining steps.

        tokens: [B] the first token each lane feeds; start: [B] its
        position; t_len: [B] 1 for live lanes, 0 for padded lanes (their
        writes drop, their outputs are discarded). greedy/top_k/
        use_top_p/want_logprobs/has_eos are static; temperature/top_p/
        eos_id traced. Returns (cache_k', cache_v', tokens_out
        [n_steps, B], latents [n_steps, L, B, 1, H], logprobs
        [n_steps, B] or None when want_logprobs is off); with has_eos,
        rows past a lane's EOS (and past the early exit) are zeros —
        the engine truncates at EOS host-side."""
        if not has_eos:
            def step(carry, _):
                ck, cv, toks, pos, key = carry
                key, sub = jax.random.split(key)
                ck, cv, nxt, latents, lp = self._step_sample(
                    params, ck, cv, toks, pos, tables, t_len, sub,
                    temperature, top_p, greedy, top_k, use_top_p,
                    want_logprobs)
                ys = (nxt, latents) + ((lp,) if want_logprobs else ())
                return (ck, cv, nxt, pos + t_len, key), ys

            (cache_k, cache_v, _, _, _), ys = jax.lax.scan(
                step, (cache_k, cache_v, tokens, start, rng_key), None,
                length=n_steps)
            toks, lats = ys[0], ys[1]
            lps = ys[2] if want_logprobs else None
            return cache_k, cache_v, toks, lats, lps

        B = tokens.shape[0]
        lat_shape = jax.eval_shape(
            lambda p, k, v: self._fwd_inner(p, k, v, tokens[:, None],
                                            start, tables, t_len)[3],
            params, cache_k, cache_v)
        toks_buf = jnp.zeros((n_steps, B), jnp.int32)
        lat_buf = jnp.zeros((n_steps,) + lat_shape.shape, lat_shape.dtype)
        lp_buf = jnp.zeros((n_steps, B), jnp.float32) if want_logprobs \
            else jnp.zeros((0,), jnp.float32)
        done0 = t_len == 0   # padded lanes never block the early exit

        def cond(st):
            return (st[0] < n_steps) & jnp.logical_not(jnp.all(st[7]))

        def body(st):
            (i, ck, cv, toks, pos, key, t_buf, done, l_buf, p_buf) = st
            t_step = jnp.where(done, 0, t_len)
            key, sub = jax.random.split(key)
            ck, cv, nxt, latents, lp = self._step_sample(
                params, ck, cv, toks, pos, tables, t_step, sub,
                temperature, top_p, greedy, top_k, use_top_p,
                want_logprobs)
            t_buf = t_buf.at[i].set(jnp.where(done, 0, nxt))
            l_buf = l_buf.at[i].set(latents)
            if want_logprobs:
                p_buf = p_buf.at[i].set(jnp.where(done, 0.0, lp))
            done = done | (nxt == eos_id)
            return (i + 1, ck, cv, nxt, pos + t_step, key, t_buf, done,
                    l_buf, p_buf)

        st = (jnp.int32(0), cache_k, cache_v, tokens, start, rng_key,
              toks_buf, done0, lat_buf, lp_buf)
        st = jax.lax.while_loop(cond, body, st)
        _, cache_k, cache_v, _, _, _, toks, _, lats, lps = st
        return cache_k, cache_v, toks, lats, \
            (lps if want_logprobs else None)

    def _fwd_tail_inner_for(self, tail: int):
        """Un-jitted (TP-wrapped when tp>1) tail forward for use INSIDE
        other compiled loops (the fused speculative decoder)."""
        fn = self._fwd_tail_inner_cache.get(tail)
        if fn is None:
            def fwd_tail(params, ck, cv, tokens, start, tables, t_len):
                return self._forward_chunk_tail(
                    params, ck, cv, tokens, start, tables, t_len, tail)
            if self.tp > 1:
                from jax.sharding import PartitionSpec as P
                cache_spec = P(None, TENSOR_AXIS, None, None)
                rep = P()
                fwd_tail = jax.shard_map(
                    fwd_tail, mesh=self.topology.mesh,
                    axis_names={TENSOR_AXIS},
                    in_specs=(self._param_spec_tree(), cache_spec,
                              cache_spec, rep, rep, rep, rep),
                    out_specs=(cache_spec, cache_spec, rep),
                    check_vma=False)
            self._fwd_tail_inner_cache[tail] = fn = fwd_tail
        return fn

    def _lookup_decode_loop(self, params, cache_k, cache_v, first_tok,
                            pos0, tables, live, hist0, hist_len0,
                            eos_id, max_new, ngram, max_draft, window,
                            has_eos):
        """Fused prompt-lookup speculative decoding: draft, verify,
        accept and roll back entirely on device inside one
        ``lax.while_loop`` — the host syncs once per *generation*, and
        each loop iteration can emit up to ``max_draft + 1`` tokens.

        Drafting is a vectorized n-gram match over a right-aligned
        rolling window of each lane's recent tokens; a bad draft only
        costs speed — acceptance compares drafts against the verified
        greedy targets, so output is bit-identical to token-by-token
        greedy decode regardless of what the draft proposes. Rejected
        draft KV stays past the lane's position cursor and is
        overwritten by the next iteration's writes (the same rollback
        arithmetic as the host-driven :meth:`generate_lookup` path,
        moved into the carry).

        first_tok/pos0/live: [B]; hist0: [B, window] right-aligned
        recent tokens; hist_len0: [B] valid counts. eos_id traced;
        max_new/ngram/max_draft/window/has_eos static. Returns
        (cache_k', cache_v', outs [B, max_new], out_len [B], iters,
        accepted [B], lane_iters [B]) — accepted and lane_iters ride
        the loop carry PER LANE, so serving can attribute acceptance
        per request instead of batch-averaging (the old scalar total
        is their sum; the old ``drafted`` upper bound is
        ``lane_iters * max_draft`` per lane)."""
        B = first_tok.shape[0]
        T = 1 + max_draft
        W = window
        fwd_tail = self._fwd_tail_inner_for(T)
        win_idx = jnp.arange(W - ngram)[:, None] + \
            jnp.arange(ngram)[None, :]              # [W-ngram, ngram]
        rows = jnp.arange(B)

        def draft(hist, hist_len, last_tok):
            key = hist[:, W - ngram:]                        # [B, ngram]
            wins = hist[:, win_idx]                  # [B, W-ngram, ngram]
            starts = jnp.arange(W - ngram)[None, :]
            valid = starts >= (W - hist_len)[:, None]        # in-window
            hit = (wins == key[:, None, :]).all(-1) & valid  # [B, W-ngram]
            any_hit = hit.any(axis=1)
            # most recent match wins
            i_star = jnp.max(jnp.where(hit, starts, -1), axis=1)
            src = jnp.clip(i_star + ngram, 0, W - 1)
            cols = jnp.clip(src[:, None] + jnp.arange(max_draft)[None, :],
                            0, W - 1)
            cand = hist[rows[:, None], cols]              # [B, max_draft]
            # no match: propose repeats of the last token (cheap; only
            # accepted if it IS the greedy continuation)
            return jnp.where(any_hit[:, None], cand,
                             last_tok[:, None].astype(hist.dtype))

        # +1 trash column: masked-out scatter lanes write there instead
        # of clipping onto a real slot (duplicate scatter indices have
        # last-write-wins semantics and would clobber the real token)
        outs0 = jnp.zeros((B, max_new + 1), jnp.int32)
        done0 = jnp.logical_not(live)

        def cond(st):
            i, done = st[0], st[7]
            return (i < max_new) & jnp.logical_not(jnp.all(done))

        def body(st):
            (i, ck, cv, last_tok, pos, hist, hist_len, done, outs,
             out_len, accepted, lane_iters) = st
            d = draft(hist, hist_len, last_tok)              # [B, k]
            toks = jnp.concatenate([last_tok[:, None], d], axis=1)
            t_step = jnp.where(done, 0, T)
            ck, cv, logits = fwd_tail(params, ck, cv, toks, pos, tables,
                                      t_step)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            # leading drafts matching their verified targets
            match = d == greedy[:, :max_draft]
            acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                          axis=1)                             # [B]
            remaining = jnp.maximum(max_new - out_len, 0)
            c = jnp.minimum(acc + 1, remaining)               # emit count
            if has_eos:
                emit_mask = jnp.arange(T)[None, :] < c[:, None]
                is_eos = (greedy == eos_id) & emit_mask
                eos_pos = jnp.argmax(is_eos, axis=1)
                c = jnp.where(is_eos.any(axis=1),
                              jnp.minimum(c, eos_pos + 1), c)
            c = jnp.where(done, 0, c)
            # scatter greedy[:, :c] into outs at out_len; masked lanes
            # target the trash column (in-range cols are unique: off < c
            # implies out_len + off <= max_new - 1)
            mask = jnp.arange(T)[None, :] < c[:, None]
            col = jnp.where(mask,
                            out_len[:, None] + jnp.arange(T)[None, :],
                            max_new)
            outs = outs.at[rows[:, None], col].set(greedy)
            # roll the history window left by c and append the emitted
            ext = jnp.concatenate([hist, greedy], axis=1)   # [B, W+T]
            idx = jnp.arange(W)[None, :] + c[:, None]
            hist = ext[rows[:, None], idx]
            hist_len = jnp.minimum(hist_len + c, W)
            out_len = out_len + c
            new_done = done | (out_len >= max_new)
            if has_eos:
                new_done = new_done | (
                    (c > 0) & (jnp.take_along_axis(
                        outs, jnp.maximum(out_len - 1, 0)[:, None],
                        axis=1)[:, 0] == eos_id))
            # cached-valid tokens this round = c (fed token + c-1
            # accepted drafts); the last emitted token is the uncached
            # bonus fed next round
            pos = pos + jnp.where(done, 0, c)
            last_tok = jnp.take_along_axis(
                outs, jnp.maximum(out_len - 1, 0)[:, None], axis=1)[:, 0]
            # per-lane carries: accepted drafts and live iterations —
            # the serving attribution the batch-scalar version lost
            accepted = accepted + jnp.where(done, 0,
                                            jnp.maximum(c - 1, 0))
            lane_iters = lane_iters + jnp.where(done, 0, 1)
            return (i + 1, ck, cv, last_tok, pos, hist, hist_len,
                    new_done, outs, out_len, accepted, lane_iters)

        st = (jnp.int32(0), cache_k, cache_v, first_tok, pos0, hist0,
              hist_len0, done0, outs0, jnp.zeros((B,), jnp.int32),
              jnp.zeros((B,), jnp.int32), jnp.zeros((B,), jnp.int32))
        st = jax.lax.while_loop(cond, body, st)
        (iters, cache_k, cache_v, _, _, _, _, _, outs, out_len,
         accepted, lane_iters) = st
        return cache_k, cache_v, outs[:, :max_new], out_len, iters, \
            accepted, lane_iters

    def lookup_decode_loop(self, cache, first_tok, pos, tables, live,
                           hist, hist_len, *, max_new, ngram, max_draft,
                           window, eos_token_id=None):
        """Public fused speculative decoder (see _lookup_decode_loop).
        Returns ``(outs, out_len, iters, accepted, lane_iters)`` with
        ``accepted`` and ``lane_iters`` PER LANE ([B] int arrays)."""
        has_eos = eos_token_id is not None
        eos = jnp.int32(eos_token_id if has_eos else -1)
        (ck, cv, outs, out_len, iters, accepted,
         lane_iters) = self._lookup_loop_jit(
            self.params, cache.k, cache.v,
            jnp.asarray(first_tok, jnp.int32),
            jnp.asarray(pos, jnp.int32),
            jnp.asarray(tables, jnp.int32),
            jnp.asarray(live, bool),
            jnp.asarray(hist, jnp.int32),
            jnp.asarray(hist_len, jnp.int32),
            eos, max_new, ngram, max_draft, window, has_eos)
        cache.replace(ck, cv)
        return (np.asarray(outs), np.asarray(out_len), int(iters),
                np.asarray(accepted), np.asarray(lane_iters))

    def decode_loop(self, cache, tokens, start, t_len, tables, n_steps,
                    temperature=0.0, top_k=0, top_p=1.0, seed=0,
                    want_logprobs=False, eos_token_id=None):
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        ck, cv, toks, lats, lps = self._decode_loop_jit(
            self.params, cache.k, cache.v, jnp.asarray(tokens, jnp.int32),
            jnp.asarray(start, jnp.int32), jnp.asarray(tables, jnp.int32),
            jnp.asarray(t_len, jnp.int32), jax.random.PRNGKey(seed),
            jnp.float32(max(temperature, 1e-6)), jnp.float32(top_p),
            jnp.int32(eos_token_id if eos_token_id is not None else -1),
            int(n_steps), temperature <= 0, int(top_k), top_p < 1.0,
            bool(want_logprobs), eos_token_id is not None)
        cache.replace(ck, cv)
        return (np.asarray(toks), lats,
                np.asarray(lps) if lps is not None else None)

    def _restore_chunk(self, params, cache_k, cache_v, layer0, lat_chunk,
                       start, tables, t_len):
        """Replay layers ``layer0 .. layer0+C`` from one latent slab
        ``[C, B, T, H]`` in a single dispatch (C is static — set by the
        engine's chunking policy)."""
        def body(i, kv):
            ck, cv = kv
            return self._restore_layer(params, ck, cv, layer0 + i,
                                       lat_chunk[i], start, tables, t_len)
        return jax.lax.fori_loop(0, lat_chunk.shape[0], body,
                                 (cache_k, cache_v))

    def restore_pipeline(self, cache, latents, start, tables, t_len,
                         progress_cb=None) -> "RestorePipeline":
        """Incremental chunk-at-a-time restore of one staged lane group
        — the unit the serving scheduler interleaves with resident
        decode (see :class:`RestorePipeline`)."""
        return RestorePipeline(self, cache, latents, start, tables,
                               t_len, progress_cb=progress_cb)

    def restore_kv(self, cache, latents, start, tables, t_len,
                   progress_cb=None):
        """latents: host array [L, B, T, H] (numpy). Layer-CHUNKED
        dispatches with the next chunk's host→HBM copy issued before this
        chunk's compute — JAX's async dispatch gives the reference's
        dual-stream overlap (io_stream copy / compute wait-event chain,
        llama_v2/model.py:229) at chunk granularity. The reference's
        literal one-dispatch-per-layer shape is latency-bound on a slow
        host link, while one whole-stack dispatch can't overlap H2D with
        compute and needs the full latent slab in HBM (million-token
        contexts: tens of GB); the chunk size interpolates
        (``hcache.restore_chunk_layers`` / ``restore_chunk_bytes``).

        ``progress_cb(layer0, shipped_bytes)`` fires as each chunk's
        dispatch is ISSUED (still in flight) — the serving scheduler's
        staging-progress hook; ``shipped_bytes`` is 0 on the
        already-staged (HBM-resident) path.

        This is the run-to-completion driver over
        :class:`RestorePipeline`; the serving scheduler instead holds
        the pipeline open and advances it chunk by chunk between decode
        dispatches (``engine.begin_restore``/``advance_restores``)."""
        pipe = self.restore_pipeline(cache, latents, start, tables,
                                     t_len, progress_cb=progress_cb)
        while not pipe.done:
            pipe.advance()


class RestorePipeline:
    """One lane group's restore as a chunk pipeline with two lanes:

    * **ship lane** — ``jax.device_put`` of the next layer-chunk's
      latent slab, dispatched (async) ahead of the replay that will
      consume it, at most ``depth`` chunks in flight (bounds staging
      HBM; depth 2 is the classic double buffer);
    * **replay lane** — the jitted QKV-replay dispatch consuming the
      previously shipped chunk.

    ``advance(max_chunks)`` issues up to ``max_chunks`` replay
    dispatches (shipping ahead as it goes) and returns immediately —
    nothing here ever blocks on the device, so the caller can issue a
    resident-decode dispatch between advances and the link ship hides
    under that decode's compute (the reference's dedicated
    ``io_stream`` vs compute-stream overlap, ``engine_v2.py:108-129``,
    expressed through JAX async dispatch). The cache object is re-read
    at every advance and replaced after, so interleaved forwards
    (which donate and replace the same buffers) compose with an open
    pipeline; interleaved dispatches only read OTHER sequences' blocks,
    so results are bit-identical to a sequential restore-then-decode.
    """

    def __init__(self, model, cache, latents, start, tables, t_len,
                 progress_cb=None, depth: int = 2):
        self.model = model
        self.cache = cache
        self.progress_cb = progress_cb
        self.depth = max(1, depth)
        self._start = jnp.asarray(start, jnp.int32)
        self._tables = jnp.asarray(tables, jnp.int32)
        self._t_len = jnp.asarray(t_len, jnp.int32)
        self.staged = isinstance(latents, jax.Array)
        L = model.n_layers
        C = model.restore_chunk_layers
        if C <= 0:
            per_layer = (int(np.prod(latents.shape[1:])) *
                         np.dtype(latents.dtype).itemsize)
            C = max(1, min(L, model.restore_chunk_bytes //
                           max(per_layer, 1)))
        self.chunk_layers = C
        self.bounds = list(range(0, L, C))
        self._next_replay = 0
        self._bufs = {}                 # chunk index -> shipped buffer
        # target placement: latents replicate over whatever mesh the
        # cache actually lives on (derived from the array, not the TP
        # degree: a hybrid engine hands over caches resident on the
        # TRAINING mesh, which can be multi-device even when the
        # serving tensor axis is 1)
        from jax.sharding import NamedSharding, PartitionSpec
        ck = cache.k
        if isinstance(ck.sharding, NamedSharding):
            self._dev = NamedSharding(ck.sharding.mesh, PartitionSpec())
        else:
            self._dev = list(ck.devices())[0]
        if self.staged:
            # already HBM-resident (hybrid-engine handoff, marginal
            # bench): chunked dispatches slice the slab on device. It
            # must still land on the CACHE's device assembly (a sharded
            # cache with a single-device slab fails the jitted call)
            if isinstance(ck.sharding, NamedSharding):
                if latents.sharding != self._dev:
                    latents = jax.device_put(latents, self._dev)
            elif latents.devices() != ck.devices():
                latents = jax.device_put(latents, list(ck.devices())[0])
            self.latents = latents
        else:
            self.latents = np.asarray(latents)

    # ------------------------------------------------------------- #
    @property
    def chunks_total(self) -> int:
        return len(self.bounds)

    @property
    def chunks_issued(self) -> int:
        return self._next_replay

    @property
    def done(self) -> bool:
        return self._next_replay >= len(self.bounds)

    # ------------------------------------------------------------- #
    def _ship(self, i):
        from ..resilience.faults import get_injector
        _inj = get_injector()
        if _inj.enabled:
            # before the H2D issue: a faulted ship is re-issuable
            _inj.fire("restore.ship", chunk=i)
        l0 = self.bounds[i]
        sl = self.latents[l0:l0 + self.chunk_layers]
        if self.staged:
            return sl                     # device slice, no transfer
        # the lane slab is layer-major contiguous (built by
        # _stage_restore_group / HostLatentStore), so this is a
        # straight block copy, not a gather
        return jax.device_put(np.ascontiguousarray(sl), self._dev)

    def prefetch(self) -> int:
        """Ship ahead: issue H2D for the next unshipped chunks up to
        the in-flight ``depth``. Returns chunks whose ship was issued.
        Call this as soon as the lane opens so the first chunk's link
        time hides under whatever the engine dispatches next."""
        issued = 0
        i = self._next_replay
        while i < len(self.bounds) and \
                len(self._bufs) < self.depth:
            if i not in self._bufs:
                self._bufs[i] = self._ship(i)
                issued += 1
            i += 1
        return issued

    def advance(self, max_chunks: int = 0) -> int:
        """Issue up to ``max_chunks`` replay dispatches (0 = all
        remaining), shipping the following chunk ahead of each replay.
        Async end to end — returns the number of replays issued."""
        from ..resilience.faults import get_injector
        from ..telemetry.tracer import get_tracer
        tracer = get_tracer()
        _inj = get_injector()
        issued = 0
        L = self.model.n_layers
        while not self.done and (max_chunks <= 0 or
                                 issued < max_chunks):
            i = self._next_replay
            l0 = self.bounds[i]
            if _inj.enabled:
                # before the cursor moves or the buffer is consumed —
                # a faulted replay retries from the same chunk
                _inj.fire("restore.replay", chunk=i, layer0=l0)
            cur = self._bufs.pop(i, None)
            nbytes = 0 if self.staged else int(
                np.prod(self.latents[l0:l0 + self.chunk_layers].shape)
                * np.dtype(self.latents.dtype).itemsize)
            # span covers ship-issue + dispatch-issue for this chunk
            # (both async — the host-side staging cost the restore
            # latency story attributes per layer chunk)
            with tracer.span("serve.restore.stage", layer0=l0,
                             layers=min(self.chunk_layers, L - l0),
                             bytes=nbytes):
                if cur is None:
                    cur = self._ship(i)
                self._next_replay = i + 1
                ck, cv = self.model._restore(
                    self.model.params, self.cache.k, self.cache.v,
                    jnp.int32(l0), cur, self._start, self._tables,
                    self._t_len)
                self.cache.replace(ck, cv)
                # dual-lane: the NEXT chunks' H2D ships issue right
                # behind this (async) replay dispatch and ride the link
                # under it. Ordered after the replay so a faulted ship
                # can never strand a half-advanced cursor — every
                # injected fault lands either before this chunk mutated
                # anything or after it fully replayed (retry-safe).
                self.prefetch()
            if self.progress_cb is not None:
                self.progress_cb(l0, nbytes)
            issued += 1
        return issued
