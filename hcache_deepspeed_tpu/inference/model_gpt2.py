"""Paged-KV inference model for the GPT-2 architecture family.

Reference analog: the v1 kernel-injection containers for gpt2/gpt-neo
(``module_inject/containers/gpt2.py``) and the v2 model-implementation
framework's per-arch layer containers — a SECOND architecture served by
the same ragged engine: LayerNorm (not RMSNorm), learned absolute
position embeddings (no RoPE), fused c_attn QKV with biases, MHA, tied
LM head.

Consumes ``models.gpt2.GPT2LMHeadModel`` training params directly
(wte/wpe/h_i/ln_f names), mirrors :class:`PagedInferenceModel`'s
engine-facing contract (``forward_chunk``, ``restore_kv``,
``cache_sharding``) so ``InferenceEngineV2`` runs either family.
Latents (HCache) = the post-ln_1 hidden states, the same pre-QKV
snapshot point the llama model uses.
"""

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gpt2 import GPT2Config
from ..ops.paged_attention import paged_attention
from .model import stack_layer_params


class PagedGPT2Model:
    def __init__(self, cfg: GPT2Config, params, *, block_size: int,
                 max_blocks_per_seq: int, capture_latents: bool = True,
                 topology=None, quantization=None):
        if topology is not None and topology.tensor_size > 1:
            raise NotImplementedError(
                "tensor-parallel serving covers the llama/mixtral/"
                "qwen2-moe/falcon-GQA/phi families; the gpt2 trunk "
                "(gpt2, opt) serves single-chip / data-parallel")
        self.cfg = cfg
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.capture_latents = capture_latents
        self.n_layers = cfg.n_layer
        self.topology = topology
        self.tp = 1
        self.quantization = quantization if (
            quantization is not None and quantization.enabled) else None
        if self.quantization and self.quantization.use_fused_kernel:
            raise NotImplementedError(
                "fused-kernel quantized serving covers the llama-trunk "
                "families; the gpt2 trunk uses the dequant-on-use path")

        self.load_params(params)
        self._fwd = jax.jit(self._forward_chunk, donate_argnums=(1, 2))
        self._restore = jax.jit(self._restore_layer, donate_argnums=(1, 2))

    def load_params(self, params):
        """(Re)load training-layout params into the serving layout — the
        hybrid engine's per-phase refresh contract (see
        PagedInferenceModel.load_params). Shapes unchanged ⇒ compiled
        functions are reused."""
        from .model import maybe_quantize_serving_params
        self.params = maybe_quantize_serving_params({
            "wte": params["wte"]["embedding"],
            "wpe": params["wpe"]["embedding"],
            "ln_f": {k: params["ln_f"][k] for k in ("scale", "bias")},
            "layers": stack_layer_params(params, self.cfg.n_layer,
                                         prefix="h_"),
        }, self.quantization)

    def cache_sharding(self):
        return None

    # -------------------------------------------------------------- #
    @staticmethod
    def _ln(x, p, eps):
        mu = jnp.mean(x.astype(jnp.float32), axis=-1, keepdims=True)
        var = jnp.var(x.astype(jnp.float32), axis=-1, keepdims=True)
        out = (x.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + eps)
        return (out * p["scale"] + p["bias"]).astype(x.dtype)

    def _qkv(self, lp, h):
        """h: [B, T, C] -> q/k/v [B, T, H, D] (fused c_attn, biases)."""
        cfg = self.cfg
        B, T, C = h.shape
        H = cfg.n_head
        D = C // H
        qkv = h @ lp["attn"]["c_attn"]["kernel"] + \
            lp["attn"]["c_attn"]["bias"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        return (q.reshape(B, T, H, D), k.reshape(B, T, H, D),
                v.reshape(B, T, H, D))

    def _scatter_kv(self, ck, cv, k, v, flat_idx):
        kv_shape = (-1,) + k.shape[2:]
        ck = ck.at[flat_idx.reshape(-1)].set(
            k.reshape(kv_shape).astype(ck.dtype), mode="drop")
        cv = cv.at[flat_idx.reshape(-1)].set(
            v.reshape(kv_shape).astype(cv.dtype), mode="drop")
        return ck, cv

    def _layer_step(self, x, lp, ck, cv, tables, positions, flat_idx,
                    kv_len):
        cfg = self.cfg
        eps = cfg.layer_norm_epsilon
        h = self._ln(x, lp["ln_1"], eps)
        latent = h if self.capture_latents else jnp.zeros(
            (x.shape[0], x.shape[1], 0), h.dtype)
        q, k, v = self._qkv(lp, h)
        ck, cv = self._scatter_kv(ck, cv, k, v, flat_idx)
        B, T, Hq, D = q.shape
        attn = paged_attention(q, ck, cv, tables, positions[:, 0], kv_len,
                               self.block_size).reshape(B, T, Hq * D)
        x = x + self._attn_proj(lp, attn)
        h2 = self._ln(x, lp["ln_2"], eps)
        x = x + self._mlp_out(lp, h2)
        return x.astype(self.cfg.compute_dtype), ck, cv, latent

    def _attn_proj(self, lp, attn):
        p = lp["attn"]["c_proj"]
        return attn @ p["kernel"] + p["bias"]

    def _mlp_out(self, lp, h2):
        """GELU MLP; the OPT family overrides with ReLU fc1/fc2."""
        ff = jax.nn.gelu(h2 @ lp["mlp"]["c_fc"]["kernel"] +
                         lp["mlp"]["c_fc"]["bias"], approximate=True)
        return ff @ lp["mlp"]["c_proj"]["kernel"] + \
            lp["mlp"]["c_proj"]["bias"]

    # -------------------------------------------------------------- #
    def _forward_chunk(self, params, cache_k, cache_v, tokens, start,
                       tables, t_len):
        from ..ops.quantizer import dequantize_tree
        # stacked layers stay int8; each scan step dequantizes one layer
        params = {k: (v if k == "layers" else dequantize_tree(v))
                  for k, v in params.items()}
        B, T = tokens.shape
        BS = self.block_size
        P = cache_k.shape[1]
        offs = jnp.arange(T)
        positions = start[:, None] + offs[None, :]
        token_valid = offs[None, :] < t_len[:, None]
        local_blk = positions // BS
        flat_idx = tables[jnp.arange(B)[:, None], local_blk] * BS + \
            positions % BS
        flat_idx = jnp.where(token_valid, flat_idx, P)
        kv_len = start + t_len

        x = (params["wte"][tokens] + params["wpe"][positions]).astype(
            self.cfg.compute_dtype)

        def step(x, xs):
            lp, ck, cv = xs
            lp = dequantize_tree(lp)   # one layer's weights only
            x, ck, cv, latent = self._layer_step(
                x, lp, ck, cv, tables, positions, flat_idx, kv_len)
            return x, (ck, cv, latent)

        x, (cache_k, cache_v, latents) = jax.lax.scan(
            step, x, (params["layers"], cache_k, cache_v))

        x = self._ln(x, params["ln_f"], self.cfg.layer_norm_epsilon)
        last = jnp.take_along_axis(
            x, jnp.maximum(t_len - 1, 0)[:, None, None], axis=1)[:, 0]
        logits = (last @ params["wte"].T).astype(jnp.float32)
        return cache_k, cache_v, logits, latents

    def forward_chunk(self, cache, tokens, start, tables, t_len):
        ck, cv, logits, latents = self._fwd(
            self.params, cache.k, cache.v, jnp.asarray(tokens, jnp.int32),
            jnp.asarray(start, jnp.int32), jnp.asarray(tables, jnp.int32),
            jnp.asarray(t_len, jnp.int32))
        cache.replace(ck, cv)
        return logits, latents

    # -------------------------------------------------------------- #
    def _restore_layer(self, params, cache_k, cache_v, layer, latent,
                       start, tables, t_len):
        from ..ops.quantizer import dequantize_tree
        lp = jax.tree.map(lambda p: p[layer], params["layers"])
        lp = dequantize_tree(lp)   # slice then dequantize: one layer
        B, T, _ = latent.shape
        BS = self.block_size
        P = cache_k.shape[1]
        offs = jnp.arange(T)
        positions = start[:, None] + offs[None, :]
        token_valid = offs[None, :] < t_len[:, None]
        local_blk = positions // BS
        flat_idx = tables[jnp.arange(B)[:, None], local_blk] * BS + \
            positions % BS
        flat_idx = jnp.where(token_valid, flat_idx, P).reshape(-1)
        _, k, v = self._qkv(lp, latent.astype(self.cfg.compute_dtype))
        kv_shape = (-1,) + k.shape[2:]
        cache_k = cache_k.at[layer, flat_idx].set(
            k.reshape(kv_shape).astype(cache_k.dtype), mode="drop")
        cache_v = cache_v.at[layer, flat_idx].set(
            v.reshape(kv_shape).astype(cache_v.dtype), mode="drop")
        return cache_k, cache_v

    def restore_kv(self, cache, latents, start, tables, t_len):
        start = jnp.asarray(start, jnp.int32)
        tables = jnp.asarray(tables, jnp.int32)
        t_len = jnp.asarray(t_len, jnp.int32)
        ck, cv = cache.k, cache.v
        dev = list(ck.devices())[0]
        buf = jax.device_put(np.asarray(latents[0]), dev)
        for l in range(self.n_layers):  # noqa: E741
            cur = buf
            if l + 1 < self.n_layers:
                buf = jax.device_put(np.asarray(latents[l + 1]), dev)
            ck, cv = self._restore(self.params, ck, cv, jnp.int32(l), cur,
                                   start, tables, t_len)
        cache.replace(ck, cv)
