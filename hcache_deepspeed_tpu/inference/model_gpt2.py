"""Paged-KV inference model for the GPT-2 architecture family.

Reference analog: the v1 kernel-injection containers for gpt2/gpt-neo
(``module_inject/containers/gpt2.py``) and the v2 model-implementation
framework's per-arch layer containers — a SECOND architecture served by
the same ragged engine: LayerNorm (not RMSNorm), learned absolute
position embeddings (no RoPE), biased projections, MHA, tied LM head.

Built on :class:`PagedInferenceModel`'s trunk, which supplies the KV
plumbing, TP machinery (vocab-parallel tied embedding, sharded KV,
per-layer psum), quantized serving and HCache restore. The fused HF
``c_attn`` splits into separate q/k/v at load time — the TP-shardable
layout (a column shard of the fused [C, 3C] kernel would mix whole-q
with half-k); biases on the row-parallel projections add once, after
the psum. Latents (HCache) = the post-ln_1 hidden states.
"""

import jax
import jax.numpy as jnp

from ..models.gpt2 import GPT2Config
from ..parallel.topology import TENSOR_AXIS
from .model import PagedInferenceModel, stack_layer_params


class PagedGPT2Model(PagedInferenceModel):
    _COL_NAMES = ("q_proj", "k_proj", "v_proj", "c_fc")
    _ROW_NAMES = ("c_proj",)          # attn and mlp output projections
    _ROW_BIAS_OK = True               # added after the psum below

    def __init__(self, cfg: GPT2Config, params, **kw):
        if not isinstance(cfg, GPT2Config):
            raise TypeError("PagedGPT2Model needs a GPT2Config")
        super().__init__(cfg, params, **kw)

    def _validate_tp(self):
        cfg, tp = self.cfg, self.tp
        for name, val in (("n_head", cfg.n_head),
                          ("n_embd", cfg.n_embd),
                          ("vocab_size", cfg.vocab_size)):
            if val % tp:
                raise ValueError(f"{name}={val} not divisible by "
                                 f"tensor parallel degree {tp}")

    # -------------------------------------------------------------- #
    def load_params(self, params):
        """Training layout -> serving layout: fused c_attn [C, 3C]
        splits into q/k/v [C, C] (+ biases), everything stacked."""
        layers = stack_layer_params(params, self.cfg.n_layer, prefix="h_")
        ca_k = layers["attn"]["c_attn"]["kernel"]      # [L, C, 3C]
        ca_b = layers["attn"]["c_attn"]["bias"]        # [L, 3C]
        qk, kk, vk = jnp.split(ca_k, 3, axis=-1)
        qb, kb, vb = jnp.split(ca_b, 3, axis=-1)
        new = {
            "embed": params["wte"]["embedding"],
            "wpe": params["wpe"]["embedding"],
            "norm": {k: params["ln_f"][k] for k in ("scale", "bias")},
            "layers": {
                "ln_1": layers["ln_1"],
                "ln_2": layers["ln_2"],
                "attn": {
                    "q_proj": {"kernel": qk, "bias": qb},
                    "k_proj": {"kernel": kk, "bias": kb},
                    "v_proj": {"kernel": vk, "bias": vb},
                    "c_proj": layers["attn"]["c_proj"],
                },
                "mlp": layers["mlp"],
            },
        }
        self.params = self._finalize_params(new)

    # -------------------------------------------------------------- #
    def _top_leaf_spec(self, key, path, leaf):
        from jax.sharding import PartitionSpec as P
        if key == "wpe":
            return P()            # positions replicate
        return super()._top_leaf_spec(key, path, leaf)

    def _embed_extra(self, params, positions):
        return params["wpe"][positions].astype(self.cfg.compute_dtype)

    @staticmethod
    def _ln(x, p, eps):
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        out = (xf - mu) * jax.lax.rsqrt(var + eps)
        return (out * p["scale"] + p["bias"]).astype(x.dtype)

    def _final_norm(self, params, x):
        return self._ln(x, params["norm"], self.cfg.layer_norm_epsilon)

    # -------------------------------------------------------------- #
    def _qkv(self, lp, h, positions):
        """Separate biased projections, no rope; head counts from the
        (possibly TP-sharded) kernel widths."""
        B, T, _ = h.shape
        D = self.cfg.head_dim
        a = lp["attn"]
        q = self._mm(h, a["q_proj"]["kernel"]) + a["q_proj"]["bias"]
        k = self._mm(h, a["k_proj"]["kernel"]) + a["k_proj"]["bias"]
        v = self._mm(h, a["v_proj"]["kernel"]) + a["v_proj"]["bias"]
        return (q.reshape(B, T, q.shape[-1] // D, D),
                k.reshape(B, T, k.shape[-1] // D, D),
                v.reshape(B, T, v.shape[-1] // D, D))

    def _attn_out_parts(self, lp, attn):
        p = lp["attn"]["c_proj"]
        return self._mm(attn, p["kernel"]), p["bias"]

    def _mlp_out_parts(self, lp, h2):
        m = lp["mlp"]
        ff = jax.nn.gelu(self._mm(h2, m["c_fc"]["kernel"]) +
                         m["c_fc"]["bias"], approximate=True)
        return self._mm(ff, m["c_proj"]["kernel"]), m["c_proj"]["bias"]

    def _layer_step(self, x, lp, ck, cv, tables, positions, flat_idx,
                    kv_len):
        cfg = self.cfg
        eps = cfg.layer_norm_epsilon
        h = self._ln(x, lp["ln_1"], eps)
        latent = h.astype(self.latent_dtype) \
            if self.capture_latents else jnp.zeros(
            (x.shape[0], x.shape[1], 0), h.dtype)
        q, k, v = self._qkv(lp, h, positions)
        ck, cv = self._scatter_kv(ck, cv, k, v, flat_idx)
        attn = self._paged_attention(q, ck, cv, tables, positions, kv_len)
        ap, ab = self._attn_out_parts(lp, attn)
        if self.tp > 1:
            ap = jax.lax.psum(ap, TENSOR_AXIS)
        x = x + ap + ab           # row bias once, after the psum
        h2 = self._ln(x, lp["ln_2"], eps)
        mp, mb = self._mlp_out_parts(lp, h2)
        if self.tp > 1:
            mp = jax.lax.psum(mp, TENSOR_AXIS)
        x = x + mp + mb
        return x.astype(cfg.compute_dtype), ck, cv, latent
