"""Paged-KV inference model for MoE (Mixtral-family) architectures.

Reference analog: the mixtral / qwen2-moe policies in
``deepspeed/inference/v2/engine_factory.py:69`` and the MoE module stack —
``modules/implementations/moe/cutlass_multi_gemm.py`` (top-k gating +
moe_scatter + grouped GEMM + moe_gather) backed by
``kernels/cutlass_ops/moe_gemm`` and ``kernels/ragged_ops/{top_k_gating,
moe_scatter,moe_gather}``.

TPU-native form: the llama paged trunk (:class:`PagedInferenceModel`)
with the dense SwiGLU MLP swapped for dropless routed experts — fp32
router, top-k renormalised gates, tokens sorted by expert with one
``lax.ragged_dot`` grouped GEMM per projection (``ops/grouped_gemm.py``),
segment-sum combine. No capacity buffers, no token drops — serving
latency must not depend on routing luck.

Consumes ``models.mixtral.MixtralForCausalLM`` training params directly
(``layers_i/mlp/moe/{wg, experts/{w1,w2,w3}}``), so a trained Mixtral
checkpoint (or the hybrid engine's live training params) serves without a
conversion step.

Tensor parallelism: expert FFN dims shard on ``tensor`` exactly like the
dense path (w1/w3 column, w2 row, one psum after combine); the router is
replicated. The expert mesh axis is a *training* concern (a2a dispatch,
``moe/layer.py``) — serving shards experts' insides, not their identity,
matching the reference's TP-sharded MoE inference.
"""

import jax
from jax.sharding import PartitionSpec as P

from ..models.mixtral import MixtralConfig
from ..moe.dropless import dropless_expert_ffn
from ..parallel.topology import TENSOR_AXIS
from .model import PagedInferenceModel, join_path


class PagedMoEModel(PagedInferenceModel):
    """Serves :class:`~..models.mixtral.MixtralConfig` checkpoints through
    the ragged engine (same ``forward_chunk`` / ``restore_kv`` / TP
    contract as the llama model)."""

    def __init__(self, cfg: MixtralConfig, params, **kw):
        if not isinstance(cfg, MixtralConfig):
            raise TypeError("PagedMoEModel needs a MixtralConfig")
        topo = kw.get("topology")
        quant = kw.get("quantization")
        if topo is not None and topo.tensor_size > 1 and quant is not None \
                and quant.enabled:
            # the ONLY rejection of TP+quantization (the base class
            # supports both int8 modes under TP via the k-major trunk
            # layout; expert stacks have no shard-aligned grouping)
            raise NotImplementedError(
                "tensor-parallel quantized serving is not available for "
                "the MoE family (expert-stack quantization groups are "
                "not shard-aligned)")
        super().__init__(cfg, params, **kw)

    def _validate_tp(self):
        super()._validate_tp()
        shared = getattr(self.cfg, "shared_expert_intermediate_size", 0)
        if shared and shared % self.tp:
            raise ValueError(
                f"shared_expert_intermediate_size={shared} not divisible "
                f"by tensor parallel degree {self.tp}")

    @staticmethod
    def _keep_fp32(path) -> bool:
        """The router weight stays fp32 (training gates run fp32,
        moe/layer.py:47; bf16 rounding of near-tie logits would select
        different experts at serve time than at train time)."""
        return str(getattr(path[-1], "key", path[-1])) == "wg"

    # -------------------------------------------------------------- #
    def _mlp_out(self, lp, h2):
        moe = lp["mlp"]["moe"]
        B, T, d = h2.shape
        renorm = getattr(self.cfg, "norm_topk_prob", True)
        out, _aux = dropless_expert_ffn(
            h2.reshape(B * T, d), moe["wg"], moe["experts"]["w1"],
            moe["experts"]["w3"], moe["experts"]["w2"], self.cfg.top_k,
            renorm)
        out = out.reshape(B, T, d)
        if "shared_gate_proj" in moe:   # qwen2-moe shared expert
            gate = self._mm(h2, moe["shared_gate_proj"]["kernel"])
            up = self._mm(h2, moe["shared_up_proj"]["kernel"])
            shared = self._mm(jax.nn.silu(gate) * up,
                              moe["shared_down_proj"]["kernel"])
            sg = h2 @ moe["shared_expert_gate"]["kernel"]
            out = out + jax.nn.sigmoid(sg) * shared
        if self.tp > 1:   # row-parallel partial sum over expert ff shards
            out = jax.lax.psum(out, TENSOR_AXIS)
        return out

    # -------------------------------------------------------------- #
    def _param_spec_tree(self, params=None):
        specs = super()._param_spec_tree(params)

        def fix(path, spec):
            joined = join_path(path)
            if "/moe/" in joined or joined.endswith("/wg"):
                if "shared" in joined:
                    # shared-expert kernels carry gate_proj/up_proj/
                    # down_proj in their names — the base col/row rules
                    # already classified them ("shared_expert_gate"
                    # matches neither and stays replicated)
                    return spec
                if "w1" in joined or "w3" in joined:
                    return P(None, None, None, TENSOR_AXIS)  # [L,E,d,f]
                if "w2" in joined:
                    return P(None, None, TENSOR_AXIS, None)  # [L,E,f,d]
                return P()                                   # router fp32
            return spec
        specs["layers"] = jax.tree_util.tree_map_with_path(
            fix, specs["layers"],
            is_leaf=lambda x: isinstance(x, P))
        return specs
