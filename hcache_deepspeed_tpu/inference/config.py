"""Inference-v2 engine configuration.

Reference analogs: ``deepspeed/inference/v2/config_v2.py``
(``RaggedInferenceEngineConfig``) and
``deepspeed/inference/v2/ragged/manager_configs.py``
(``DSStateManagerConfig``: max_tracked_sequences, max_ragged_batch_size,
max_ragged_sequence_count, memory_config). Same knob names where they still
mean something on TPU.
"""

from typing import Optional

from pydantic import Field

from ..runtime.config_utils import HDSConfigModel


class KVCacheConfig(HDSConfigModel):
    """Reference: ``AllocationMode``/``KVCacheConfig`` in manager_configs —
     'reserve' (fraction of free HBM) or explicit block count."""
    block_size: int = 64              # tokens per KV block (ref: KV_BLOCK)
    num_blocks: Optional[int] = None  # explicit pool size
    memory_fraction: float = 0.8      # used when num_blocks is None (TPU:
    #                                   sized from platform free-memory)
    cache_dtype: str = "bfloat16"


class StateManagerConfig(HDSConfigModel):
    max_tracked_sequences: int = 2048
    max_ragged_batch_size: int = 768      # max total tokens per forward
    max_ragged_sequence_count: int = 512  # max sequences per forward
    max_context: int = 8192               # max tokens of any one sequence
    #: > 0: prefills longer than this process in chunks of this size
    #: (the FastGen Dynamic-SplitFuse idea) — prompt length is then
    #: bounded by max_context, not by the per-forward token budget,
    #: and long prefills stop monopolizing a forward
    prefill_chunk: int = Field(0, ge=0)
    #: share full KV blocks across sequences with identical prompt
    #: prefixes (system prompts): a new sequence attaches the matching
    #: blocks by reference and prefills only the tail. Requires
    #: hcache.enable_latents=false (shared prefixes produce no latents,
    #: which would break the restore contract). No reference analog —
    #: FastGen lacks prefix caching.
    prefix_caching: bool = False


class HCacheConfig(HDSConfigModel):
    """The fork delta: latent capture + restore_kv (no reference config —
    the fork hard-enables it; here it is a switch)."""
    enable_latents: bool = True
    #: layers replayed per restore dispatch. 0 = auto: group layers so
    #: each chunk's latent slab is ~restore_chunk_bytes (per-layer
    #: dispatch — the reference's literal dual-stream shape — is
    #: latency-bound when the host link is slow; one giant dispatch
    #: can't overlap H2D with compute and caps at available HBM for
    #: million-token contexts; chunking interpolates)
    restore_chunk_layers: int = Field(0, ge=0)
    restore_chunk_bytes: int = 64 * 1024 * 1024
    #: dtype latents are captured/stored/shipped in; "" = the model's
    #: compute dtype (bit-exact restore). Restore is host-link-
    #: bandwidth-bound and latents live in host DRAM per evicted
    #: sequence, so "float8_e4m3fn" halves both the wire time and the
    #: storage bill for ~0.4% K/V error (latents are post-norm, O(1)
    #: scale — comfortably inside e4m3 range); K/V projections replay
    #: in the compute dtype either way
    latent_dtype: str = ""


class QuantizationConfig(HDSConfigModel):
    """Weight-only serving quantization (reference:
    ``deepspeed/inference/quantization`` — v1's int8 QuantLinear / MoQ
    checkpoints). Weights are stored int8 with per-group scales and
    dequantized inside the compiled forward; ~2x HBM capacity for
    weights at a small accuracy cost."""
    enabled: bool = False
    bits: int = 8
    group_size: int = 256
    #: leaves smaller than this stay full precision (norms, biases)
    min_size: int = 4096
    #: route the llama-trunk families' layer matmuls through the fused
    #: int8-weight Pallas kernel (ops/quantized_matmul.py) instead of
    #: dequantize-then-matmul — weights stream int8 from HBM and
    #: dequantize tile-by-tile in VMEM. Default ON: measured 12.8 vs
    #: 81.4 ms/token 7B decode floors (DECODE_DIAG_7B_FLOORS_V2); the
    #: kernel falls back to the dequant path per-matmul for shapes its
    #: tiles cannot cover and on platforms without Pallas, so the flag
    #: is a measurement escape hatch, not a safety knob.
    use_fused_kernel: bool = True


class RaggedInferenceEngineConfig(HDSConfigModel):
    state_manager: StateManagerConfig = Field(
        default_factory=StateManagerConfig)
    kv_cache: KVCacheConfig = Field(default_factory=KVCacheConfig)
    hcache: HCacheConfig = Field(default_factory=HCacheConfig)
    quantization: QuantizationConfig = Field(
        default_factory=QuantizationConfig)
    # tensor_parallel degree for sharding the KV-head dim over the mesh
    tensor_parallel: int = 1
