"""Paged-KV serving for the Falcon family.

Reference analog: the falcon policy in
``deepspeed/inference/v2/engine_factory.py:69`` +
``model_implementations/falcon/``. Reuses the llama paged trunk's
KV plumbing (RoPE + GQA/MQA paged attention); overrides the layer to
Falcon's **parallel** form — one shared LayerNorm feeding both the
attention and GELU-MLP branches — and the final norm to LayerNorm.

Latents (HCache) = the post-input_layernorm hidden states, the same
pre-QKV snapshot the llama model uses, so ``restore_kv`` (QKV-only
replay) works unchanged.

Tensor-parallel serving: GQA configs shard q + kv heads and the MLP
dims (one psum covers the attention and MLP row-parallel partials of
the parallel block); MQA (n_kv_head=1) is rejected — it would need KV
replication, which the cache layout doesn't model.
"""

import jax
import jax.numpy as jnp

from ..models.falcon import FalconConfig
from ..parallel.topology import TENSOR_AXIS
from .model import PagedInferenceModel, stack_layer_params


class PagedFalconModel(PagedInferenceModel):
    def __init__(self, cfg: FalconConfig, params, **kw):
        if not isinstance(cfg, FalconConfig):
            raise TypeError("PagedFalconModel needs a FalconConfig")
        super().__init__(cfg, params, **kw)

    def _validate_tp(self):
        """GQA falcon (40b/180b-style) shards KV heads; MQA (falcon-7b,
        n_kv_head=1) would need KV replication — rejected explicitly."""
        cfg, tp = self.cfg, self.tp
        for name, val in (("n_head", cfg.n_head),
                          ("n_kv_head", cfg.n_kv_head),
                          ("ffn_dim", cfg.ffn_dim),
                          ("vocab_size", cfg.vocab_size)):
            if val % tp:
                raise ValueError(f"{name}={val} not divisible by "
                                 f"tensor parallel degree {tp}")

    _COL_NAMES = ("q_proj", "k_proj", "v_proj", "dense_h_to_4h")
    _ROW_NAMES = ("o_proj", "dense_4h_to_h")

    def load_params(self, params):
        new = {
            "embed": params["embed_tokens"]["embedding"],
            "norm": {k: params["ln_f"][k] for k in ("scale", "bias")},
            "layers": stack_layer_params(params, self.cfg.n_layer),
        }
        if not self.tied:
            new["lm_head"] = params["lm_head"]["kernel"]
        self.params = self._finalize_params(new)

    @staticmethod
    def _ln(x, p, eps):
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        out = (xf - mu) * jax.lax.rsqrt(var + eps)
        return (out * p["scale"] + p["bias"]).astype(x.dtype)

    def _final_norm(self, params, x):
        return self._ln(x, params["norm"], self.cfg.layer_norm_epsilon)

    def _layer_step(self, x, lp, ck, cv, tables, positions, flat_idx,
                    kv_len):
        """Parallel residual (falcon-7b): x + attn(h) + mlp(h) with ONE
        shared input LayerNorm h."""
        cfg = self.cfg
        h = self._ln(x, lp["input_layernorm"], cfg.layer_norm_epsilon)
        latent = h.astype(self.latent_dtype) \
            if self.capture_latents else jnp.zeros(
            (x.shape[0], x.shape[1], 0), h.dtype)
        q, k, v = self._qkv(lp, h, positions)
        ck, cv = self._scatter_kv(ck, cv, k, v, flat_idx)
        attn = self._paged_attention(q, ck, cv, tables, positions, kv_len)
        attn = self._mm(attn, lp["self_attn"]["o_proj"]["kernel"])
        up = self._mm(h, lp["dense_h_to_4h"]["kernel"])
        mlp = self._mm(jax.nn.gelu(up), lp["dense_4h_to_h"]["kernel"])
        both = attn + mlp
        if self.tp > 1:   # one psum covers both row-parallel partials
            both = jax.lax.psum(both, TENSOR_AXIS)
        x = x + both
        return x.astype(cfg.compute_dtype), ck, cv, latent
