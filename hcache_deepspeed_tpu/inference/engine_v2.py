"""Ragged-batching inference engine with HCache KV restoration.

Reference analog: ``deepspeed/inference/v2/engine_v2.py:30
InferenceEngineV2`` — ``put`` (:131), ``can_schedule``/``query``
(:191-264), ``flush`` (:275), ``serialize`` (:284) and the fork's
``restore_kv`` (:108-129).

TPU-native scheduling: a ``put`` batch is routed into at most one batched
decode dispatch (all single-token sequences together — the ragged decode
batch) plus one bucketed prefill dispatch per multi-token sequence; each
(batch, tokens) bucket shape compiles once and is cached by XLA. The
reference's atom-builder/CUDA-graph machinery dissolves into those static
buckets.
"""

import functools
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..platform import get_platform
from ..resilience.faults import get_injector
from ..telemetry.tracer import get_tracer
from ..utils.logging import log_dist
from .config import RaggedInferenceEngineConfig
from .model import PagedInferenceModel
from .ragged.kv_cache import BlockedKVCache, StateManager
from .scheduling import SchedulingError, SchedulingResult


def _annotated(name):
    """Trace-annotate a serving entry point (reference:
    instrument_w_nvtx on the v2 engine's hot methods). ``get_platform``
    is called per invocation (cheap singleton) so test platform
    overrides are respected."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with get_platform().annotate(name):
                return fn(*args, **kwargs)
        return wrapper
    return deco


@dataclass
class RestoreTicket:
    """Handle for one ``begin_restore`` batch: ``done`` flips when
    every lane the batch staged has issued its last replay chunk (the
    sequences are then decodable)."""
    uids: List[int] = field(default_factory=list)
    pending: int = 0          # lanes still open
    done: bool = False


@dataclass
class _RestoreLane:
    """One bucket group's open restore pipeline + the state ops owed
    at completion."""
    pipe: object
    seqs: List[object]
    uids: List[int]
    ticket: RestoreTicket


def _bucket(n: int, minimum: int = 8) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def _logsumexp_rows(logits):
    """Row-wise logsumexp, keepdims (fp64 host math for the first-token
    logprob — the decode-loop tokens get theirs on device)."""
    x = logits.astype(np.float64)
    m = x.max(axis=-1, keepdims=True)
    return m + np.log(np.exp(x - m).sum(axis=-1, keepdims=True))


def _sample_host(row, rng, temperature, top_k, top_p):
    """Host-side token sampler (greedy / temperature / top-k / nucleus) —
    shared by generate()'s step loop and generate_fused()'s first token."""
    if temperature <= 0:
        return int(np.argmax(row))
    logits = row.astype(np.float64) / temperature
    k = min(top_k, len(logits))
    if k > 0:
        kth = np.partition(logits, -k)[-k]
        logits = np.where(logits < kth, -np.inf, logits)
    p = np.exp(logits - logits.max())
    p /= p.sum()
    if top_p < 1.0:
        # nucleus: smallest prob-sorted set with mass >= top_p
        order = np.argsort(p)[::-1]
        keep_sorted = np.cumsum(p[order]) - p[order] < top_p
        keep = np.zeros_like(p, dtype=bool)
        keep[order] = keep_sorted
        p = np.where(keep, p, 0.0)
        p /= p.sum()
    return int(rng.choice(len(p), p=p))


class InferenceEngineV2:

    def __init__(self, model_config, params,
                 config: RaggedInferenceEngineConfig = None,
                 topology=None):
        """``topology``: a MeshTopology with a ``tensor`` axis enables
        tensor-parallel serving — sharded heads/KV blocks, per-layer
        allreduce (reference: TP sharding throughout the v2 model
        implementations, llama_v2/model.py:160,169)."""
        self.config = config or RaggedInferenceEngineConfig()
        self.topology = topology
        sm_cfg = self.config.state_manager
        kv_cfg = self.config.kv_cache

        self.block_size = kv_cfg.block_size
        self.max_context = min(sm_cfg.max_context,
                               model_config.max_positions)
        self.max_blocks_per_seq = -(-self.max_context // self.block_size)

        num_blocks = kv_cfg.num_blocks
        if num_blocks is None:
            # reserve mode, capped at what tracked sequences can ever use
            cap = sm_cfg.max_tracked_sequences * self.max_blocks_per_seq + 1
            num_blocks = min(self._size_cache_blocks(model_config, kv_cfg),
                             cap)
        self._model_config = model_config

        self.state = StateManager(sm_cfg.max_tracked_sequences,
                                  num_blocks, self.block_size,
                                  self.max_context)
        # block 0 is reserved scratch: padded decode lanes write there
        self._scratch_block = self.state.allocator.allocate(1)[0]

        self.prefix_caching = sm_cfg.prefix_caching
        if self.prefix_caching and self.config.hcache.enable_latents:
            raise ValueError(
                "prefix_caching requires hcache.enable_latents=false: a "
                "shared prefix runs no forward, so its latents would be "
                "missing from the HCache restore contract")
        #: chained prefix index: (parent block id, this block's tokens)
        #: -> block id. KV content depends on the ENTIRE context, so the
        #: key must identify the full prefix — the parent block id does
        #: that transitively (a block is registered under exactly one
        #: chain, and a child entry keeps its parent alive through the
        #: owning sequence's refs), giving O(P) lookups instead of
        #: O(P^2) full-prefix tuples. _block_prefix is the reverse map
        #: for purge.
        self._prefix_index: Dict[tuple, int] = {}
        self._block_prefix: Dict[int, tuple] = {}
        #: parent block id -> chain keys registered under it (purge of a
        #: parent must drop its now-unreachable subtree)
        self._chain_children: Dict[int, set] = {}
        #: observability: prompts that attached >= 1 shared block, and
        #: prompt tokens whose prefill was skipped entirely
        self.prefix_stats = {"hits": 0, "shared_tokens": 0}
        #: bumped on every purge: sequences cache their chain-walk tip
        #: keyed on this epoch, so registration is O(new blocks) in the
        #: common case and only re-walks from the root after a purge
        self._index_epoch = 1

        from ..models.falcon import FalconConfig
        from ..models.gpt2 import GPT2Config
        from ..models.mixtral import MixtralConfig
        from ..models.opt import OPTConfig
        from ..models.phi import PhiConfig
        model_cls = PagedInferenceModel
        if isinstance(model_config, GPT2Config):
            from .model_gpt2 import PagedGPT2Model
            model_cls = PagedGPT2Model
        elif isinstance(model_config, OPTConfig):
            from .model_opt import PagedOPTModel
            model_cls = PagedOPTModel
        elif isinstance(model_config, FalconConfig):
            from .model_falcon import PagedFalconModel
            model_cls = PagedFalconModel
        elif isinstance(model_config, PhiConfig):
            from .model_phi import PagedPhiModel
            model_cls = PagedPhiModel
        elif isinstance(model_config, MixtralConfig):
            from .model_moe import PagedMoEModel
            model_cls = PagedMoEModel
        self.model = model_cls(
            model_config, params, block_size=self.block_size,
            max_blocks_per_seq=self.max_blocks_per_seq,
            capture_latents=self.config.hcache.enable_latents,
            restore_chunk_layers=self.config.hcache.restore_chunk_layers,
            restore_chunk_bytes=self.config.hcache.restore_chunk_bytes,
            latent_dtype=self.config.hcache.latent_dtype,
            topology=topology, quantization=self.config.quantization)
        self.cache = BlockedKVCache(
            model_config.n_layer, num_blocks, self.block_size,
            model_config.n_kv_head, model_config.head_dim,
            dtype=jnp.dtype(kv_cfg.cache_dtype),
            sharding=self.model.cache_sharding())
        #: restore staging progress for the serving layer: cumulative
        #: counts of restore groups, sequences, per-chunk dispatches
        #: issued and latent bytes shipped host->device (a dispatch is
        #: counted when ISSUED, not when it lands — the serving
        #: scheduler overlaps the in-flight ship with resident decode)
        self.restore_stats = {"restores": 0, "sequences": 0,
                              "chunks_issued": 0, "bytes_shipped": 0}
        #: open decode-interleaved restore lanes (FIFO), advanced by
        #: advance_restores between the scheduler's decode dispatches
        self._restore_lanes: List[_RestoreLane] = []
        log_dist(f"InferenceEngineV2: {num_blocks} KV blocks x "
                 f"{self.block_size} tokens, max_context="
                 f"{self.max_context}", ranks=[0])

    @staticmethod
    def _size_cache_blocks(model_config, kv_cfg) -> int:
        """'reserve' allocation mode: size the pool from free device memory
        (reference: memory_config reserve fraction)."""
        from ..platform import get_platform
        per_token = BlockedKVCache.token_bytes(
            model_config.n_layer, model_config.n_kv_head,
            model_config.head_dim, kv_cfg.cache_dtype)
        free = get_platform().available_memory()
        if free <= 0:          # unknown limit (e.g. CPU test platform)
            free = 1 << 30
        blocks = int(free * kv_cfg.memory_fraction /
                     (per_token * kv_cfg.block_size))
        return max(blocks, 16)

    # -------------------------------------------------------------- #
    # Scheduling API (reference: engine_v2.py:191-264)
    # -------------------------------------------------------------- #
    def query(self, uid: int, max_request_tokens: int,
              max_request_blocks: int) -> Tuple[int, int]:
        """Token/block budget for a request (reference :191): how many
        tokens of this sequence could be scheduled and the blocks needed."""
        seq = self.state.get_sequence(uid)
        seen = seq.seen_tokens if seq else 0
        max_tokens = min(max_request_tokens, self.max_context - seen)
        blocks = self.state.blocks_needed(seq, max_tokens)
        return max_tokens, min(blocks, max_request_blocks)

    @property
    def free_blocks(self) -> int:
        """Free KV-pool blocks right now (serving-layer admission and
        preemption decisions read this between steps)."""
        return self.state.free_blocks

    def can_schedule(self, uids: Iterable[int],
                     lengths: Iterable[int]) -> SchedulingResult:
        uids, lengths = list(uids), list(lengths)
        sm = self.config.state_manager
        new_seqs = sum(1 for u in uids if self.state.get_sequence(u) is None)
        if self.state.n_tracked_sequences + new_seqs > \
                sm.max_tracked_sequences:
            return SchedulingResult.EngineSequenceLimitExceeded
        if len(uids) > sm.max_ragged_sequence_count:
            return SchedulingResult.BatchSequenceLimitExceeded
        # with chunked prefill each forward sees at most prefill_chunk
        # tokens per sequence, so the batch budget counts the chunk
        per_fwd = [min(n, sm.prefill_chunk) if sm.prefill_chunk else n
                   for n in lengths]
        if sum(per_fwd) > sm.max_ragged_batch_size:
            return SchedulingResult.BatchTokenLimitExceeded
        blocks = 0
        for uid, n in zip(uids, lengths):
            seq = self.state.get_sequence(uid)
            seen = seq.seen_tokens if seq else 0
            if seen + n > self.max_context:
                return SchedulingResult.SequenceTokenLimitExceeded
            blocks += self.state.blocks_needed(seq, n)
        if blocks > self.state.free_blocks:
            return SchedulingResult.KVCacheLimitExceeded
        return SchedulingResult.Success

    # -------------------------------------------------------------- #
    # put (reference: engine_v2.py:131)
    # -------------------------------------------------------------- #
    @_annotated("hds.serve.put")
    def put(self, batch_uids: Iterable[int],
            batch_tokens: Iterable, do_checks: bool = True,
            defer_fetch: bool = False):
        """One forward over a ragged batch. Returns
        ``(logits [n_seqs, vocab], latents)`` where ``latents[i]`` is the
        per-sequence host array [L, new_tokens, H] (None when HCache latent
        capture is disabled).

        ``defer_fetch=True`` skips every device→host fetch: calls then
        chain on-device without a host sync per dispatch (the
        marginal-cost measurement mode; plain path only — incompatible
        with latent capture, prefix caching and chunked prefill). The
        logits return is then a per-uid list of ``(device_array, lane)``
        pairs — ``np.asarray(device_array)[lane]`` is that uid's row;
        sequences dispatched in one group share the same padded device
        array."""
        batch_uids = list(batch_uids)
        batch_tokens = [np.asarray(t, np.int32).reshape(-1)
                        for t in batch_tokens]
        tracer = get_tracer()
        if tracer.enabled:
            tracer.instant("serve.put", n_seqs=len(batch_uids),
                           tokens=int(sum(len(t) for t in batch_tokens)))
        if do_checks:
            # NOTE: with prefix caching the block budget is conservative
            # (checked before any prefix attaches reduce the real need)
            result = self.can_schedule(batch_uids,
                                       [len(t) for t in batch_tokens])
            if result != SchedulingResult.Success:
                raise SchedulingError(result)
        self._reject_suspended(batch_uids)
        _inj = get_injector()
        if _inj.enabled and batch_uids:
            # resilience fault site: before any state mutation, so a
            # faulted dispatch is retryable / its batch quarantinable
            _inj.fire("engine.prefill"
                      if any(len(t) > 1 for t in batch_tokens)
                      else "engine.decode",
                      uid=batch_uids[-1], uids=tuple(batch_uids))
        if defer_fetch and (self.prefix_caching or
                            self.config.hcache.enable_latents or
                            self.config.state_manager.prefill_chunk):
            raise ValueError(
                "defer_fetch supports only the plain put() path (no "
                "prefix caching, latent capture, or chunked prefill)")
        if self.prefix_caching:
            # two-wave in-batch dedup: a new prompt that could share a
            # prefix with an EARLIER new prompt in this same call defers
            # to a second wave — wave 1 writes and registers the blocks,
            # wave 2 then attaches them from the index (sharing within
            # one dispatch is impossible: the blocks don't exist yet)
            wave2 = self._defer_in_batch_duplicates(batch_uids,
                                                    batch_tokens)
            if wave2:
                keep = [i for i in range(len(batch_uids))
                        if i not in wave2]
                l1, _ = self.put([batch_uids[i] for i in keep],
                                 [batch_tokens[i] for i in keep],
                                 do_checks=False)
                l2, _ = self.put([batch_uids[i] for i in wave2],
                                 [batch_tokens[i] for i in wave2],
                                 do_checks=False)
                logits = np.zeros((len(batch_uids),) + l1.shape[1:],
                                  l1.dtype)
                logits[keep] = l1
                logits[list(wave2)] = l2
                # dropping l1/l2 latents is only sound because the
                # constructor forbids prefix_caching with latent capture
                # — pin that invariant here so relaxing it elsewhere
                # can't silently lose latents
                assert not self.config.hcache.enable_latents, (
                    "wave-split put() discards latents; prefix_caching "
                    "with hcache.enable_latents must stay mutually "
                    "exclusive")
                return logits, [None] * len(batch_uids)
            batch_tokens = self._attach_shared_prefixes(batch_uids,
                                                        batch_tokens)
            processed = [list(t) for t in batch_tokens]

        # chunked prefill (Dynamic SplitFuse): run the leading chunks of
        # long prompts round by round — all sequences' chunk-k heads
        # share ONE dispatch (the shape can_schedule budgeted), KV
        # allocated as it grows, latents accumulated — leaving tails
        # <= chunk for the normal mixed decode/prefill batch below
        chunk = self.config.state_manager.prefill_chunk
        lead_latents: Dict[int, List] = {}
        if chunk:
            while True:
                long_idx = [i for i, t in enumerate(batch_tokens)
                            if len(t) > chunk]
                if not long_idx:
                    break
                heads: List = [None] * len(batch_tokens)
                for i in long_idx:
                    heads[i] = batch_tokens[i][:chunk]
                    seq = self.state.get_or_create_sequence(batch_uids[i])
                    self.state.maybe_allocate_kv(seq, chunk)
                    seq.pre_forward(chunk)
                part_l: List = [None] * len(batch_tokens)
                part_t: List = [None] * len(batch_tokens)
                self._run_prefill(batch_uids, heads, long_idx,
                                  _bucket(chunk), part_l, part_t)
                for i in long_idx:
                    self.state.get_sequence(batch_uids[i]).post_forward()
                    if self.config.hcache.enable_latents:
                        lead_latents.setdefault(i, []).append(part_t[i])
                    batch_tokens[i] = batch_tokens[i][chunk:]

        for uid, tokens in zip(batch_uids, batch_tokens):
            seq = self.state.get_or_create_sequence(uid)
            self.state.maybe_allocate_kv(seq, len(tokens))
            seq.pre_forward(len(tokens))

        # route: single-token continuations -> one batched decode;
        # everything else -> per-sequence bucketed prefill
        decode_idx = [i for i, (u, t) in enumerate(
            zip(batch_uids, batch_tokens))
            if len(t) == 1 and self.state.get_sequence(u).seen_tokens > 0]
        prefill_idx = [i for i in range(len(batch_uids))
                       if i not in decode_idx]

        n = len(batch_uids)
        logits_out: List = [None] * n
        latents_out: List = [None] * n

        if decode_idx:
            self._run_decode(batch_uids, batch_tokens, decode_idx,
                             logits_out, latents_out, defer=defer_fetch)
        # prefills batch per length bucket: one dispatch per (B, T)
        # bucket instead of one jit call per sequence (round-1 latency
        # hygiene finding; reference batches prefills in one ragged pass)
        groups: Dict[int, List[int]] = {}
        for i in prefill_idx:
            groups.setdefault(_bucket(len(batch_tokens[i])), []).append(i)
        for T, idx in sorted(groups.items()):
            self._run_prefill(batch_uids, batch_tokens, idx, T,
                              logits_out, latents_out, defer=defer_fetch)

        for uid in batch_uids:
            self.state.get_sequence(uid).post_forward()

        if self.prefix_caching:
            for uid, toks in zip(batch_uids, processed):
                seq = self.state.get_sequence(uid)
                seq.history.extend(int(t) for t in toks)
                self._register_full_blocks(seq)

        if lead_latents:   # chunked prefill: stitch per-chunk latents
            for i, parts in lead_latents.items():
                tail = [latents_out[i]] if latents_out[i] is not None \
                    else []
                latents_out[i] = np.concatenate(parts + tail, axis=1)

        if defer_fetch:
            return logits_out, latents_out
        return np.stack(logits_out), latents_out

    def _tables(self, idx, uids):
        return np.stack([
            self.state.block_table(self.state.get_sequence(uids[i]),
                                   self.max_blocks_per_seq) for i in idx])

    def _blank_lanes(self, B, T=1):
        """Padded-lane scaffolding shared by every batched dispatch:
        zeroed tokens/start/t_len plus tables whose padded lanes point at
        the scratch block (their writes drop on t_len=0 anyway)."""
        tok = np.zeros((B, T), np.int32)
        start = np.zeros((B,), np.int32)
        t_len = np.zeros((B,), np.int32)
        tables = np.zeros((B, self.max_blocks_per_seq), np.int32)
        tables[:, 0] = self._scratch_block
        return tok, start, t_len, tables

    def _run_decode(self, uids, tokens, idx, logits_out, latents_out,
                    defer=False):
        B = _bucket(len(idx))
        tok, start, t_len, tables = self._blank_lanes(B)
        tables[:len(idx)] = self._tables(idx, uids)
        for j, i in enumerate(idx):
            tok[j, 0] = tokens[i][0]
            start[j] = self.state.get_sequence(uids[i]).seen_tokens
            t_len[j] = 1
        with get_tracer().span("serve.decode_dispatch",
                               lanes=len(idx), bucket=B):
            logits, latents = self.model.forward_chunk(
                self.cache, tok, start, tables, t_len)
        if defer:   # keep the device array whole (row slicing here would
            for j, i in enumerate(idx):   # dispatch an op per lane) —
                logits_out[i] = (logits, j)   # every uid gets its lane
            return
        logits = np.asarray(logits)
        if self.config.hcache.enable_latents:
            latents = np.asarray(latents)      # [L, B, 1, H] -> host
        for j, i in enumerate(idx):
            logits_out[i] = logits[j]
            if self.config.hcache.enable_latents:
                latents_out[i] = latents[:, j]

    def _run_prefill(self, uids, tokens, idx, T, logits_out, latents_out,
                     defer=False):
        """One batched dispatch for all prefills in a length bucket;
        padded rows (t_len=0) write to the scratch block like padded
        decode lanes."""
        B = _bucket(len(idx), minimum=1)
        tok, start, t_len, tables = self._blank_lanes(B, T)
        tables[:len(idx)] = self._tables(idx, uids)
        for j, i in enumerate(idx):
            seq = self.state.get_sequence(uids[i])
            tok[j, :len(tokens[i])] = tokens[i]
            start[j] = seq.seen_tokens
            t_len[j] = len(tokens[i])
        with get_tracer().span("serve.prefill_dispatch",
                               lanes=len(idx), bucket=B, bucket_T=T,
                               tokens=int(sum(len(tokens[i])
                                              for i in idx))):
            logits, latents = self.model.forward_chunk(
                self.cache, tok, start, tables, t_len)
        if defer:
            for j, i in enumerate(idx):
                logits_out[i] = (logits, j)
            return
        logits = np.asarray(logits)
        if self.config.hcache.enable_latents:
            latents = np.asarray(latents)      # [L, B, T, H]
        for j, i in enumerate(idx):
            logits_out[i] = logits[j]
            if self.config.hcache.enable_latents:
                latents_out[i] = latents[:, j, :len(tokens[i])]

    # -------------------------------------------------------------- #
    # Serving loop (reference: the generate() surface the v1 engine
    # exposes via HF and hybrid_engine.py wraps; v2's counterpart is the
    # mii serving loop — here a built-in utility)
    # -------------------------------------------------------------- #
    def generate(self, prompts, max_new_tokens: int = 32,
                 eos_token_id: int = None, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                 return_logits: bool = False):
        """Batched prefill + ragged decode loop.

        ``prompts``: list of token-id lists. Greedy when temperature==0,
        else softmax sampling (optionally top-k and/or nucleus top-p).
        Returns the generated continuations (without the prompt), plus
        per-step logits when ``return_logits`` (for RLHF-style log-prob
        computation). Sequences are flushed from the KV cache on
        completion.
        """
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        rng = np.random.default_rng(seed)
        base = max(self.state._seqs.keys(), default=-1) + 1
        uids = [base + i for i in range(len(prompts))]

        def sample(row):
            return _sample_host(row, rng, temperature, top_k, top_p)

        outs = [[] for _ in prompts]
        logit_trace = [[] for _ in prompts]
        for p in prompts:
            if len(p) + max_new_tokens > self.max_context:
                raise SchedulingError(
                    SchedulingResult.SequenceTokenLimitExceeded)

        def need_blocks(i):
            """Whole-generation KV budget, committed at admission."""
            return -(-(len(prompts[i]) + max_new_tokens) //
                     self.block_size) + 1

        # Continuous batching (the FastGen scheduler semantics): every
        # iteration admits whatever pending prompts still fit, then runs
        # ONE ragged put() mixing their prefills with the active
        # sequences' decodes; finished sequences flush mid-flight and
        # their blocks let new prompts join without draining the batch.
        pending = list(range(len(prompts)))
        active: List[int] = []
        live: List[int] = []            # active + this step's admissions
        reserved: Dict[int, int] = {}   # admission-time block commitment
        cur: Dict[int, np.ndarray] = {}
        # admission headroom changes when a sequence finishes (KV blocks
        # free) AND one step after any prefill (the ragged token budget
        # that blocked a co-admission frees once the prefill becomes a
        # 1-token decode)
        headroom_changed = True
        try:
            while pending or active:
                admit = []
                if pending and headroom_changed:
                    # headroom the still-running reservations hold back
                    # (measured against the allocator's own state, not a
                    # re-derivation of its policy)
                    held = sum(
                        reserved[i] - self.state.get_sequence(
                            uids[i]).cur_allocated_blocks - 1
                        for i in active)
                    blocks_left = self.state.allocator.free_blocks - held
                    for i in list(pending):
                        cand = admit + [i]
                        if need_blocks(i) > blocks_left:
                            continue
                        lens = [1] * len(active) + \
                            [len(prompts[j]) for j in cand]
                        uid_c = [uids[j] for j in active + cand]
                        if self.can_schedule(uid_c, lens) == \
                                SchedulingResult.Success:
                            admit.append(i)
                            blocks_left -= need_blocks(i)
                headroom_changed = bool(admit)
                if not active and not admit:
                    # nothing fits even alone — surface the verdict
                    i = pending[0]
                    result = self.can_schedule([uids[i]],
                                               [len(prompts[i])])
                    raise SchedulingError(
                        result if result != SchedulingResult.Success
                        else SchedulingResult.KVCacheLimitExceeded)

                step = active + admit
                live = step   # put() may allocate before raising
                toks = [[outs[i][-1]] for i in active] + \
                    [prompts[i] for i in admit]
                step_logits, _ = self.put([uids[i] for i in step], toks)
                for j, i in enumerate(step):
                    cur[i] = step_logits[j]
                for i in admit:
                    reserved[i] = need_blocks(i)
                pending = [i for i in pending if i not in admit]
                active = step

                finished = []
                for i in active:
                    tok = sample(cur[i])
                    outs[i].append(tok)
                    if return_logits:
                        logit_trace[i].append(cur[i])
                    if (eos_token_id is not None and
                            tok == eos_token_id) or \
                            len(outs[i]) >= max_new_tokens:
                        finished.append(i)
                for i in finished:
                    self.flush(uids[i])
                    reserved.pop(i, None)
                    headroom_changed = True
                active = [i for i in active if i not in finished]
        finally:
            for i in set(active) | set(live):
                if self.state.get_sequence(uids[i]) is not None:
                    self.flush(uids[i])
        if return_logits:
            return outs, [np.stack(t) if t else None for t in logit_trace]
        return outs

    # -------------------------------------------------------------- #
    # Fused decode: N greedy steps per device program (TPU-native — the
    # host-driven generate() above pays a host round-trip per token; this
    # compiles the whole decode stretch, reference has no analog because
    # its engine must rebuild the ragged batch host-side each step)
    # -------------------------------------------------------------- #
    @_annotated("hds.serve.generate_fused")
    def generate_fused(self, prompts, max_new_tokens: int = 32,
                       eos_token_id: int = None, temperature: float = 0.0,
                       top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                       return_logprobs: bool = False):
        """Batched generation with on-device token feedback.

        Prefill runs through :meth:`put` (capturing latents as usual);
        the decode stretch then runs as ONE jitted ``lax.scan`` — the
        sampled token (greedy argmax when temperature<=0, else
        temperature/top-k/top-p via a threaded PRNG key) feeds the next
        step on device, so the host syncs once per *generation*, not
        once per token. temperature/top_p are traced (per-request values
        reuse the compiled program); only the sampling MODE, top_k and
        n_steps recompile. KV blocks for the whole stretch are reserved
        up front. Returns ``(outs, latents)`` — or ``(outs, latents,
        logprobs)`` with per-generated-token raw-model logprobs (RLHF
        consumers) when ``return_logprobs`` — where ``latents[i]``
        covers prompt + fed tokens (None when latent capture is off) —
        a returning sequence can be HCache-restored from them after a
        flush."""
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        base = max(self.state._seqs.keys(), default=-1) + 1
        uids = [base + i for i in range(len(prompts))]
        n_feed = max_new_tokens - 1   # tokens fed (and cached) on device
        # per-forward batch budget sees only the prompts (the fused loop
        # runs 1 token/lane); context + KV-block budgets must cover the
        # whole stretch
        result = self.can_schedule(uids, [len(p) for p in prompts])
        if result != SchedulingResult.Success:
            raise SchedulingError(result)
        blocks = 0
        for p in prompts:
            if len(p) + n_feed > self.max_context:
                raise SchedulingError(
                    SchedulingResult.SequenceTokenLimitExceeded)
            blocks += -(-(len(p) + n_feed) // self.block_size)
        if blocks > self.state.free_blocks:
            raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)
        try:
            logits, latents = self.put(uids, prompts)
            host_rng = np.random.default_rng(seed)
            first = np.asarray(
                [_sample_host(row, host_rng, temperature, top_k, top_p)
                 for row in logits], np.int32)                    # [n]
            outs = [[int(t)] for t in first]
            logprobs = None
            if return_logprobs:
                lse = _logsumexp_rows(logits)
                logprobs = [[float(logits[j, first[j]] - lse[j, 0])]
                            for j in range(len(uids))]
            if n_feed > 0:
                n = len(uids)
                tok, start, t_len, tables = self._blank_lanes(_bucket(n))
                for j, uid in enumerate(uids):
                    seq = self.state.get_sequence(uid)
                    self.state.maybe_allocate_kv(seq, n_feed)
                    seq.pre_forward(n_feed)
                    tok[j, 0] = first[j]
                    start[j] = seq.seen_tokens
                    t_len[j] = 1
                tables[:n] = self._tables(list(range(n)), uids)
                # already-finished lanes (EOS on the first token) join
                # as done so they neither feed nor block the early exit
                if eos_token_id is not None:
                    for j in range(n):
                        if outs[j][0] == eos_token_id:
                            t_len[j] = 0
                with get_tracer().span("serve.fused_decode",
                                       lanes=n, n_feed=n_feed):
                    toks, lats, lps = self.model.decode_loop(
                        self.cache, tok[:, 0], start, t_len, tables,
                        n_feed, temperature=temperature, top_k=top_k,
                        top_p=top_p, seed=seed,
                        want_logprobs=return_logprobs,
                        eos_token_id=eos_token_id)
                for j, uid in enumerate(uids):
                    self.state.get_sequence(uid).post_forward()
                    outs[j].extend(int(t) for t in toks[:, j])
                    if return_logprobs:
                        logprobs[j].extend(float(x) for x in lps[:, j])
                if self.config.hcache.enable_latents:
                    # slice to live lanes on device: padded bucket lanes
                    # would otherwise ride the D2H copy
                    lats = np.asarray(lats[:, :, :n])  # [n_feed,L,n,1,H]
                    for j in range(n):
                        fed = lats[:, :, j, 0].transpose(1, 0, 2)
                        latents[j] = np.concatenate([latents[j], fed],
                                                    axis=1)
        finally:
            for uid in uids:
                if self.state.get_sequence(uid) is not None:
                    self.flush(uid)
        if eos_token_id is not None:
            for j, o in enumerate(outs):
                if eos_token_id in o:
                    outs[j] = o[:o.index(eos_token_id) + 1]
                    if return_logprobs:
                        logprobs[j] = logprobs[j][:len(outs[j])]
                    if latents[j] is not None:
                        # keep the restore contract: latents cover
                        # prompt + fed tokens = prompt + len(outs)-1
                        latents[j] = latents[j][
                            :, :len(prompts[j]) + len(outs[j]) - 1]
        if return_logprobs:
            return outs, latents, [np.asarray(l, np.float32)
                                   for l in logprobs]
        return outs, latents

    @staticmethod
    def _lookup_draft(history, ngram: int, k: int):
        """Prompt-lookup drafting: find the most recent PRIOR occurrence
        of the trailing ``ngram`` tokens and propose the ``k`` tokens
        that followed it (PLD/"prompt lookup decoding" — no draft
        model; the sequence's own history is the proposer)."""
        n = len(history)
        if n < ngram + 1:
            return []
        arr = np.asarray(history, np.int64)
        key = arr[-ngram:]
        # windows ending strictly before the trailing ngram itself
        limit = n - ngram
        if limit <= 0:
            return []
        windows = np.lib.stride_tricks.sliding_window_view(
            arr[:n - 1], ngram)[:limit]
        hits = np.flatnonzero((windows == key).all(axis=1))
        if hits.size == 0:
            return []
        i = int(hits[-1]) + ngram      # first token after the match
        return [int(t) for t in arr[i:i + k]]

    def generate_lookup(self, prompts, max_new_tokens: int = 32,
                        ngram: int = 2, max_draft: int = 8,
                        eos_token_id: int = None):
        """Greedy generation with prompt-lookup speculative decoding.

        Beyond-reference feature (FastGen has no speculative path): each
        step drafts up to ``max_draft`` tokens from the sequence's own
        history (:meth:`_lookup_draft`), verifies the whole stretch in
        ONE batched dispatch via the tail-logits forward
        (``model.forward_chunk_tail``), accepts the matching prefix plus
        the bonus token, and rolls rejected draft KV back
        (``SequenceDescriptor.rollback`` — slots past ``seen_tokens``
        are never read and get overwritten by the next dispatch). Every
        dispatch has the same static shape (lane bucket × (1+max_draft)),
        so the whole generation reuses one compiled program. Exact:
        output is identical to token-by-token greedy decode; on
        repetitive text each dispatch yields up to ``max_draft+1``
        tokens instead of 1.

        Returns ``(outs, stats)`` with
        ``stats = {drafted, accepted, dispatches, tokens}``.
        """
        if self.prefix_caching:
            raise ValueError(
                "generate_lookup with prefix_caching is unsupported: "
                "rolled-back draft KV must never be registered as a "
                "sharable prefix")
        if self.config.hcache.enable_latents:
            raise ValueError(
                "generate_lookup does not capture latents (rejected "
                "drafts would poison them); disable "
                "hcache.enable_latents")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if ngram < 1 or max_draft < 1:
            raise ValueError("ngram and max_draft must be >= 1")
        n = len(prompts)
        base = max(self.state._seqs.keys(), default=-1) + 1
        uids = [base + i for i in range(n)]
        result = self.can_schedule(uids, [len(p) for p in prompts])
        if result != SchedulingResult.Success:
            raise SchedulingError(result)
        # budget the whole stretch incl. a rejected draft tail beyond
        # the final accepted token (its KV transiently occupies slots)
        blocks = 0
        for p in prompts:
            span = len(p) + max_new_tokens - 1 + max_draft
            if span > self.max_context:
                raise SchedulingError(
                    SchedulingResult.SequenceTokenLimitExceeded)
            blocks += -(-span // self.block_size)
        if blocks > self.state.free_blocks:
            raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)

        stats = {"drafted": 0, "accepted": 0, "dispatches": 0,
                 "tokens": 0}
        T = 1 + max_draft
        try:
            logits, _ = self.put(uids, prompts)
            outs = [[int(np.argmax(l))] for l in logits]
            hist = [list(p) + outs[i] for i, p in enumerate(prompts)]
            done = {i for i in range(n)
                    if eos_token_id is not None
                    and outs[i][0] == eos_token_id}
            while True:
                live = [i for i in range(n)
                        if i not in done and len(outs[i]) < max_new_tokens]
                if not live:
                    break
                B = _bucket(len(live))
                tok, start, t_len, tables = self._blank_lanes(B, T)
                feeds = []
                for j, i in enumerate(live):
                    draft = self._lookup_draft(hist[i], ngram, max_draft)
                    draft = draft[:max_new_tokens - len(outs[i]) - 1]
                    feed = [outs[i][-1]] + draft
                    feeds.append(feed)
                    seq = self.state.get_sequence(uids[i])
                    self.state.maybe_allocate_kv(seq, len(feed))
                    seq.pre_forward(len(feed))
                    tok[j, :len(feed)] = feed
                    start[j] = seq.seen_tokens
                    t_len[j] = len(feed)
                    stats["drafted"] += len(draft)
                tables[:len(live)] = self._tables(live, uids)
                tail_logits = np.asarray(self.model.forward_chunk_tail(
                    self.cache, tok, start, tables, t_len, T))
                stats["dispatches"] += 1
                for j, i in enumerate(live):
                    seq = self.state.get_sequence(uids[i])
                    seq.post_forward()
                    feed = feeds[j]
                    m = len(feed) - 1            # drafted count
                    # logits for the last t_len positions sit at the END
                    # of the tail window
                    lane = tail_logits[j, T - len(feed):]
                    greedy = [int(np.argmax(lane[t]))
                              for t in range(len(feed))]
                    acc = 0
                    while acc < m and feed[1 + acc] == greedy[acc]:
                        acc += 1
                    new = greedy[:acc + 1]       # accepted + bonus
                    stats["accepted"] += acc
                    seq.rollback(m - acc)        # rejected draft KV
                    if eos_token_id is not None and eos_token_id in new:
                        new = new[:new.index(eos_token_id) + 1]
                        done.add(i)
                    outs[i].extend(new)
                    hist[i].extend(new)
                    stats["tokens"] += len(new)
                    if len(outs[i]) >= max_new_tokens:
                        done.add(i)
        finally:
            for uid in uids:
                if self.state.get_sequence(uid) is not None:
                    self.flush(uid)
        stats["tokens"] += n   # the first token from prefill
        return [o[:max_new_tokens] for o in outs], stats

    def generate_lookup_fused(self, prompts, max_new_tokens: int = 32,
                              ngram: int = 2, max_draft: int = 8,
                              window: int = 128,
                              eos_token_id: int = None):
        """Fully fused prompt-lookup speculative decoding: drafting,
        verification, acceptance and KV rollback all run inside ONE
        on-device ``lax.while_loop`` (``model.lookup_decode_loop``), so
        the host syncs once per generation AND each device step can
        emit up to ``max_draft+1`` tokens — the two serving wins
        (:meth:`generate_fused`, :meth:`generate_lookup`) composed.
        Greedy-exact like both. ``window`` caps the on-device n-gram
        search to each lane's most recent tokens (static shape).

        Returns ``(outs, stats)`` like :meth:`generate_lookup`, plus
        per-lane attribution: ``accepted_per_lane`` / ``drafted_per_
        lane`` ride the loop carry as [B] counters, so a serving layer
        can attribute acceptance per request instead of
        batch-averaging (``drafted`` remains the per-lane upper bound
        ``lane_iters*max_draft``, now summed over actual live
        iterations instead of ``iters*max_draft`` for the whole
        batch)."""
        if self.prefix_caching:
            raise ValueError(
                "generate_lookup_fused with prefix_caching is "
                "unsupported: rolled-back draft KV must never be "
                "registered as a sharable prefix")
        if self.config.hcache.enable_latents:
            raise ValueError(
                "generate_lookup_fused does not capture latents; "
                "disable hcache.enable_latents")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if ngram < 1 or max_draft < 1 or window <= ngram:
            raise ValueError("need ngram>=1, max_draft>=1, window>ngram")
        n = len(prompts)
        base = max(self.state._seqs.keys(), default=-1) + 1
        uids = [base + i for i in range(n)]
        result = self.can_schedule(uids, [len(p) for p in prompts])
        if result != SchedulingResult.Success:
            raise SchedulingError(result)
        blocks = 0
        for p in prompts:
            span = len(p) + max_new_tokens - 1 + max_draft
            if span > self.max_context:
                raise SchedulingError(
                    SchedulingResult.SequenceTokenLimitExceeded)
            blocks += -(-span // self.block_size)
        if blocks > self.state.free_blocks:
            raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)

        try:
            logits, _ = self.put(uids, prompts)
            first = [int(np.argmax(l)) for l in logits]
            outs = [[t] for t in first]
            if max_new_tokens == 1 or (
                    eos_token_id is not None
                    and all(t == eos_token_id for t in first)):
                return outs, {"drafted": 0, "accepted": 0,
                              "dispatches": 0, "tokens": n,
                              "accepted_per_lane": [0] * n,
                              "drafted_per_lane": [0] * n}
            B = _bucket(n)
            first_tok, pos, t_blank, tables = self._blank_lanes(B)
            del t_blank
            live = np.zeros((B,), bool)
            hist = np.zeros((B, window), np.int32)
            hist_len = np.zeros((B,), np.int32)
            for j, uid in enumerate(uids):
                seq = self.state.get_sequence(uid)
                # reserve the whole stretch incl. transient rejected
                # tails (generate_fused-style up-front reservation)
                self.state.maybe_allocate_kv(
                    seq, max_new_tokens - 1 + max_draft)
                full = list(prompts[j]) + [first[j]]
                w = min(len(full), window)
                hist[j, window - w:] = full[-w:]
                hist_len[j] = w
                pos[j] = seq.seen_tokens
                first_tok[j, 0] = first[j]
                live[j] = not (eos_token_id is not None
                               and first[j] == eos_token_id)
            tables[:n] = self._tables(list(range(n)), uids)
            out_buf, out_len, iters, accepted, lane_iters = \
                self.model.lookup_decode_loop(
                    self.cache, first_tok[:, 0], pos, tables, live,
                    hist, hist_len, max_new=max_new_tokens - 1,
                    ngram=ngram, max_draft=max_draft, window=window,
                    eos_token_id=eos_token_id)
            for j in range(n):
                outs[j].extend(int(t) for t in out_buf[j, :out_len[j]])
            drafted_per_lane = [int(lane_iters[j]) * max_draft
                                for j in range(n)]
            stats = {"drafted": sum(drafted_per_lane),
                     "accepted": int(accepted[:n].sum()),
                     "dispatches": int(iters),
                     "tokens": n + int(out_len[:n].sum()),
                     "accepted_per_lane": [int(accepted[j])
                                           for j in range(n)],
                     "drafted_per_lane": drafted_per_lane}
        finally:
            for uid in uids:
                if self.state.get_sequence(uid) is not None:
                    self.flush(uid)
        return [o[:max_new_tokens] for o in outs], stats

    # -------------------------------------------------------------- #
    # fused speculative verify step (the serving speculation surface)
    # -------------------------------------------------------------- #
    #: ``put_spec`` captures accepted-span latents through the
    #: latent-capturing tail forward (``forward_chunk_tail_lat``), so
    #: the serving scheduler may speculate against this engine under
    #: latent preemption as well as in exact-KV suspension mode
    spec_latent_capture = True

    @_annotated("hds.serve.put_spec")
    def put_spec(self, batch_uids: Iterable[int], batch_feeds,
                 do_checks: bool = True):
        """One fused speculative verify step over tracked decode
        residents: each feed is ``[fed_token] + draft``; ONE tail-
        logits dispatch (``model.forward_chunk_tail``, the same
        verification forward :meth:`generate_lookup` drives) verifies
        every stretch, the matching draft prefix plus the bonus token
        is accepted, and rejected draft KV rolls back
        (``SequenceDescriptor.rollback``). Greedy-exact per lane.

        Returns ``(emitted, latents)``. Under
        ``hcache.enable_latents`` the dispatch runs the
        latent-capturing tail forward and each lane's entry is its
        ACCEPTED span's latent chunk ``[L, acc+1, H]`` (the fed token
        plus accepted drafts — rolled-back positions never reach a
        latent payload); in exact-KV mode the entries are all None.
        ``prefix_caching`` stays unsupported (rolled-back KV must
        never register as a sharable prefix)."""
        capture = bool(self.config.hcache.enable_latents)
        if self.prefix_caching:
            raise RuntimeError(
                "put_spec with prefix_caching is unsupported: "
                "rolled-back draft KV must never be registered as a "
                "sharable prefix")
        batch_uids = list(batch_uids)
        batch_feeds = [list(np.asarray(f, np.int32).reshape(-1))
                       for f in batch_feeds]
        if any(len(f) < 1 for f in batch_feeds):
            raise ValueError("put_spec feeds need >= 1 token "
                             "(the fed token)")
        if do_checks:
            result = self.can_schedule(
                batch_uids, [len(f) for f in batch_feeds])
            if result != SchedulingResult.Success:
                raise SchedulingError(result)
        self._reject_suspended(batch_uids)
        for uid in batch_uids:
            if self.state.get_sequence(uid) is None:
                raise KeyError(
                    f"put_spec: unknown sequence {uid} (speculation "
                    "runs on decode residents only)")
        inj = get_injector()
        if inj.enabled and batch_uids:
            inj.fire("engine.spec", uid=batch_uids[-1],
                     uids=tuple(batch_uids))
        n = len(batch_uids)
        T = max(len(f) for f in batch_feeds)
        B = _bucket(n)
        tok, start, t_len, tables = self._blank_lanes(B, T)
        starts = []
        for j, (uid, feed) in enumerate(zip(batch_uids, batch_feeds)):
            seq = self.state.get_sequence(uid)
            self.state.maybe_allocate_kv(seq, len(feed))
            starts.append(seq.seen_tokens)
            seq.pre_forward(len(feed))
            tok[j, :len(feed)] = feed
            start[j] = starts[j]
            t_len[j] = len(feed)
        tables[:n] = self._tables(list(range(n)), batch_uids)
        with get_tracer().span("serve.spec_dispatch", lanes=n,
                               tokens=int(sum(len(f)
                                              for f in batch_feeds))):
            if capture:
                tail_logits, lat = self.model.forward_chunk_tail_lat(
                    self.cache, tok, start, tables, t_len, T)
                tail_logits = np.asarray(tail_logits)
                lat = np.asarray(lat)          # [L, B, T, H]
            else:
                tail_logits = np.asarray(self.model.forward_chunk_tail(
                    self.cache, tok, start, tables, t_len, T))
        emitted_out: List[List[int]] = []
        lat_out: List = []
        for j, (uid, feed) in enumerate(zip(batch_uids, batch_feeds)):
            seq = self.state.get_sequence(uid)
            seq.post_forward()
            d = len(feed) - 1
            # logits for the last t_len positions sit at the END of
            # the tail window (the forward_chunk_tail contract)
            lane = tail_logits[j, T - len(feed):]
            greedy = [int(np.argmax(lane[t]))
                      for t in range(len(feed))]
            acc = 0
            while acc < d and feed[1 + acc] == greedy[acc]:
                acc += 1
            seq.rollback(d - acc)        # rejected draft KV
            emitted_out.append(greedy[:acc + 1])
            # feeds are left-aligned at column 0, so the accepted
            # span's latents are the first acc+1 columns of the lane
            lat_out.append(lat[:, j, :acc + 1].copy() if capture
                           else None)
        return emitted_out, lat_out

    # -------------------------------------------------------------- #
    # HCache restore (fork: engine_v2.py:108)
    # -------------------------------------------------------------- #
    @_annotated("hds.serve.restore_kv")
    def restore_kv(self, batch_uids: Iterable[int], batch_tokens: Iterable,
                   batch_latents: Iterable) -> None:
        """Rebuild the blocked KV cache for ``batch_uids`` from saved
        latents without a full forward: allocate blocks, then per layer
        replay the K/V projection + RoPE + cache write with host→HBM copies
        double-buffered against compute.

        Run-to-completion driver over the restore lane
        (:meth:`begin_restore` + :meth:`advance_restores`); the serving
        scheduler holds the lane open instead and trickles chunks
        between resident decode dispatches."""
        self.begin_restore(batch_uids, batch_tokens, batch_latents)
        self.advance_restores()

    def begin_restore(self, batch_uids: Iterable[int],
                      batch_tokens: Iterable,
                      batch_latents: Iterable) -> "RestoreTicket":
        """Open a restore lane: validate + admit the batch
        all-or-nothing, allocate KV blocks, build the padded lane slabs
        and issue the FIRST layer-chunks' host→device ships — but
        dispatch no replay yet. The returned ticket completes as
        :meth:`advance_restores` drains the lane; until then the
        sequences are tracked and in-flight (their blocks are held, and
        they must not be decoded). The ship of chunk 0 is already on
        the link when this returns, so whatever the engine dispatches
        next (typically the residents' decode) computes under it."""
        batch_uids = list(batch_uids)
        self._reject_suspended(batch_uids)
        # group sequences by length bucket: ONE batched restore dispatch
        # chain per bucket (the per-sequence loop costs a full layer-chunk
        # dispatch chain per uid — latency-bound on slow host links)
        items = []
        for uid, tokens, latents in zip(batch_uids, batch_tokens,
                                        batch_latents):
            if latents is None:
                continue
            tokens = np.asarray(tokens, np.int32).reshape(-1)
            latents = np.asarray(latents)          # [L, T, H]
            if latents.shape[1] != len(tokens):
                raise ValueError(
                    f"uid {uid}: {len(tokens)} tokens but latents for "
                    f"{latents.shape[1]}")
            items.append((uid, tokens, latents))
        uid_list = [it[0] for it in items]
        if len(set(uid_list)) != len(uid_list):
            # grouped lanes read seen_tokens before any post_forward — a
            # duplicated uid would overwrite its own slots silently
            raise ValueError(f"duplicate uids in restore_kv: {uid_list}")
        # all-or-nothing admission: a mid-group failure would strand
        # earlier lanes with in-flight accounting and no KV
        new_seqs = sum(1 for uid in uid_list
                       if self.state.get_sequence(uid) is None)
        if self.state.n_tracked_sequences + new_seqs > \
                self.config.state_manager.max_tracked_sequences:
            raise SchedulingError(
                SchedulingResult.EngineSequenceLimitExceeded)
        need = 0
        for uid, tokens, _ in items:
            seq = self.state.get_sequence(uid)
            seen = seq.seen_tokens if seq else 0
            if seen + len(tokens) > self.max_context:
                raise SchedulingError(
                    SchedulingResult.SequenceTokenLimitExceeded)
            need += self.state.blocks_needed(seq, len(tokens))
        if need > self.state.free_blocks:
            raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)
        groups: Dict[int, List] = {}
        for item in items:
            groups.setdefault(_bucket(len(item[1])), []).append(item)
        self.restore_stats["restores"] += 1
        self.restore_stats["sequences"] += len(items)

        def _progress(layer0, nbytes):
            self.restore_stats["chunks_issued"] += 1
            self.restore_stats["bytes_shipped"] += int(nbytes)

        ticket = RestoreTicket(uids=list(uid_list))
        # the umbrella span covers STAGING (state ops + slab build +
        # first ships); the replay chunks get their own
        # serve.restore.stage spans as advance_restores issues them
        with get_tracer().span(
                "serve.restore_kv", sequences=len(items),
                tokens=int(sum(len(it[1]) for it in items)),
                latent_bytes=int(sum(it[2].nbytes for it in items))):
            for T, group in sorted(groups.items()):
                lat, start, t_len, tables, seqs = \
                    self._stage_restore_group(group, T)
                pipe = self.model.restore_pipeline(
                    self.cache, lat, start, tables, t_len,
                    progress_cb=_progress)
                pipe.prefetch()   # chunk 0's H2D rides the link now
                ticket.pending += 1
                self._restore_lanes.append(
                    _RestoreLane(pipe=pipe, seqs=seqs,
                                 uids=[it[0] for it in group],
                                 ticket=ticket))
        if ticket.pending == 0:
            ticket.done = True
        return ticket

    def advance_restores(self, max_chunks: int = 0):
        """Issue up to ``max_chunks`` replay-chunk dispatches across
        the open restore lanes, oldest lane first (0 = drain
        everything). Entirely async — the caller may dispatch decode
        forwards between calls and the pending chunks' H2D ships hide
        under that compute. Returns ``(chunks_issued, completed_uids,
        touched_uids)`` — ``touched`` are the lanes that issued >= 1
        chunk this call (the scheduler's overlap accounting);
        a lane's sequences become decodable (their ``post_forward``
        runs) exactly when the lane's last chunk has been issued."""
        issued = 0
        completed: List[int] = []
        touched: List[int] = []
        while self._restore_lanes and (max_chunks <= 0 or
                                       issued < max_chunks):
            lane = self._restore_lanes[0]
            budget = 0 if max_chunks <= 0 else max_chunks - issued
            n = lane.pipe.advance(budget)
            issued += n
            if n:
                touched.extend(lane.uids)
            if not lane.pipe.done:
                break
            for seq in lane.seqs:
                seq.post_forward()
            completed.extend(lane.uids)
            lane.ticket.pending -= 1
            if lane.ticket.pending <= 0:
                lane.ticket.done = True
            self._restore_lanes.pop(0)
        return issued, completed, touched

    def abort_restore(self, uid: int) -> List[int]:
        """Abort the open restore lane holding ``uid`` (resilience
        path: retry exhaustion or the scheduler's stuck-lane watchdog).
        Every sequence the lane staged is flushed — its blocks and
        tracked slot free immediately; chunks already replayed into the
        cache are unreachable once the block table is gone, so a
        partially-restored lane leaves no visible state. Returns the
        aborted uids ([] when no lane holds ``uid``). The host latent
        payload belongs to the caller and survives for a later re-begin
        or recompute re-entry."""
        for i, lane in enumerate(self._restore_lanes):
            if uid in lane.uids:
                self._restore_lanes.pop(i)
                for u in lane.uids:
                    self.state.flush_sequence(u)
                lane.ticket.pending -= 1
                if lane.ticket.pending <= 0:
                    lane.ticket.done = True
                get_tracer().instant("serve.restore_abort",
                                     uids=list(lane.uids))
                return list(lane.uids)
        return []

    @property
    def pending_restore_chunks(self) -> int:
        """Replay chunks not yet issued across all open lanes."""
        return sum(l.pipe.chunks_total - l.pipe.chunks_issued
                   for l in self._restore_lanes)

    @property
    def restoring_uids(self) -> List[int]:
        return [u for l in self._restore_lanes for u in l.uids]

    def restore_profile(self) -> Dict:
        """Static shape facts the restore-vs-recompute crossover model
        (``serving/crossover.py``) seeds itself from: latent bytes per
        token, the replay/prefill FLOPs split, and how many replay
        chunks a restore costs (each chunk is one dispatch — the fixed
        overhead that makes recompute win at short prompts)."""
        cfg = self._model_config
        H = cfg.hidden_size
        kvd = cfg.n_kv_head * cfg.head_dim
        qd = cfg.n_head * cfg.head_dim
        # matmul flops per token per layer (factor 2 folded out — only
        # the ratio matters): replay runs the q/k/v projections; a full
        # forward adds the o-projection and the 3 SwiGLU matmuls
        replay = H * (qd + 2 * kvd)
        full = replay + H * qd + 3 * H * cfg.intermediate_size
        latent_itemsize = jnp.dtype(self.model.latent_dtype).itemsize
        return {
            "n_layer": cfg.n_layer,
            "latent_bytes_per_token": cfg.hidden_size * latent_itemsize
            * cfg.n_layer,
            "replay_flops_frac": replay / full,
            "restore_chunk_layers": self.model.restore_chunk_layers,
            "restore_chunk_bytes": self.model.restore_chunk_bytes,
        }

    def _stage_restore_group(self, group, T=None):
        """State ops + lane slab for ONE bucket group of
        ``(uid, tokens, latents)`` items: allocates KV, marks the
        sequences in-flight (caller must ``post_forward()`` each returned
        seq after the cache write lands) and builds the padded latent
        slab [L, n, T, H] with its lane metadata. Shared by
        ``restore_kv`` and the marginal-cost benchmark so both time the
        same compiled program."""
        if T is None:
            T = _bucket(max(len(it[1]) for it in group))
        # lane count buckets too: each distinct n would otherwise
        # shape-specialize (and recompile) the restore chain
        n = _bucket(len(group), minimum=1)
        L = group[0][2].shape[0]
        H = group[0][2].shape[2]
        lat = np.zeros((L, n, T, H), group[0][2].dtype)
        _, start, t_len, tables = self._blank_lanes(n)
        seqs = []
        for j, (uid, tokens, latents) in enumerate(group):
            seq = self.state.get_or_create_sequence(uid)
            self.state.maybe_allocate_kv(seq, len(tokens))
            seq.pre_forward(len(tokens))
            lat[:, j, :len(tokens)] = latents
            start[j] = seq.seen_tokens
            t_len[j] = len(tokens)
            tables[j] = self.state.block_table(
                seq, self.max_blocks_per_seq)
            seqs.append(seq)
        return lat, start, t_len, tables, seqs

    # -------------------------------------------------------------- #
    # Prefix caching (no reference analog — FastGen lacks it): full KV
    # blocks shared by refcount across sequences with identical prompt
    # prefixes; a new sequence attaches the matched blocks and prefills
    # only the tail (the same start>0 continuation path chunked prefill
    # uses).
    # -------------------------------------------------------------- #
    @staticmethod
    def _chain_key(parent_bid, block_tokens):
        return (parent_bid, tuple(int(t) for t in block_tokens))

    def _match_chain(self, tokens, max_blocks):
        """Walk the index: block ids for the longest registered prefix
        of ``tokens``, up to ``max_blocks``."""
        BS = self.block_size
        blocks = []
        parent = -1
        for k in range(max_blocks):
            key = self._chain_key(parent, tokens[k * BS:(k + 1) * BS])
            bid = self._prefix_index.get(key)
            if bid is None:
                break
            blocks.append(bid)
            parent = bid
        return blocks

    def _defer_in_batch_duplicates(self, uids, tokens_list):
        """Indices of NEW long prompts whose first block token-matches
        an earlier new prompt in the same batch AND whose prefix is not
        already registered (cheap sufficient trigger: equal first
        blocks ⇒ sharing is possible after wave 1 registers; unequal —
        or already in the global index, where a single wave attaches
        for everyone — ⇒ no reason to split the dispatch)."""
        BS = self.block_size
        seen_first = set()
        wave2 = []
        for i, (uid, tokens) in enumerate(zip(uids, tokens_list)):
            seq = self.state.get_sequence(uid)
            if (seq is not None and seq.seen_tokens > 0) or \
                    len(tokens) <= BS:
                continue
            first = tuple(int(t) for t in tokens[:BS])
            shareable = (len(tokens) - 1) // BS
            if first in seen_first and \
                    len(self._match_chain(tokens, shareable)) < shareable:
                # the index covers less than this duplicate could share
                # — wave 1 (the first occurrence) will extend it
                wave2.append(i)
            else:
                seen_first.add(first)
        return wave2

    def _attach_shared_prefixes(self, uids, tokens_list):
        BS = self.block_size
        out = []
        for uid, tokens in zip(uids, tokens_list):
            seq = self.state.get_sequence(uid)
            if (seq is not None and seq.seen_tokens > 0) or \
                    len(tokens) <= BS:
                out.append(tokens)
                continue
            # new sequence: longest fully-indexed block-prefix match
            # (walking the chain), capped so at least one token still
            # runs the forward (the caller needs logits)
            blocks = self._match_chain(tokens, (len(tokens) - 1) // BS)
            if not blocks:
                out.append(tokens)
                continue
            matched = len(blocks) * BS
            seq = self.state.get_or_create_sequence(uid)
            for b in blocks:
                self.state.allocator.acquire(b)
            seq.extend_blocks(blocks)
            seq.seen_tokens = matched
            seq.history.extend(int(t) for t in tokens[:matched])
            # prime the chain-walk cache: registration resumes after
            # the attached blocks
            seq.registered_full = len(blocks)
            seq.chain_parent = blocks[-1]
            seq.chain_epoch = self._index_epoch
            self.prefix_stats["hits"] += 1
            self.prefix_stats["shared_tokens"] += matched
            out.append(tokens[matched:])
        return out

    def _register_full_blocks(self, seq) -> None:
        """Index this sequence's FULL blocks along the canonical prefix
        chain. The walk runs from the root so the parent is always the
        INDEXED block for that prefix (which may belong to another
        sequence) — chaining on our own unshared duplicate would create
        unreachable entries — but only when a NEW full block completed
        since the last walk (a per-decode-token full rewalk would put
        O(context) host work on every step; the trade-off is that
        entries dropped by a subtree purge re-heal at the next block
        boundary, not the next token). Sequences whose history does not
        cover every cached token (restore_kv-built ones) are skipped:
        their block k holds KV for unknown tokens, and indexing it
        under later-decoded history would share wrong KV. Partial tail
        blocks are never shared (still being written)."""
        BS = self.block_size
        if len(seq.history) != seq.seen_tokens:
            return
        n_full = seq.seen_tokens // BS
        if n_full == seq.registered_full and \
                seq.chain_epoch == self._index_epoch:
            return
        if seq.chain_epoch == self._index_epoch and \
                seq.registered_full > 0:
            start, parent = seq.registered_full, seq.chain_parent
        else:
            start, parent = 0, -1      # a purge invalidated cached tips
        for k in range(start, n_full):
            key = self._chain_key(parent,
                                  seq.history[k * BS:(k + 1) * BS])
            bid = self._prefix_index.get(key)
            if bid is None:
                bid = seq.blocks[k]
                self._prefix_index[key] = bid
                self._block_prefix[bid] = key
                if parent != -1:
                    self._chain_children.setdefault(parent,
                                                    set()).add(key)
            parent = bid
        seq.registered_full = n_full
        seq.chain_parent = parent
        seq.chain_epoch = self._index_epoch

    def _unindex_subtree(self, block) -> None:
        """Drop entries chained under ``block`` — unreachable once its
        entry died. Their blocks may still be alive (other owners); if
        those owners keep decoding, re-registration self-heals with a
        fresh chain. Iterative: a chain is one level per block, so a
        long shared prefix (64k tokens = 1000+ blocks) would blow the
        recursion limit."""
        stack = [block]
        while stack:
            b = stack.pop()
            for ckey in self._chain_children.pop(b, set()):
                cbid = self._prefix_index.pop(ckey, None)
                if cbid is not None:
                    if self._block_prefix.get(cbid) == ckey:
                        del self._block_prefix[cbid]
                    stack.append(cbid)

    def _purge_freed_blocks(self, blocks) -> None:
        purged = False
        for b in blocks:
            if self.state.allocator.refcount(b) == 0:
                key = self._block_prefix.pop(b, None)
                if key is not None:
                    self._prefix_index.pop(key, None)
                    if key[0] != -1 and key[0] in self._chain_children:
                        self._chain_children[key[0]].discard(key)
                    purged = True
                if self._chain_children.get(b):
                    purged = True
                self._unindex_subtree(b)
        if purged:
            self._index_epoch += 1    # cached chain tips are now stale

    # -------------------------------------------------------------- #
    # Lifecycle (reference: flush :275, serialize :284)
    # -------------------------------------------------------------- #
    def flush(self, uid: int) -> None:
        if self._restore_lanes and uid in self.restoring_uids:
            raise RuntimeError(
                f"sequence {uid} has an open restore lane; its blocks "
                "cannot be freed while replay chunks are in flight")
        seq = self.state.get_sequence(uid)
        held = list(seq.blocks) if seq is not None else []
        get_tracer().instant("serve.flush", uid=uid,
                             blocks=len(held))
        self.state.flush_sequence(uid)
        if self.prefix_caching and held:
            self._purge_freed_blocks(held)

    # -------------------------------------------------------------- #
    # Host offload of a sequence's KV (reference: BlockedKVCache's
    # optional host-offloaded blocks, ragged/kv_cache.py:40). Unlike
    # HCache restore (recompute-from-latents), suspend/resume moves the
    # EXACT cache contents — bit-identical continuation, no QKV replay.
    # -------------------------------------------------------------- #
    def _reject_suspended(self, uids):
        """Both cache write paths (put, restore_kv) must refuse suspended
        sequences BEFORE any allocation/bookkeeping — writing against the
        stale seen_tokens would corrupt the host copy's accounting.
        Likewise sequences whose restore lane is still open: their
        ``seen_tokens`` only advances when the lane completes, so a
        forward now would write over the restoring slots."""
        restoring = set(self.restoring_uids) if self._restore_lanes \
            else ()
        for uid in uids:
            if uid in restoring:
                raise RuntimeError(
                    f"sequence {uid} has an open restore lane; drain "
                    "advance_restores before forwarding it")
            seq = self.state.get_sequence(uid)
            if seq is not None and seq.host_kv is not None:
                raise RuntimeError(
                    f"sequence {uid} is suspended (KV on host); call "
                    "resume_sequence first")

    def _token_slots(self, seq, n):
        """Flat pool indices of the sequence's first n token slots."""
        t = np.arange(n)
        blocks = np.asarray(seq.blocks, np.int64)
        return blocks[t // self.block_size] * self.block_size + \
            t % self.block_size

    def suspend_sequence(self, uid: int) -> None:
        """Copy the sequence's KV to host memory and free its pool
        blocks. The sequence stays tracked; ``resume_sequence`` swaps it
        back in (possibly into different blocks)."""
        seq = self.state.get_sequence(uid)
        if seq is None:
            raise KeyError(f"unknown sequence {uid}")
        if seq.host_kv is not None:
            return   # already suspended
        idx = self._token_slots(seq, seq.seen_tokens)
        seq.host_kv = (np.asarray(self.cache.k[:, :, idx]),
                       np.asarray(self.cache.v[:, :, idx]))
        if seq.blocks:
            held = list(seq.blocks)
            self.state.allocator.free(seq.blocks)
            seq.blocks = []
            if self.prefix_caching:
                self._purge_freed_blocks(held)
                seq.registered_full = 0   # fresh blocks on resume
                seq.chain_parent = -1

    def resume_sequence(self, uid: int) -> None:
        seq = self.state.get_sequence(uid)
        if seq is None:
            raise KeyError(f"unknown sequence {uid}")
        if seq.host_kv is None:
            return   # not suspended
        need = self.state.blocks_needed(seq, 0)
        if need > self.state.free_blocks:
            raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)
        self.state.maybe_allocate_kv(seq, 0)
        host_k, host_v = seq.host_kv
        seq.host_kv = None
        if seq.seen_tokens == 0:
            return
        idx = self._token_slots(seq, seq.seen_tokens)
        k, v = self._swap_in(
            self.cache.k, self.cache.v, jnp.asarray(idx),
            jnp.asarray(host_k, self.cache.k.dtype),
            jnp.asarray(host_v, self.cache.v.dtype))
        self.cache.replace(k, v)

    @staticmethod
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def _swap_in(k, v, idx, host_k, host_v):
        """Donated scatter: the pool buffers update in place instead of
        allocating a second full-size pool copy (the pool is sized to
        nearly fill HBM in reserve mode — an eager .at[].set would OOM
        exactly at production sizes)."""
        return k.at[:, :, idx].set(host_k), v.at[:, :, idx].set(host_v)

    def serialize(self) -> Dict:
        """Host-side engine state (reference serializes scheduling state)."""
        return {
            "sequences": {
                uid: {"seen_tokens": s.seen_tokens, "blocks": list(s.blocks)}
                for uid, s in self.state._seqs.items()
            },
            "free_blocks": self.state.free_blocks,
        }
