"""Paged-KV serving for the Phi family.

Reference analog: the phi policy in
``deepspeed/inference/v2/engine_factory.py:69`` +
``model_implementations/phi/``. Builds on the falcon parallel-block
serving model; adds partial rotary (only ``rotary_dim`` features
rotate), biased q/k/v/dense/fc projections, and the biased untied LM
head.
"""

import jax
import jax.numpy as jnp

from ..models.phi import PhiConfig, partial_rope
from ..ops.rope import rope_frequencies
from ..parallel.topology import TENSOR_AXIS
from .model import stack_layer_params
from .model_falcon import PagedFalconModel


class PagedPhiModel(PagedFalconModel):
    def __init__(self, cfg: PhiConfig, params, **kw):
        if not isinstance(cfg, PhiConfig):
            raise TypeError("PagedPhiModel needs a PhiConfig")
        # skip PagedFalconModel's FalconConfig check
        super(PagedFalconModel, self).__init__(cfg, params, **kw)
        # rope tables over the rotated slice only (must exist before the
        # first jitted call, which __init__ does not trigger)
        self.cos, self.sin = rope_frequencies(cfg.rotary_dim,
                                              cfg.max_positions,
                                              cfg.rope_theta)

    def _validate_tp(self):
        cfg, tp = self.cfg, self.tp
        for name, val in (("n_head", cfg.n_head),
                          ("intermediate_size", cfg.intermediate_size),
                          ("vocab_size", cfg.vocab_size)):
            if val % tp:
                raise ValueError(f"{name}={val} not divisible by "
                                 f"tensor parallel degree {tp}")

    _COL_NAMES = ("q_proj", "k_proj", "v_proj", "fc1")
    _ROW_NAMES = ("dense", "fc2")
    _ROW_BIAS_OK = True   # _layer_step adds row biases after the psum

    def load_params(self, params):
        new = {
            "embed": params["embed_tokens"]["embedding"],
            "norm": {k: params["final_layernorm"][k]
                     for k in ("scale", "bias")},
            "lm_head": {k: params["lm_head"][k]
                        for k in ("kernel", "bias")},
            "layers": stack_layer_params(params, self.cfg.n_layer),
        }

        self.params = self._finalize_params(new)

    def _qkv(self, lp, h, positions):
        cfg = self.cfg
        B, T, _ = h.shape
        D = cfg.head_dim
        a = lp["self_attn"]
        # head counts from the (possibly TP-sharded) kernel widths
        q = self._mm(h, a["q_proj"]["kernel"]) + a["q_proj"]["bias"]
        k = self._mm(h, a["k_proj"]["kernel"]) + a["k_proj"]["bias"]
        v = self._mm(h, a["v_proj"]["kernel"]) + a["v_proj"]["bias"]
        q = q.reshape(B, T, q.shape[-1] // D, D)
        k = k.reshape(B, T, k.shape[-1] // D, D)
        v = v.reshape(B, T, v.shape[-1] // D, D)
        q = partial_rope(q, self.cos, self.sin, positions,
                         rotary_dim=cfg.rotary_dim)
        k = partial_rope(k, self.cos, self.sin, positions,
                         rotary_dim=cfg.rotary_dim)
        return q, k, v

    def _layer_step(self, x, lp, ck, cv, tables, positions, flat_idx,
                    kv_len):
        cfg = self.cfg
        h = self._ln(x, lp["input_layernorm"], cfg.layer_norm_epsilon)
        latent = h.astype(self.latent_dtype) \
            if self.capture_latents else jnp.zeros(
            (x.shape[0], x.shape[1], 0), h.dtype)
        q, k, v = self._qkv(lp, h, positions)
        ck, cv = self._scatter_kv(ck, cv, k, v, flat_idx)
        attn = self._paged_attention(q, ck, cv, tables, positions, kv_len)
        d = lp["self_attn"]["dense"]
        attn = self._mm(attn, d["kernel"])
        up = self._mm(h, lp["fc1"]["kernel"]) + lp["fc1"]["bias"]
        mlp = self._mm(jax.nn.gelu(up), lp["fc2"]["kernel"])
        both = attn + mlp
        if self.tp > 1:
            # row-parallel partials psum together; their (replicated)
            # biases add exactly once, after the sum
            both = jax.lax.psum(both, TENSOR_AXIS)
        x = x + both + d["bias"] + lp["fc2"]["bias"]
        return x.astype(cfg.compute_dtype), ck, cv, latent

    def _head_logits(self, params, last):
        head = params["lm_head"]
        return (self._mm(last, head["kernel"])
                + head["bias"]).astype(jnp.float32)
