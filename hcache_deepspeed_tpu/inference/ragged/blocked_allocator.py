"""KV-cache block allocator.

Reference analog: ``deepspeed/inference/v2/ragged/blocked_allocator.py:11
BlockedAllocator`` — a free-list allocator handing out fixed-size KV cache
block ids (there via an int32 linked-list tensor; here a plain Python
free list, since on TPU the block ids live host-side and only the gather
indices built from them reach the device).
"""

from typing import Iterable, List


class BlockedAllocator:

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"need at least 1 block, got {num_blocks}")
        self._num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def num_blocks(self) -> int:
        return self._num_blocks

    def allocate(self, num_blocks: int) -> List[int]:
        if num_blocks < 1:
            raise ValueError(f"invalid allocation size {num_blocks}")
        if num_blocks > len(self._free):
            raise ValueError(
                f"cannot allocate {num_blocks} blocks, only "
                f"{len(self._free)} free")
        out, self._free = self._free[:num_blocks], self._free[num_blocks:]
        return out

    def free(self, blocks: Iterable[int]) -> None:
        blocks = list(blocks)
        live = set(self._free)
        for b in blocks:
            if not 0 <= b < self._num_blocks:
                raise ValueError(f"invalid block id {b}")
            if b in live:
                raise ValueError(f"double free of block {b}")
        self._free.extend(blocks)
