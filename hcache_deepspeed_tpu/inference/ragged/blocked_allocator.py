"""KV-cache block allocator.

Reference analog: ``deepspeed/inference/v2/ragged/blocked_allocator.py:11
BlockedAllocator`` — a free-list allocator handing out fixed-size KV cache
block ids (there via an int32 linked-list tensor; here a plain Python
free list, since on TPU the block ids live host-side and only the gather
indices built from them reach the device).

Blocks are reference-counted so prefix caching can share a full block
across sequences: ``allocate`` hands out blocks at refcount 1,
``acquire`` adds a reference, ``free`` drops one and only returns the
block to the free list when the count reaches zero.
"""

from typing import Dict, Iterable, List


class BlockedAllocator:

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"need at least 1 block, got {num_blocks}")
        self._num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks))
        self._refs: Dict[int, int] = {}

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def num_blocks(self) -> int:
        return self._num_blocks

    def refcount(self, block: int) -> int:
        return self._refs.get(block, 0)

    def allocate(self, num_blocks: int) -> List[int]:
        if num_blocks < 1:
            raise ValueError(f"invalid allocation size {num_blocks}")
        from ...resilience.faults import get_injector
        _inj = get_injector()
        if _inj.enabled:
            # fires before the free list mutates: a faulted allocation
            # is retryable and leaks nothing
            _inj.fire("alloc.blocks", n=num_blocks,
                      free=len(self._free))
        if num_blocks > len(self._free):
            raise ValueError(
                f"cannot allocate {num_blocks} blocks, only "
                f"{len(self._free)} free")
        out, self._free = self._free[:num_blocks], self._free[num_blocks:]
        for b in out:
            self._refs[b] = 1
        return out

    def acquire(self, block: int) -> int:
        """Add a reference to an already-allocated block (prefix
        sharing)."""
        if self._refs.get(block, 0) < 1:
            raise ValueError(f"cannot acquire unallocated block {block}")
        self._refs[block] += 1
        return block

    def free(self, blocks: Iterable[int]) -> None:
        blocks = list(blocks)
        drops: Dict[int, int] = {}
        for b in blocks:
            if not 0 <= b < self._num_blocks:
                raise ValueError(f"invalid block id {b}")
            drops[b] = drops.get(b, 0) + 1
        for b, n in drops.items():
            # count duplicates within THIS call too: free([b, b]) with
            # one reference held is a double free, not two decrements
            if self._refs.get(b, 0) < n:
                raise ValueError(f"double free of block {b}")
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                self._free.append(b)
