"""Per-sequence host-side state.

Reference analog: ``deepspeed/inference/v2/ragged/sequence_descriptor.py``
``DSSequenceDescriptor`` — tracks seen tokens, in-flight tokens and the KV
block ids of one sequence (there mirrored into device tensors; on TPU only
the block table is shipped, as gather indices at batch build time).
"""

from typing import List


class SequenceDescriptor:

    def __init__(self, uid: int):
        self.uid = uid
        self.seen_tokens = 0            # tokens whose KV is materialized
        self.in_flight_tokens = 0       # tokens in the current forward
        self.blocks: List[int] = []     # KV pool block ids, in order
        #: host copy of the KV while suspended (engine.suspend_sequence;
        #: reference: BlockedKVCache's host-offloaded blocks)
        self.host_kv = None
        #: token ids whose KV this sequence holds — maintained only when
        #: prefix caching is on (feeds the chained block index; a
        #: restore_kv-built sequence leaves it short of seen_tokens,
        #: which excludes it from registration)
        self.history: List[int] = []
        #: full blocks counted at the last prefix-index walk (skip
        #: rewalking on every decode token), plus the chain position the
        #: walk ended at — valid only while the engine's index epoch
        #: matches (purges invalidate cached chain tips)
        self.registered_full = 0
        self.chain_parent = -1
        self.chain_epoch = 0

    @property
    def cur_allocated_blocks(self) -> int:
        return len(self.blocks)

    def extend_blocks(self, new_blocks: List[int]) -> None:
        self.blocks.extend(new_blocks)

    def pre_forward(self, num_tokens: int) -> None:
        self.in_flight_tokens = num_tokens

    def post_forward(self) -> None:
        self.seen_tokens += self.in_flight_tokens
        self.in_flight_tokens = 0

    def rollback(self, num_tokens: int) -> None:
        """Un-count the last ``num_tokens`` cached tokens (speculative
        decoding: rejected draft KV). The physical slots keep their
        stale values but sit past ``seen_tokens`` so no attention reads
        them, and the next dispatch overwrites the same positions;
        blocks stay allocated (they are about to be refilled)."""
        if self.in_flight_tokens:
            raise RuntimeError("rollback during an in-flight forward")
        if not 0 <= num_tokens <= self.seen_tokens:
            raise ValueError(
                f"rollback({num_tokens}) with seen={self.seen_tokens}")
        self.seen_tokens -= num_tokens

    def __repr__(self):
        return (f"SequenceDescriptor(uid={self.uid}, "
                f"seen={self.seen_tokens}, blocks={len(self.blocks)})")
