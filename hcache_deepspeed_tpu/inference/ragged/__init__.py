"""Ragged batching state (reference: ``deepspeed/inference/v2/ragged/``)."""

from .blocked_allocator import BlockedAllocator  # noqa: F401
from .kv_cache import BlockedKVCache, StateManager  # noqa: F401
from .sequence import SequenceDescriptor  # noqa: F401
