"""Blocked (paged) KV cache + host-side state manager.

Reference analogs:
* ``deepspeed/inference/v2/ragged/kv_cache.py:40 BlockedKVCache`` — the
  device block pool,
* ``deepspeed/inference/v2/ragged/ragged_manager.py:19 DSStateManager`` —
  uid → sequence tracking plus allocator wiring.

TPU-native layout: one pool per k/v of shape ``[L, KV, P, D]`` with
``P = num_blocks * block_size`` token slots, kept as jnp arrays that flow
*functionally* through the jitted forward (donated, so XLA updates them in
place in HBM). Head-major (KV before P) so the paged-attention kernel's
per-(head, block) DMA tile is ``[block_size, D]`` — a legal Mosaic tile
whose last two dims match the array's minor dims; token-major would force
an un-tileable ``[BS, 1, D]`` block. Block granularity exists only in the
host-side allocator and the flat gather/scatter indices built from block
tables — the device never sees a block structure, which keeps every cache
op a single fused gather/scatter instead of the reference's per-block copy
kernels.
"""

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .blocked_allocator import BlockedAllocator
from .sequence import SequenceDescriptor


class BlockedKVCache:
    """Device block pool for all layers of one model."""

    def __init__(self, n_layers: int, num_blocks: int, block_size: int,
                 n_kv_heads: int, head_dim: int, dtype=jnp.bfloat16,
                 sharding=None):
        self.n_layers = n_layers
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.n_kv_heads = n_kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        shape = (n_layers, n_kv_heads, num_blocks * block_size, head_dim)
        k = jnp.zeros(shape, dtype)
        v = jnp.zeros(shape, dtype)
        if sharding is not None:
            k = jax.device_put(k, sharding)
            v = jax.device_put(v, sharding)
        self.k = k
        self.v = v

    @staticmethod
    def token_bytes(n_layers: int, n_kv_heads: int, head_dim: int,
                    dtype) -> int:
        """KV bytes per cached token (k + v across all layers)."""
        return (2 * n_layers * n_kv_heads * head_dim *
                jnp.dtype(dtype).itemsize)

    @property
    def per_token_bytes(self) -> int:
        return self.token_bytes(self.n_layers, self.n_kv_heads,
                                self.head_dim, self.dtype)

    def replace(self, k, v):
        self.k, self.v = k, v


class StateManager:
    """uid → SequenceDescriptor tracking + block budget arithmetic."""

    def __init__(self, max_tracked_sequences: int, num_blocks: int,
                 block_size: int, max_seq_len: int):
        self.max_tracked_sequences = max_tracked_sequences
        self.block_size = block_size
        self.max_seq_len = max_seq_len
        self.allocator = BlockedAllocator(num_blocks)
        self._seqs: Dict[int, SequenceDescriptor] = {}

    @property
    def n_tracked_sequences(self) -> int:
        return len(self._seqs)

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    def get_sequence(self, uid: int) -> Optional[SequenceDescriptor]:
        return self._seqs.get(uid)

    def get_or_create_sequence(self, uid: int) -> SequenceDescriptor:
        seq = self._seqs.get(uid)
        if seq is None:
            if len(self._seqs) >= self.max_tracked_sequences:
                raise RuntimeError(
                    f"sequence limit {self.max_tracked_sequences} reached")
            seq = SequenceDescriptor(uid)
            self._seqs[uid] = seq
        return seq

    def blocks_needed(self, seq: Optional[SequenceDescriptor],
                      new_tokens: int) -> int:
        seen = seq.seen_tokens if seq else 0
        have = seq.cur_allocated_blocks if seq else 0
        total = seen + new_tokens
        need = -(-total // self.block_size)  # ceil
        return max(need - have, 0)

    def maybe_allocate_kv(self, seq: SequenceDescriptor,
                          new_tokens: int) -> None:
        need = self.blocks_needed(seq, new_tokens)
        if need:
            seq.extend_blocks(self.allocator.allocate(need))

    def flush_sequence(self, uid: int) -> None:
        seq = self._seqs.pop(uid, None)
        if seq is None:
            return
        if seq.blocks:
            self.allocator.free(seq.blocks)

    def block_table(self, seq: SequenceDescriptor,
                    max_blocks: int) -> np.ndarray:
        """Padded int32 block table; unused entries point at block 0 but are
        never read/written thanks to length masks."""
        table = np.zeros((max_blocks,), np.int32)
        n = min(len(seq.blocks), max_blocks)
        table[:n] = seq.blocks[:n]
        return table
