"""Coalesced layer-major host storage for HCache latent payloads.

A preempted-to-latents sequence accumulates one ``[L, t, H]`` latent
chunk per forward (prefill once, then one token per decode step). The
naive accumulation — ``np.concatenate`` per step — reallocates and
copies the whole history on every decoded token (O(T^2) bytes copied
over a generation) and leaves the payload wherever the last concat put
it. :class:`HostLatentStore` keeps ONE growable layer-major
(C-contiguous ``[L, capacity, H]``) host buffer with amortized-doubling
growth along the token axis, so:

* absorbing a decode step is an O(L*H) copy into place (amortized);
* the restore payload is a zero-copy view whose per-layer-chunk slices
  ``[l0:l0+C, :T]`` walk memory in layer-major order — the same order
  the restore pipeline ships them host→device, so staging a chunk is a
  straight block copy instead of a gather;
* the dtype is whatever the engine captured (``hcache.latent_dtype``,
  e.g. ``float8_e4m3fn`` to halve the wire/storage bytes) — the store
  never up-casts.
"""

from typing import Optional, Tuple

import numpy as np


class HostLatentStore:
    """Growable ``[L, T, H]`` host latent buffer (layer-major).

    Quacks like the ndarray the restore contract expects: ``.shape`` /
    ``.nbytes`` cover the VALID tokens, and ``np.asarray(store)``
    yields the ``[L, T, H]`` view — so it drops into
    ``engine.restore_kv`` / ``begin_restore`` payload lists unchanged.
    """

    __slots__ = ("_buf", "_len")

    def __init__(self, first_chunk=None):
        self._buf: Optional[np.ndarray] = None
        self._len = 0
        if first_chunk is not None:
            self.append(first_chunk)

    @classmethod
    def from_array(cls, arr) -> "HostLatentStore":
        """Rebuild a store around a complete ``[L, T, H]`` latent slab
        (e.g. one that just crossed a wire). Unlike :meth:`append`
        this is not an absorb — no fault site fires — and the slab is
        adopted as the valid span verbatim, preserving dtype."""
        arr = np.ascontiguousarray(arr)
        if arr.ndim != 3:
            raise ValueError(
                f"latent slab must be [L, T, H], got {arr.shape}")
        store = cls()
        if arr.size:
            store._buf = arr
            store._len = arr.shape[1]
        return store

    def append(self, chunk) -> None:
        """Absorb one ``[L, t, H]`` latent chunk (t >= 1)."""
        from ...resilience.faults import get_injector
        _inj = get_injector()
        if _inj.enabled:
            # before any buffer growth/write: a faulted absorb leaves
            # the store's valid span untouched
            _inj.fire("host.latents", tokens=self._len)
        chunk = np.asarray(chunk)
        if chunk.ndim != 3:
            raise ValueError(
                f"latent chunk must be [L, t, H], got {chunk.shape}")
        L, t, H = chunk.shape
        if self._buf is None:
            cap = max(t, 16)
            self._buf = np.empty((L, cap, H), chunk.dtype)
        elif (L, H) != (self._buf.shape[0], self._buf.shape[2]):
            raise ValueError(
                f"latent chunk {chunk.shape} does not match store "
                f"layout [L={self._buf.shape[0]}, H={self._buf.shape[2]}]")
        if self._len + t > self._buf.shape[1]:
            cap = self._buf.shape[1]
            while cap < self._len + t:
                cap *= 2
            grown = np.empty((L, cap, H), self._buf.dtype)
            grown[:, :self._len] = self._buf[:, :self._len]
            self._buf = grown
        self._buf[:, self._len:self._len + t] = chunk
        self._len += t

    # ------------------------------------------------------------- #
    # ndarray-compatible surface (the restore payload contract)
    # ------------------------------------------------------------- #
    @property
    def shape(self) -> Tuple[int, int, int]:
        if self._buf is None:
            return (0, 0, 0)
        return (self._buf.shape[0], self._len, self._buf.shape[2])

    @property
    def dtype(self):
        return self._buf.dtype if self._buf is not None else None

    @property
    def nbytes(self) -> int:
        if self._buf is None:
            return 0
        return self._len * self._buf.shape[0] * self._buf.shape[2] * \
            self._buf.dtype.itemsize

    def view(self) -> np.ndarray:
        """Zero-copy ``[L, T, H]`` view of the valid tokens."""
        if self._buf is None:
            raise ValueError("empty HostLatentStore has no view")
        return self._buf[:, :self._len]

    def __array__(self, dtype=None, copy=None):
        v = self.view()
        return v.astype(dtype) if dtype is not None and \
            dtype != v.dtype else v

    def __len__(self) -> int:
        return self._len
