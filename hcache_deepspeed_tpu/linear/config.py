"""Configs for the optimized-linear / LoRA subsystem.

Reference analog: ``deepspeed/linear/config.py`` — ``LoRAConfig`` (rank,
alpha, base-weight sharding, target module names) and
``QuantizationConfig`` (bits + group size for the frozen base weights).
Field names follow the reference so JSON configs carry over.
"""

from dataclasses import dataclass, field
from typing import List, Optional

#: reference default target_mods (llama-arch projection names)
DEFAULT_TARGET_MODS = ["q_proj", "k_proj", "v_proj", "o_proj",
                       "gate_proj", "up_proj", "down_proj"]


@dataclass
class QuantizationConfig:
    """Groupwise quantization of the frozen base weights (QLoRA-style).

    Reference: ``deepspeed/linear/config.py QuantizationConfig`` —
    ``q_bits``/``group_size`` map directly; ``mantissa_bits`` > 0 selects
    an fp8 base (3 → e4m3, 2 → e5m2; reference: ``csrc/fp_quantizer``,
    here ``ops/fp_quantizer``) instead of integer groupwise.
    """
    q_bits: int = 8
    group_size: int = 512
    mantissa_bits: int = 0  # 0 = integer groupwise (ops/quantizer)


@dataclass
class LoRAConfig:
    """Reference: ``deepspeed/linear/config.py LoRAConfig``.

    ``base_weight_sharding`` degree dissolves into the ZeRO stage here:
    frozen base weights keep the engine's parameter sharding (stage 3 ≡
    fully sharded base, the reference's ``base_weight_sharding = dp``),
    so the knob is accepted for config compat but the mesh decides.
    ``delay_lora_init``/``offload`` are torch-initialization artifacts
    with no TPU analog (params are created sharded; host offload of a
    *frozen* tree is the checkpoint engine's job).
    """
    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1
    target_mods: List[str] = field(
        default_factory=lambda: list(DEFAULT_TARGET_MODS))
    quantization: Optional[QuantizationConfig] = None

    @property
    def scaling(self) -> float:
        return self.lora_alpha / self.lora_r
