"""Tree-level LoRA: adapter init, frozen-base quantization, merge.

Reference analog: ``deepspeed/linear/optimized_linear.py
LoRAOptimizedLinear`` — there, an ``nn.Module`` replaces each targeted
``nn.Linear`` (frozen, possibly quantized, possibly sharded base weight +
trainable ``lora_weight_1/2``), installed by module surgery.

TPU re-design: no module surgery. The model stays untouched; LoRA is a
*parameter-tree transformation* used by the engine's compiled train step:

- ``init_lora_params(rng, params, cfg)`` builds a small trainable tree of
  ``{a, b}`` factors for every targeted 2D kernel,
- ``quantize_base(params, cfg)`` optionally replaces those kernels with
  groupwise-quantized storage (``ops/quantizer.QuantizedTensor`` /
  ``ops/fp_quantizer``) — the QLoRA memory shape,
- ``merge_lora(frozen, lora, cfg)`` produces the effective parameters
  ``W + (alpha/r) * a @ b`` inside the jitted step; XLA fuses the
  dequantize+add into the consumer matmuls.

The optimizer then only ever sees the adapter tree — optimizer state and
master weights for the base disappear, which is the reference's memory
win, obtained without hooks.
"""

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from .config import LoRAConfig

_SEP = "/"


def _kernel_paths(params, target_mods) -> Dict[str, Tuple[int, int]]:
    """Flat-path -> (in, out) for every targeted 2D ``kernel`` leaf.

    A leaf is targeted when its name is ``kernel``, it is 2D, and any
    path component matches a ``target_mods`` entry (reference:
    AutoTP-style name matching, ``auto_tp.py``)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = {}
    for path, leaf in flat:
        names = [getattr(k, "key", getattr(k, "name", str(k)))
                 for k in path]
        if names[-1] != "kernel" or getattr(leaf, "ndim", 0) != 2:
            continue
        if not any(m in names for m in target_mods):
            continue
        out[_SEP.join(str(n) for n in names[:-1])] = leaf.shape
    return out


def init_lora_params(rng, params, cfg: LoRAConfig,
                     dtype=None) -> Dict[str, Dict[str, Any]]:
    """Trainable adapter tree: ``{module_path: {"a": [in,r], "b": [r,out]}}``.

    ``a`` is scaled-normal (fan-in), ``b`` zeros — so the merged model
    starts exactly at the base model (standard LoRA init; reference:
    LoRAOptimizedLinear.init_lora)."""
    targets = _kernel_paths(params, cfg.target_mods)
    if not targets:
        raise ValueError(
            f"LoRA found no 2D 'kernel' parameters matching target_mods="
            f"{cfg.target_mods}")
    keys = jax.random.split(rng, len(targets))
    tree = {}
    for key, (path, (fan_in, fan_out)) in zip(keys, sorted(targets.items())):
        leaf_dtype = dtype or jnp.float32
        tree[path] = {
            "a": (jax.random.normal(key, (fan_in, cfg.lora_r))
                  * (1.0 / fan_in ** 0.5)).astype(leaf_dtype),
            "b": jnp.zeros((cfg.lora_r, fan_out), leaf_dtype),
        }
    return tree


def _is_quantized(leaf):
    return hasattr(leaf, "dequantize")


def _path_names(path):
    return [str(getattr(k, "key", getattr(k, "name", k))) for k in path]


def quantize_base(params, cfg: LoRAConfig):
    """Replace targeted kernels with quantized storage (QLoRA base).

    Integer groupwise (``q_bits`` 8/4) via ``ops/quantizer``; fp8
    (e4m3/e5m2 selected by ``mantissa_bits`` 3/2) via
    ``ops/fp_quantizer``. Non-targeted leaves pass through untouched."""
    qcfg = cfg.quantization
    if qcfg is None:
        return params
    targets = set(_kernel_paths(params, cfg.target_mods))
    from ..ops.quantizer import QuantizedTensor

    if qcfg.mantissa_bits > 0:
        # FP8 base (reference: fp_quantizer mantissa_bits): e4m3 for 3
        # mantissa bits, e5m2 for 2. The (q, scale, shape, n) layout is
        # QuantizedTensor's, so the same container (and its dequantize)
        # carries fp8 codes.
        from ..ops.fp_quantizer import quantize_fp8
        if qcfg.q_bits != 8 or qcfg.mantissa_bits not in (2, 3):
            raise ValueError(
                "fp base quantization supports q_bits=8 with "
                f"mantissa_bits 2 (e5m2) or 3 (e4m3); got "
                f"q_bits={qcfg.q_bits} mantissa_bits={qcfg.mantissa_bits}")
        fmt = "e4m3" if qcfg.mantissa_bits == 3 else "e5m2"

        def make(x):
            q, scale, shape, n = quantize_fp8(
                x, group_size=qcfg.group_size, fmt=fmt)
            return QuantizedTensor(q, scale, shape, n, x.dtype)
    else:
        def make(x):
            return QuantizedTensor.make(x, group_size=qcfg.group_size,
                                        num_bits=qcfg.q_bits)

    def visit(path, leaf):
        names = _path_names(path)
        if names[-1] == "kernel" and _SEP.join(names[:-1]) in targets:
            return make(leaf)
        return leaf

    # tree_map_with_path handles any Mapping pytree (dict, FrozenDict)
    return jax.tree_util.tree_map_with_path(visit, params)


def merge_lora(frozen, lora, cfg: LoRAConfig):
    """Effective parameter tree: ``W + (alpha/r) * a @ b`` at every
    adapted kernel, plain (dequantized) weights everywhere else. Pure and
    trace-friendly — called inside the jitted loss so gradients flow to
    ``lora`` only (``frozen`` arrives as a non-differentiated argument)."""
    scale = cfg.scaling
    consumed = set()

    def visit(path, leaf):
        if _is_quantized(leaf):
            leaf = leaf.dequantize()
        names = _path_names(path)
        prefix = _SEP.join(names[:-1])
        if names[-1] == "kernel" and prefix in lora:
            consumed.add(prefix)
            ab = lora[prefix]["a"].astype(jnp.float32) @ \
                lora[prefix]["b"].astype(jnp.float32)
            return (leaf.astype(jnp.float32)
                    + scale * ab).astype(leaf.dtype)
        return leaf

    merged = jax.tree_util.tree_map_with_path(visit, frozen,
                                              is_leaf=_is_quantized)
    unused = set(lora) - consumed
    if unused:
        raise ValueError(
            f"merge_lora: adapters for {sorted(unused)} matched no kernel "
            "in the frozen tree — the trees disagree (wrong model or "
            "path layout)")
    return merged
