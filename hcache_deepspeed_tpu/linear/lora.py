"""Tree-level LoRA: adapter init, frozen-base quantization, merge.

Reference analog: ``deepspeed/linear/optimized_linear.py
LoRAOptimizedLinear`` — there, an ``nn.Module`` replaces each targeted
``nn.Linear`` (frozen, possibly quantized, possibly sharded base weight +
trainable ``lora_weight_1/2``), installed by module surgery.

TPU re-design: no module surgery. The model stays untouched; LoRA is a
*parameter-tree transformation* used by the engine's compiled train step:

- ``init_lora_params(rng, params, cfg)`` builds a small trainable tree of
  ``{a, b}`` factors for every targeted weight — 2D kernels, and 3D
  expert-stacked matrices (per-expert adapter pairs; beyond the
  reference, which never adapts experts),
- ``quantize_base(params, cfg)`` optionally replaces those kernels with
  groupwise-quantized storage (``ops/quantizer.QuantizedTensor`` /
  ``ops/fp_quantizer``) — the QLoRA memory shape,
- ``merge_lora(frozen, lora, cfg)`` produces the effective parameters
  ``W + (alpha/r) * a @ b`` inside the jitted step; XLA fuses the
  dequantize+add into the consumer matmuls.

The optimizer then only ever sees the adapter tree — optimizer state and
master weights for the base disappear, which is the reference's memory
win, obtained without hooks.
"""

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from .config import LoRAConfig

_SEP = "/"


def _path_names(path):
    return [str(getattr(k, "key", getattr(k, "name", k))) for k in path]


def _adapter_key(names, keys):
    """The adapter-tree key for a weight leaf, or None: 2D kernels are
    keyed by module prefix (the ``kernel`` level implied), 3D
    expert-stacked leaves by their full path."""
    prefix = _SEP.join(names[:-1])
    if names[-1] == "kernel" and prefix in keys:
        return prefix
    full = _SEP.join(names)
    return full if full in keys else None


def _kernel_paths(params, target_mods) -> Dict[str, Tuple[int, ...]]:
    """Flat-path -> shape for every targeted weight leaf.

    Two leaf forms are targeted (reference: AutoTP-style name matching,
    ``auto_tp.py``):

    - a 2D ``kernel`` under a module whose name matches ``target_mods``
      (keyed by the module path — the ``kernel`` level is implied);
    - a 3D expert-stacked matrix ``[E, in, out]`` whose OWN name matches
      ``target_mods`` (e.g. the dropless MoE ``w1``/``w3``/``w2``),
      keyed by the full leaf path. Each expert then gets its own
      adapter pair (beyond the reference, which never adapts experts).
    """
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = {}
    for path, leaf in flat:
        names = _path_names(path)
        ndim = getattr(leaf, "ndim", 0)
        if names[-1] == "kernel" and ndim == 2 and \
                any(m in names for m in target_mods):
            out[_SEP.join(names[:-1])] = leaf.shape
        elif ndim == 3 and names[-1] in target_mods:
            out[_SEP.join(names)] = leaf.shape
    return out


def init_lora_params(rng, params, cfg: LoRAConfig,
                     dtype=None) -> Dict[str, Dict[str, Any]]:
    """Trainable adapter tree: ``{module_path: {"a": [in,r], "b": [r,out]}}``.

    ``a`` is scaled-normal (fan-in), ``b`` zeros — so the merged model
    starts exactly at the base model (standard LoRA init; reference:
    LoRAOptimizedLinear.init_lora)."""
    targets = _kernel_paths(params, cfg.target_mods)
    if not targets:
        raise ValueError(
            f"LoRA found no adaptable weights for target_mods="
            f"{cfg.target_mods}: 2D 'kernel' leaves match by ANCESTOR "
            "module name (e.g. 'q_proj'), 3D expert stacks by their OWN "
            "leaf name (e.g. 'w1'/'w3'/'w2')")
    keys = jax.random.split(rng, len(targets))
    tree = {}
    for key, (path, shape) in zip(keys, sorted(targets.items())):
        leaf_dtype = dtype or jnp.float32
        if len(shape) == 3:   # expert-stacked [E, in, out]
            n_e, fan_in, fan_out = shape
            tree[path] = {
                "a": (jax.random.normal(key, (n_e, fan_in, cfg.lora_r))
                      * (1.0 / fan_in ** 0.5)).astype(leaf_dtype),
                "b": jnp.zeros((n_e, cfg.lora_r, fan_out), leaf_dtype),
            }
        else:
            fan_in, fan_out = shape
            tree[path] = {
                "a": (jax.random.normal(key, (fan_in, cfg.lora_r))
                      * (1.0 / fan_in ** 0.5)).astype(leaf_dtype),
                "b": jnp.zeros((cfg.lora_r, fan_out), leaf_dtype),
            }
    return tree


def _is_quantized(leaf):
    return hasattr(leaf, "dequantize")


def quantize_base(params, cfg: LoRAConfig):
    """Replace targeted kernels with quantized storage (QLoRA base).

    Integer groupwise (``q_bits`` 8/4) via ``ops/quantizer``; fp8
    (e4m3/e5m2 selected by ``mantissa_bits`` 3/2) via
    ``ops/fp_quantizer``. Non-targeted leaves pass through untouched."""
    qcfg = cfg.quantization
    if qcfg is None:
        return params
    targets = set(_kernel_paths(params, cfg.target_mods))
    from ..ops.quantizer import QuantizedTensor

    if qcfg.mantissa_bits > 0:
        # FP8 base (reference: fp_quantizer mantissa_bits): e4m3 for 3
        # mantissa bits, e5m2 for 2. The (q, scale, shape, n) layout is
        # QuantizedTensor's, so the same container (and its dequantize)
        # carries fp8 codes.
        from ..ops.fp_quantizer import quantize_fp8
        if qcfg.q_bits != 8 or qcfg.mantissa_bits not in (2, 3):
            raise ValueError(
                "fp base quantization supports q_bits=8 with "
                f"mantissa_bits 2 (e5m2) or 3 (e4m3); got "
                f"q_bits={qcfg.q_bits} mantissa_bits={qcfg.mantissa_bits}")
        fmt = "e4m3" if qcfg.mantissa_bits == 3 else "e5m2"

        def make(x):
            q, scale, shape, n = quantize_fp8(
                x, group_size=qcfg.group_size, fmt=fmt)
            return QuantizedTensor(q, scale, shape, n, x.dtype)
    else:
        def make(x):
            return QuantizedTensor.make(x, group_size=qcfg.group_size,
                                        num_bits=qcfg.q_bits)

    def visit(path, leaf):
        if _adapter_key(_path_names(path), targets) is not None:
            return make(leaf)
        return leaf

    # tree_map_with_path handles any Mapping pytree (dict, FrozenDict)
    return jax.tree_util.tree_map_with_path(visit, params)


def merge_lora(frozen, lora, cfg: LoRAConfig):
    """Effective parameter tree: ``W + (alpha/r) * a @ b`` at every
    adapted kernel, plain (dequantized) weights everywhere else. Pure and
    trace-friendly — called inside the jitted loss so gradients flow to
    ``lora`` only (``frozen`` arrives as a non-differentiated argument)."""
    scale = cfg.scaling
    consumed = set()

    def visit(path, leaf):
        if _is_quantized(leaf):
            leaf = leaf.dequantize()
        key = _adapter_key(_path_names(path), lora)
        if key is not None:
            consumed.add(key)
            a = lora[key]["a"].astype(jnp.float32)
            b = lora[key]["b"].astype(jnp.float32)
            if a.ndim == 3:   # per-expert adapters [E, in, r] @ [E, r, out]
                ab = jnp.einsum("eir,ero->eio", a, b)
            else:
                ab = a @ b
            return (leaf.astype(jnp.float32)
                    + scale * ab).astype(leaf.dtype)
        return leaf

    merged = jax.tree_util.tree_map_with_path(visit, frozen,
                                              is_leaf=_is_quantized)
    unused = set(lora) - consumed
    if unused:
        raise ValueError(
            f"merge_lora: adapters for {sorted(unused)} matched no kernel "
            "in the frozen tree — the trees disagree (wrong model or "
            "path layout)")
    return merged
