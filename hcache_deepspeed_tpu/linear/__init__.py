"""Optimized-linear subsystem: LoRA fine-tuning + quantized frozen base.

Reference analog: ``deepspeed/linear/`` (OptimizedLinear,
LoRAOptimizedLinear, QuantizedParameter/QuantizedLinear, LoRAConfig,
QuantizationConfig). The ``context_manager.Init`` module-swap has no TPU
analog — flax models either use :class:`OptimizedLinear` directly or,
for existing models, the engine applies the tree-level LoRA transform
(``runtime.config`` ``lora`` block) with no model changes at all.
"""

from .config import DEFAULT_TARGET_MODS, LoRAConfig, QuantizationConfig
from .lora import init_lora_params, merge_lora, quantize_base
from .optimized_linear import OptimizedLinear

__all__ = ["LoRAConfig", "QuantizationConfig", "DEFAULT_TARGET_MODS",
           "OptimizedLinear", "init_lora_params", "merge_lora",
           "quantize_base"]
