"""OptimizedLinear: a Dense layer with optional LoRA and quantized base.

Reference analog: ``deepspeed/linear/optimized_linear.py`` —
``OptimizedLinear.__new__`` dispatches to ``nn.Linear`` /
``QuantizedLinear`` / ``LoRAOptimizedLinear`` by config.

TPU/flax form: one ``nn.Module``; the dispatch happens in which variable
collections hold the weight:

- plain: ``kernel`` in the ``params`` collection (trainable) — exactly
  ``nn.Dense``;
- LoRA: the base kernel moves to the ``frozen_base`` collection
  (excluded from gradients/optimizer by construction — flax only
  differentiates ``params``), and trainable ``lora_a``/``lora_b`` live
  in ``params``;
- quantized (+ LoRA): ``frozen_base`` stores the groupwise-quantized
  codes and scales; forward dequantizes on the fly and XLA folds the
  dequant into the consumer matmul.

For whole-model LoRA fine-tuning with the engine, prefer the tree-level
API (``linear.lora``) — this module is the reference-parity surface for
building new models with adapter-ready linears.
"""

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from .config import LoRAConfig, QuantizationConfig


class OptimizedLinear(nn.Module):
    features: int
    use_bias: bool = False
    lora: Optional[LoRAConfig] = None
    quantization: Optional[QuantizationConfig] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        kernel_init = nn.initializers.lecun_normal()
        if self.quantization is not None and self.lora is None:
            raise ValueError(
                "quantization without LoRA freezes the whole layer; use "
                "QuantizationConfig together with LoRAConfig (reference "
                "QuantizedLinear is inference-side: ops/quantizer."
                "quantize_tree covers it)")

        if self.lora is None:
            y = nn.Dense(self.features, use_bias=self.use_bias,
                         dtype=self.dtype, name="dense")(x)
            return y

        qcfg = self.quantization

        def base_init(rng):
            w = kernel_init(rng, (in_features, self.features), jnp.float32)
            w = w.astype(self.dtype)
            if qcfg is not None:
                from ..ops.quantizer import QuantizedTensor
                return QuantizedTensor.make(w, group_size=qcfg.group_size,
                                            num_bits=qcfg.q_bits)
            return w

        base = self.variable("frozen_base", "kernel", base_init,
                             self.make_rng("params")
                             if self.has_rng("params") else None).value
        w = base.dequantize() if hasattr(base, "dequantize") else base

        r = self.lora.lora_r
        a = self.param("lora_a",
                       lambda rng: kernel_init(
                           rng, (in_features, r),
                           jnp.float32).astype(self.dtype))
        b = self.param("lora_b", nn.initializers.zeros, (r, self.features),
                       self.dtype)
        y = x @ w.astype(x.dtype)
        y = y + self.lora.scaling * ((x @ a.astype(x.dtype))
                                     @ b.astype(x.dtype))
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), self.dtype)
            y = y + bias
        return y
