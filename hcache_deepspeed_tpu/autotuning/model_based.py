"""Model-based autotuning: prune with compile-time estimates, then
explore the remaining space with a learned cost model instead of timing
every candidate.

Reference analogs (``/root/reference/deepspeed/autotuning/``):
* ``autotuner.py`` — memory-estimate pruning of micro-batch sizes
  before any experiment runs, staged experiment flow, and the
  ``ds_config_optimal.json`` artifact.
* ``tuner/model_based_tuner.py`` — XGBoost cost model over flattened
  config features: random init trials, predict-the-rest, measure the
  top prediction, refit (INIT_NUM=2, 0.2 random exploration).
* ``scheduler.py`` — resumable experiment state on disk.

TPU re-design: the expensive reference machinery (cluster relaunch per
experiment, xgboost) dissolves into two XLA facilities —
* **OOM prediction is exact, not modeled**: ``jit(...).lower().compile()
  .memory_analysis()`` returns the partitioned program's true peak HBM
  (args + temps); candidates over the budget are pruned without a
  single timed step (the reference must estimate activation memory by
  formula: ``autotuner.py _get_plausible_mbs``).
* **The cost model's prior is the roofline**: XLA ``cost_analysis()``
  flops + memory_analysis bytes give ``t >= max(flops/peak,
  bytes/bandwidth)`` per candidate; a least-squares correction over
  measured trials (features: config numerics + the roofline estimate)
  replaces xgboost — the estimate already carries the physics, so a
  linear residual model is enough to rank.
"""

import json
import math
import os
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..utils.logging import logger
from .autotuner import ExperimentResult

INIT_NUM = 2                      # reference model_based_tuner.py:16
RANDOM_EXPLORATION = 0.2          # reference model_based_tuner.py:56


def aot_estimate(jitted, *args, peak_flops: float = 0.0,
                 hbm_bytes_per_s: float = 0.0, **kwargs) -> Dict:
    """AOT-compile ``jitted`` for ``args`` and return
    ``{"peak_bytes", "flops", "time_est"}`` without executing it.
    Works on any backend (the CPU mesh gives the same partitioned
    program the chips would run)."""
    compiled = jitted.lower(*args, **kwargs).compile()
    mem = compiled.memory_analysis()
    peak_bytes = 0
    if mem is not None:
        peak_bytes = int(
            getattr(mem, "temp_size_in_bytes", 0)
            + getattr(mem, "argument_size_in_bytes", 0)
            + getattr(mem, "output_size_in_bytes", 0)
            - getattr(mem, "alias_size_in_bytes", 0))
    cost = (compiled.cost_analysis() or {})
    if isinstance(cost, (list, tuple)):   # older jax returns [dict]
        cost = cost[0] if cost else {}
    flops = float(cost.get("flops", 0.0) or 0.0)
    bytes_accessed = float(cost.get("bytes accessed", 0.0) or 0.0)
    t_flops = flops / peak_flops if peak_flops else 0.0
    t_mem = bytes_accessed / hbm_bytes_per_s if hbm_bytes_per_s else 0.0
    return {"peak_bytes": peak_bytes, "flops": flops,
            "bytes_accessed": bytes_accessed,
            "time_est": max(t_flops, t_mem)}


def _config_key(cfg: Dict) -> str:
    return json.dumps(cfg, sort_keys=True)


def _features(cfg: Dict, est: Dict, keys: List[str]) -> List[float]:
    """Feature vector over a FIXED key set (configs may carry different
    keys; absent ones read 0 so every vector has the same length)."""
    vals = [float(cfg.get(k, 0) or 0) for k in keys]
    return vals + [math.log1p(est.get("time_est", 0.0) * 1e6),
                   math.log1p(est.get("peak_bytes", 0) / 2 ** 20),
                   math.log1p(est.get("flops", 0.0) / 1e9)]


class _ResidualModel:
    """Least-squares throughput predictor over config features + the
    roofline estimate (the reference's XGBoostCostModel role)."""

    def __init__(self):
        self._w = None

    def fit(self, X: List[List[float]], y: List[float]):
        A = np.asarray(X, np.float64)
        A = np.concatenate([A, np.ones((A.shape[0], 1))], axis=1)
        b = np.asarray(y, np.float64)
        # ridge for stability on tiny trial counts
        lam = 1e-3 * np.eye(A.shape[1])
        self._w = np.linalg.solve(A.T @ A + lam, A.T @ b)

    def predict(self, X: List[List[float]]) -> np.ndarray:
        A = np.asarray(X, np.float64)
        A = np.concatenate([A, np.ones((A.shape[0], 1))], axis=1)
        return A @ self._w


class ModelBasedAutotuner:
    """Two-stage tuner over an explicit candidate list.

    ``build_fn(candidate) -> runner`` where the runner exposes
    ``estimate() -> {"peak_bytes", "flops", "time_est"}`` (cheap, AOT —
    see :func:`aot_estimate`) and ``step()`` (one training step,
    called warmup+measure times only for candidates the model selects).

    Stage 1 prunes every candidate whose ``peak_bytes`` exceeds
    ``hbm_budget_bytes`` — predicted OOM, never timed. Stage 2 measures
    ``init_num`` roofline-best candidates, then alternates fit → pick
    best predicted unmeasured (with the reference's 0.2 random
    exploration) → measure, until ``max_trials`` (default: half the
    space, the verdict's budget) or ``early_stop`` trials without
    improvement. State persists to ``state_path`` after every
    measurement and resumes seamlessly."""

    def __init__(self, build_fn: Callable[[Dict], object],
                 space: List[Dict], *,
                 hbm_budget_bytes: Optional[int] = None,
                 init_num: int = INIT_NUM,
                 max_trials: Optional[int] = None,
                 early_stop: int = 4,
                 warmup_steps: int = 1, measure_steps: int = 3,
                 state_path: Optional[str] = None,
                 rng_seed: int = 0):
        if not space:
            raise ValueError("empty tuning space")
        self.build_fn = build_fn
        self.space = list(space)
        self.hbm_budget_bytes = hbm_budget_bytes
        self.init_num = max(1, init_num)
        self.max_trials = max_trials or max(1, len(space) // 2)
        self.early_stop = early_stop
        self.warmup_steps = warmup_steps
        self.measure_steps = measure_steps
        self.state_path = state_path
        self._rng = np.random.default_rng(rng_seed)
        self.results: List[ExperimentResult] = []
        self.pruned: List[Dict] = []
        self.estimates: Dict[str, Dict] = {}
        self.measured: Dict[str, float] = {}
        self.failed: Dict[str, str] = {}
        self._feat_keys = sorted(
            {k for c in space for k, v in c.items()
             if isinstance(v, (int, float, bool))})
        self._load_state()

    # ---------------- persistence (reference scheduler.py) ----------- #
    def _load_state(self):
        if not (self.state_path and os.path.exists(self.state_path)):
            return
        try:
            with open(self.state_path) as fh:
                st = json.load(fh)
            self.measured = {k: float(v)
                             for k, v in st.get("measured", {}).items()}
            self.failed = dict(st.get("failed", {}))
            self.estimates = st.get("estimates", {})
            logger.info(f"autotune: resumed {len(self.measured)} measured "
                        f"trials from {self.state_path}")
        except (OSError, json.JSONDecodeError) as e:
            logger.warning(f"autotune: could not resume state: {e}")

    def _save_state(self):
        if not self.state_path:
            return
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"measured": self.measured, "failed": self.failed,
                       "estimates": self.estimates}, fh)
        os.replace(tmp, self.state_path)

    # ---------------- measurement ------------------------------------ #
    def _measure(self, cfg: Dict) -> ExperimentResult:
        key = _config_key(cfg)
        if key in self.failed:
            # a failure stays a failure across resume — never replayed
            # as a 0-throughput "success"
            return ExperimentResult(cfg, error=self.failed[key])
        if key in self.measured:
            return ExperimentResult(cfg, throughput=self.measured[key])
        runner = None
        try:
            runner = self.build_fn(cfg)
            for _ in range(self.warmup_steps):
                runner.step()
            t0 = time.perf_counter()
            for _ in range(self.measure_steps):
                runner.step()
            dt = (time.perf_counter() - t0) / self.measure_steps
            tput = float(cfg.get("micro_batch", 1)) / dt
            self.measured[key] = tput
            self._save_state()
            return ExperimentResult(cfg, throughput=tput)
        except Exception as e:   # OOM / trace failure = failed experiment
            self.failed[key] = type(e).__name__
            self._save_state()
            return ExperimentResult(cfg, error=type(e).__name__)
        finally:
            # a failed runner's buffers must not haunt the next trial
            close = getattr(runner, "close", None)
            if close:
                try:
                    close()
                except Exception:
                    pass

    # ---------------- tuning loop ------------------------------------ #
    def tune(self) -> ExperimentResult:
        # stage 1: estimate everything, prune predicted OOM
        viable: List[Dict] = []
        for cfg in self.space:
            key = _config_key(cfg)
            if key not in self.estimates:
                runner = None
                try:
                    runner = self.build_fn(cfg)
                    self.estimates[key] = dict(runner.estimate())
                except Exception as e:
                    self.estimates[key] = {"error": type(e).__name__}
                finally:
                    close = getattr(runner, "close", None)
                    if close:
                        try:
                            close()
                        except Exception:
                            pass
            est = self.estimates[key]
            if "error" in est:
                self.pruned.append(cfg)
                logger.info(f"autotune: pruned (estimate failed "
                            f"{est['error']}): {cfg}")
            elif (self.hbm_budget_bytes
                    and est.get("peak_bytes", 0) > self.hbm_budget_bytes):
                self.pruned.append(cfg)
                logger.info(
                    f"autotune: pruned (predicted "
                    f"{est['peak_bytes'] / 2**30:.2f} GiB > budget): {cfg}")
            else:
                viable.append(cfg)
        self._save_state()
        if not viable:
            raise RuntimeError(
                f"all {len(self.space)} candidates pruned by the memory "
                "estimate; raise hbm_budget_bytes or shrink the configs")

        # stage 2: roofline-seeded model-guided measurement
        by_roofline = sorted(
            viable,
            key=lambda c: self.estimates[_config_key(c)].get(
                "time_est", 0.0))
        to_measure = by_roofline[:self.init_num]
        measured_cfgs: List[Dict] = []
        best: Optional[ExperimentResult] = None
        stale = 0
        trials = 0
        model = _ResidualModel()

        def remaining():
            done = {_config_key(c) for c in measured_cfgs}
            return [c for c in viable if _config_key(c) not in done]

        while trials < self.max_trials:
            if not to_measure:
                rest = remaining()
                if not rest:
                    break
                ok_cfgs = [c for c in measured_cfgs
                           if _config_key(c) in self.measured]
                X = [_features(c, self.estimates[_config_key(c)],
                               self._feat_keys) for c in ok_cfgs]
                y = [self.measured[_config_key(c)] for c in ok_cfgs]
                if len(X) >= 2:
                    model.fit(X, y)
                    Xr = [_features(c, self.estimates[_config_key(c)],
                                    self._feat_keys) for c in rest]
                    pred = model.predict(Xr)
                    pick = rest[int(np.argmax(pred))]
                else:
                    pick = rest[0]
                if self._rng.random() < RANDOM_EXPLORATION and \
                        len(rest) > 1:
                    pick = rest[int(self._rng.integers(len(rest)))]
                to_measure = [pick]
            cfg = to_measure.pop(0)
            res = self._measure(cfg)
            self.results.append(res)
            measured_cfgs.append(cfg)
            trials += 1
            logger.info(f"autotune trial {trials}/{self.max_trials}: {res}")
            if res.ok and (best is None or res.throughput >
                           best.throughput):
                best = res
                stale = 0
            else:
                stale += 1
                if stale >= self.early_stop:
                    logger.info("autotune: early stop "
                                f"({stale} trials without improvement)")
                    break
        if best is None:
            raise RuntimeError("no measured candidate succeeded")
        logger.info(f"autotune best: {best}")
        return best

    # ---------------- artifact (reference ds_config_optimal.json) ---- #
    def write_results(self, out_dir: str) -> str:
        """Reference-style artifact directory: ``ds_config_optimal.json``
        (the winning candidate), plus the full ledger."""
        os.makedirs(out_dir, exist_ok=True)
        ok = [r for r in self.results if r.ok]
        if not ok:
            raise RuntimeError("nothing to write: no successful trials")
        best = max(ok, key=lambda r: r.throughput)
        with open(os.path.join(out_dir, "ds_config_optimal.json"),
                  "w") as fh:
            json.dump(best.config, fh, indent=2)
        ledger = {
            "measured": [
                {"config": r.config, "throughput": r.throughput,
                 "error": r.error} for r in self.results],
            "pruned": self.pruned,
            "space_size": len(self.space),
            "trials": len(self.results),
        }
        with open(os.path.join(out_dir, "autotuning_results.json"),
                  "w") as fh:
            json.dump(ledger, fh, indent=2)
        return out_dir
