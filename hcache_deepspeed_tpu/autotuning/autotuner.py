"""Autotuner: micro-batch / remat sweep driver.

Reference analog: ``deepspeed/autotuning/`` — the Autotuner launches
experiment grids over micro-batch size and ZeRO stage, measures
throughput, and reports the fastest viable config. TPU re-design: no
subprocess relaunches — a candidate is one jit compile + a few timed
steps in-process (XLA gives OOM back as an exception, the reference's
"experiment failed" signal), so a sweep that costs the reference minutes
of cluster relaunches is seconds of compiles.
"""

import itertools
import time
from typing import Callable, Dict, List, Optional

from ..utils.logging import logger


class ExperimentResult:
    def __init__(self, config: Dict, throughput: float = 0.0,
                 error: Optional[str] = None):
        self.config = config
        self.throughput = throughput
        self.error = error

    @property
    def ok(self):
        return self.error is None

    def __repr__(self):
        status = f"{self.throughput:.1f} samples/s" if self.ok \
            else f"FAILED({self.error})"
        return f"Experiment({self.config} -> {status})"


class Autotuner:
    """Sweep driver. ``run_fn(candidate_config) -> step_callable`` builds
    a candidate (typically ``hds.initialize`` + a train_batch closure);
    the tuner times it and picks the fastest.

    Candidate axes follow the reference's tuning space: micro batch size,
    ZeRO stage, remat on/off (the reference's activation-checkpointing
    flag in the DEFAULT_TUNING_SPACE).
    """

    def __init__(self, run_fn: Callable[[Dict], Callable],
                 micro_batch_sizes: List[int],
                 zero_stages: List[int] = (0,),
                 remat: List[bool] = (False,),
                 extra_space: Optional[Dict[str, List]] = None,
                 warmup_steps: int = 2, measure_steps: int = 4):
        """``extra_space`` adds arbitrary axes to the sweep product —
        e.g. ``{"flash_block_q": [256, 512], "flash_block_k": [512,
        1024]}`` to tune the flash kernel's MXU tiling per shape (the
        bench winner's ``blk*`` variants, vetted in one sweep instead
        of one chip session each)."""
        self.run_fn = run_fn
        extra = dict(extra_space or {})
        extra_keys = list(extra)
        self.space = [
            dict({"micro_batch": mb, "zero_stage": z, "remat": r},
                 **dict(zip(extra_keys, vals)))
            for mb, z, r in itertools.product(micro_batch_sizes,
                                              zero_stages, remat)
            for vals in itertools.product(
                *[extra[k] for k in extra_keys])
        ]
        self.warmup_steps = warmup_steps
        self.measure_steps = measure_steps
        self.results: List[ExperimentResult] = []

    def _measure(self, candidate: Dict) -> ExperimentResult:
        try:
            step = self.run_fn(candidate)
            for _ in range(self.warmup_steps):
                step()
            t0 = time.perf_counter()
            for _ in range(self.measure_steps):
                step()
            dt = (time.perf_counter() - t0) / self.measure_steps
            samples = candidate["micro_batch"]
            return ExperimentResult(candidate, throughput=samples / dt)
        except Exception as e:  # OOM / trace errors = failed experiment
            return ExperimentResult(candidate, error=type(e).__name__)

    def tune(self) -> ExperimentResult:
        self.results = []
        for candidate in self.space:
            result = self._measure(candidate)
            logger.info(f"autotune: {result}")
            self.results.append(result)
        ok = [r for r in self.results if r.ok]
        if not ok:
            raise RuntimeError(
                f"no viable config among {len(self.space)} candidates")
        best = max(ok, key=lambda r: r.throughput)
        logger.info(f"autotune best: {best}")
        return best

    def summary(self) -> str:
        extra_keys = [k for k in (self.space[0] if self.space else {})
                      if k not in ("micro_batch", "zero_stage", "remat")]
        head = f"{'micro':>6} {'zero':>5} {'remat':>6}" + "".join(
            f" {k:>14}" for k in extra_keys) + f" {'samples/s':>10}"
        lines = [head]
        for r in self.results:
            tput = f"{r.throughput:.1f}" if r.ok else r.error
            row = (f"{r.config['micro_batch']:>6} "
                   f"{r.config['zero_stage']:>5} "
                   f"{str(r.config['remat']):>6}")
            row += "".join(f" {str(r.config[k]):>14}" for k in extra_keys)
            lines.append(row + f" {tput:>10}")
        return "\n".join(lines)
