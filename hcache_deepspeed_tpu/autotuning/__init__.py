"""Autotuning (reference: ``deepspeed/autotuning/``)."""

from .autotuner import Autotuner, ExperimentResult  # noqa: F401
from .model_based import (ModelBasedAutotuner, aot_estimate)  # noqa: F401
