"""Autotuning (reference: ``deepspeed/autotuning/``)."""

from .autotuner import Autotuner, ExperimentResult  # noqa: F401
