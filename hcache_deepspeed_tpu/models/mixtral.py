"""Mixtral-style MoE causal LM: the Llama block with an MoE FFN.

Reference analog: the MoE training path (``deepspeed/moe/``) applied to a
llama-architecture trunk, and inference-v2's mixtral policy
(``inference/v2/model_implementations`` engine_factory mapping). Expert
parameters carry a leading ``[E, ...]`` dim sharded on the ``expert`` mesh
axis; everything else follows ``models/llama.py``.
"""

from dataclasses import dataclass

from jax.sharding import PartitionSpec

from ..moe.layer import MoEMLP
from ..parallel.topology import EXPERT_AXIS, TENSOR_AXIS
from .llama import LlamaConfig, LlamaForCausalLM, llama_tp_spec_fn


@dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 1.25
    min_capacity: int = 4
    moe_aux_loss_coef: float = 0.01
    #: route through the dropless grouped-GEMM path (moe/dropless.py)
    #: instead of capacity buffers; same param tree either way
    dropless: bool = False


@dataclass(frozen=True)
class Qwen2MoeConfig(MixtralConfig):
    """Qwen2-MoE (HF qwen2_moe): mixtral trunk + a gated shared expert
    every token passes through, raw (un-renormalized) top-k gate mass,
    and attention biases. Requires the dropless path (the shared expert
    lives in DroplessMOELayer)."""
    shared_expert_intermediate_size: int = 5632
    norm_topk_prob: bool = False
    dropless: bool = True
    attention_bias: bool = True


def qwen2_moe_a14b(**kw):
    defaults = dict(vocab_size=151936, hidden_size=3584,
                    intermediate_size=2560, n_layer=28, n_head=28,
                    n_kv_head=4, max_positions=32768, rope_theta=1e6,
                    num_experts=64, top_k=8,
                    shared_expert_intermediate_size=20480,
                    dtype="bfloat16", remat=True)
    defaults.update(kw)
    return Qwen2MoeConfig(**defaults)


def qwen2_moe_tiny(**kw):
    defaults = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    n_layer=2, n_head=4, n_kv_head=2, max_positions=128,
                    num_experts=4, top_k=2,
                    shared_expert_intermediate_size=96)
    defaults.update(kw)
    return Qwen2MoeConfig(**defaults)


def mixtral_8x7b(**kw):
    defaults = dict(vocab_size=32000, hidden_size=4096,
                    intermediate_size=14336, n_layer=32, n_head=32,
                    n_kv_head=8, max_positions=8192, rope_theta=1e6,
                    num_experts=8, top_k=2, dtype="bfloat16", remat=True)
    defaults.update(kw)
    return MixtralConfig(**defaults)


def mixtral_tiny(**kw):
    defaults = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    n_layer=2, n_head=4, n_kv_head=2, max_positions=128,
                    num_experts=4, top_k=2)
    defaults.update(kw)
    return MixtralConfig(**defaults)


def MixtralForCausalLM(cfg: MixtralConfig, attention_fn=None):
    return LlamaForCausalLM(cfg, attention_fn=attention_fn, mlp_cls=MoEMLP)


def mixtral_tp_spec_fn(path, leaf):
    """TP + EP rules: expert stacks shard their leading E dim on ``expert``
    (+ optionally their ff dim on ``tensor``); dense params follow llama."""
    names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
    joined = "/".join(str(n) for n in names)
    if "experts" in joined and leaf.ndim == 3:
        if any(w in joined for w in ("w1", "w3")):
            return PartitionSpec(EXPERT_AXIS, None, TENSOR_AXIS)
        if "w2" in joined:
            return PartitionSpec(EXPERT_AXIS, TENSOR_AXIS, None)
        return PartitionSpec(EXPERT_AXIS)
    if joined.endswith("wg") or "/wg" in joined:
        return PartitionSpec()  # router replicated, fp32
    return llama_tp_spec_fn(path, leaf)
