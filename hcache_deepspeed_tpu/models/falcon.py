"""Falcon model family (tiiuae/falcon-*) in flax.linen.

Reference analog: the falcon policy in
``deepspeed/inference/v2/engine_factory.py:69`` +
``model_implementations/falcon/``. Falcon-7B-style architecture:
**parallel** attention + MLP branches off ONE shared input LayerNorm
(``x + attn(ln(x)) + mlp(ln(x))``), rotary embeddings, multi-query /
grouped-query attention, GELU MLP, no projection biases, tied LM head.

Deviation from the HF layout, on purpose: HF falcon fuses q/k/v into a
single ``query_key_value`` with group-striped interleaving; here the
projections are separate ``q_proj/k_proj/v_proj`` (the TPU-friendly
layout the rest of the zoo uses — converting an HF checkpoint is a
de-stripe + split, not a math change). Attention itself reuses
:class:`~.llama.LlamaAttention` (rope + GQA + flash).
"""

from dataclasses import dataclass

import flax.linen as nn
import jax.numpy as jnp

from .gpt2 import causal_lm_loss, default_lm_labels
from .llama import LlamaAttention


@dataclass(frozen=True)
class FalconConfig:
    vocab_size: int = 65024
    hidden_size: int = 4544
    n_layer: int = 32
    n_head: int = 71
    n_kv_head: int = 1             # falcon-7b is MQA; 40b/180b GQA
    max_positions: int = 2048
    layer_norm_epsilon: float = 1e-5
    rope_theta: float = 10000.0
    dtype: str = "float32"
    remat: bool = False
    use_flash: bool = True
    attention_bias: bool = False   # LlamaAttention contract
    tie_word_embeddings: bool = True

    @property
    def head_dim(self):
        return self.hidden_size // self.n_head

    @property
    def ffn_dim(self):
        return 4 * self.hidden_size

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


def falcon_7b(**kw):
    defaults = dict(dtype="bfloat16", remat=True)
    defaults.update(kw)
    return FalconConfig(**defaults)


def falcon_tiny(**kw):
    defaults = dict(vocab_size=256, hidden_size=64, n_layer=2, n_head=4,
                    n_kv_head=1, max_positions=128)
    defaults.update(kw)
    return FalconConfig(**defaults)


class FalconBlock(nn.Module):
    """Parallel residual: both branches read the same normed input, so
    the block has ONE LayerNorm (falcon-7b ``parallel_attn`` +
    ``num_ln_in_parallel_attn=1``)."""
    cfg: FalconConfig

    @nn.compact
    def __call__(self, x, train: bool):
        cfg = self.cfg
        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=x.dtype,
                         name="input_layernorm")(x)
        attn = LlamaAttention(cfg, name="self_attn")(h, train)
        up = nn.Dense(cfg.ffn_dim, use_bias=False, dtype=x.dtype,
                      name="dense_h_to_4h")(h)
        mlp = nn.Dense(cfg.hidden_size, use_bias=False, dtype=x.dtype,
                       name="dense_4h_to_h")(nn.gelu(up))
        return x + attn + mlp


class FalconForCausalLM(nn.Module):
    """Same batch contract as the rest of the model zoo."""
    cfg: FalconConfig

    @nn.compact
    def __call__(self, batch, train: bool = False,
                 return_logits: bool = False):
        cfg = self.cfg
        ids = batch["input_ids"]
        dtype = cfg.compute_dtype

        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=dtype,
                         name="embed_tokens")
        x = embed(ids)
        block = FalconBlock
        if cfg.remat:
            block = nn.remat(FalconBlock, static_argnums=(2,))
        for i in range(cfg.n_layer):
            x = block(cfg, name=f"layers_{i}")(x, train)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=dtype,
                         name="ln_f")(x)

        if cfg.tie_word_embeddings:
            logits = embed.attend(x)
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=dtype,
                              name="lm_head")(x)
        if return_logits:
            return logits
        labels = batch.get("labels")
        if labels is None:
            labels = default_lm_labels(ids)
        return causal_lm_loss(logits, labels)
