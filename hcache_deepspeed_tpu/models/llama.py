"""Llama model family (Llama-2 / Llama-3 style) in flax.linen — the
flagship model for the BASELINE north-star config (ZeRO-3 Llama-2-7B).

Reference analog: the inference-v2 llama implementation
(``deepspeed/inference/v2/model_implementations/llama_v2/model.py``) and the
HF-Llama AutoTP sharding policy (``deepspeed/module_inject/auto_tp.py``).
This module is the *training-side* definition, built TPU-first:

* pre-norm RMSNorm (Pallas kernel via ``ops.rms_norm``),
* rotary embeddings (``ops.rope``; XLA fuses into the QKV matmul),
* grouped-query attention (n_kv_heads <= n_heads) through the Pallas flash
  attention kernel (``ops.flash_attention``),
* SwiGLU MLP,
* static shapes, bf16-friendly, remat-able blocks,
* Megatron-style TP rules exposed via ``llama_tp_spec_fn`` (column-split
  q/k/v/gate/up, row-split o/down, vocab-split embed/lm_head) so the same
  module runs pure-DP, ZeRO-sharded, or TP without code changes,
* optional Ulysses sequence parallelism: pass ``attention_fn`` (see
  ``sequence/layer.py``) to swap the core attention for the
  all-to-all-wrapped one.
"""

from dataclasses import dataclass
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ..ops.flash_attention import attention as flash_attention
from ..ops.rms_norm import rms_norm
from ..ops.rope import apply_rope, rope_frequencies
from ..parallel.topology import TENSOR_AXIS
from .gpt2 import causal_lm_loss, default_lm_labels


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    n_layer: int = 32
    n_head: int = 32
    n_kv_head: int = 32          # < n_head => GQA; == 1 => MQA
    max_positions: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    dtype: str = "float32"
    remat: bool = False
    #: jax.checkpoint_policies name for per-block remat (implies remat;
    #: see GPT2Config.remat_policy)
    remat_policy: str = ""
    use_flash: bool = True
    #: flash kernel tile sizes (0 = kernel default; see
    #: GPT2Config.flash_block_q)
    flash_block_q: int = 0
    flash_block_k: int = 0
    #: biases on q/k/v projections (qwen / qwen1.5-style; llama: False)
    attention_bias: bool = False
    #: > 0: chunked LM loss — no full [B, T, V] fp32 logits (see
    #: GPT2Config.loss_chunk)
    loss_chunk: int = 0

    @property
    def head_dim(self):
        return self.hidden_size // self.n_head

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


def llama2_7b(**kw):
    defaults = dict(vocab_size=32000, hidden_size=4096,
                    intermediate_size=11008, n_layer=32, n_head=32,
                    n_kv_head=32, max_positions=4096, dtype="bfloat16",
                    remat=True)
    defaults.update(kw)
    return LlamaConfig(**defaults)


def llama2_13b(**kw):
    defaults = dict(hidden_size=5120, intermediate_size=13824, n_layer=40,
                    n_head=40, n_kv_head=40, dtype="bfloat16", remat=True)
    defaults.update(kw)
    return LlamaConfig(**defaults)


def llama3_8b(**kw):
    defaults = dict(vocab_size=128256, hidden_size=4096,
                    intermediate_size=14336, n_layer=32, n_head=32,
                    n_kv_head=8, max_positions=8192, rope_theta=500000.0,
                    dtype="bfloat16", remat=True)
    defaults.update(kw)
    return LlamaConfig(**defaults)


def llama_tiny(**kw):
    """Test-scale config (reference tests' SimpleModel analog)."""
    defaults = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    n_layer=2, n_head=4, n_kv_head=2, max_positions=128)
    defaults.update(kw)
    return LlamaConfig(**defaults)


class LlamaAttention(nn.Module):
    cfg: LlamaConfig
    attention_fn: Optional[Callable] = None  # Ulysses hook

    @nn.compact
    def __call__(self, x, train: bool):
        cfg = self.cfg
        B, T, C = x.shape
        H, KV, D = cfg.n_head, cfg.n_kv_head, cfg.head_dim

        ab = cfg.attention_bias
        q = nn.Dense(H * D, use_bias=ab, dtype=x.dtype, name="q_proj")(x)
        k = nn.Dense(KV * D, use_bias=ab, dtype=x.dtype, name="k_proj")(x)
        v = nn.Dense(KV * D, use_bias=ab, dtype=x.dtype, name="v_proj")(x)
        q = q.reshape(B, T, H, D)
        k = k.reshape(B, T, KV, D)
        v = v.reshape(B, T, KV, D)

        cos, sin = rope_frequencies(D, cfg.max_positions, cfg.rope_theta)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)

        if self.attention_fn is not None:
            if KV < H and not getattr(self.attention_fn, "supports_gqa",
                                      False):
                # fns without GQA support (e.g. ring) take dense heads;
                # Ulysses declares supports_gqa and moves compact k/v
                # through its all-to-alls (H/KV x less wire)
                rep = H // KV
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            y = self.attention_fn(q, k, v, causal=True)
        elif cfg.use_flash:
            # GQA-native: the kernel's index map shares kv blocks across
            # each query-head group — no repeat, KV HBM reads drop H/KV x
            y = flash_attention(
                q, k, v, causal=True,
                # family configs reusing this block (falcon/phi/...)
                # may not declare the tiling knobs
                block_q=getattr(cfg, "flash_block_q", 0),
                block_k=getattr(cfg, "flash_block_k", 0))
        else:
            from ..ops.flash_attention import reference_attention
            y = reference_attention(q, k, v, causal=True)
        y = y.reshape(B, T, H * D)
        return nn.Dense(C, use_bias=False, dtype=x.dtype, name="o_proj")(y)


class LlamaMLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        gate = nn.Dense(cfg.intermediate_size, use_bias=False, dtype=x.dtype,
                        name="gate_proj")(x)
        up = nn.Dense(cfg.intermediate_size, use_bias=False, dtype=x.dtype,
                      name="up_proj")(x)
        h = nn.silu(gate) * up
        return nn.Dense(cfg.hidden_size, use_bias=False, dtype=x.dtype,
                        name="down_proj")(h)


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.ones, (x.shape[-1],),
                       jnp.float32)
        return rms_norm(x, w, eps=self.eps)


class LlamaBlock(nn.Module):
    """Returns ``(x, aux_loss)`` — dense blocks report 0 aux; an MoE
    ``mlp_cls`` (models/mixtral.py) returns its load-balancing loss, which
    the top-level model sums and folds into the training loss (the
    reference collects ``MOELayer.l_aux`` the same way, moe/sharded_moe.py)."""
    cfg: LlamaConfig
    attention_fn: Optional[Callable] = None
    mlp_cls: Any = None  # MoE swap-in point (models/mixtral.py)

    @nn.compact
    def __call__(self, x, train: bool):
        cfg = self.cfg
        x = x + LlamaAttention(cfg, attention_fn=self.attention_fn,
                               name="self_attn")(
            RMSNorm(cfg.rms_norm_eps, name="input_layernorm")(x), train)
        h = RMSNorm(cfg.rms_norm_eps, name="post_attention_layernorm")(x)
        if self.mlp_cls is None:
            y = LlamaMLP(cfg, name="mlp")(h)
            aux = jnp.zeros((), jnp.float32)
        else:
            out = self.mlp_cls(cfg, name="mlp")(h, train)
            y, aux = out if isinstance(out, tuple) \
                else (out, jnp.zeros((), jnp.float32))
        return x + y, aux


class _HeadKernel(nn.Module):
    """Declares the LM-head weight at the ``lm_head/kernel`` path (the
    tree nn.Dense would create) while handing the raw kernel back, so the
    chunked loss can stream it without a full-logits GEMM."""
    hidden: int
    vocab: int

    @nn.compact
    def __call__(self):
        return self.param("kernel", nn.initializers.lecun_normal(),
                          (self.hidden, self.vocab), jnp.float32)


class LlamaForCausalLM(nn.Module):
    """Batch contract matches GPT2LMHeadModel: {"input_ids": [B,T] int32,
    optional "labels" (-100 ignore), optional "attention_mask"}. Returns the
    mean causal-LM loss (fp32 scalar)."""
    cfg: LlamaConfig
    attention_fn: Optional[Callable] = None
    mlp_cls: Any = None

    @nn.compact
    def __call__(self, batch, train: bool = False,
                 return_logits: bool = False):
        cfg = self.cfg
        ids = batch["input_ids"]
        B, T = ids.shape
        dtype = cfg.compute_dtype

        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=dtype,
                         name="embed_tokens")
        x = embed(ids)

        block = LlamaBlock
        if cfg.remat or cfg.remat_policy:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy) \
                if cfg.remat_policy else None
            block = nn.remat(LlamaBlock, static_argnums=(2,),
                             policy=policy)
        aux_total = jnp.zeros((), jnp.float32)
        for i in range(cfg.n_layer):
            x, aux = block(cfg, attention_fn=self.attention_fn,
                           mlp_cls=self.mlp_cls, name=f"layers_{i}")(x, train)
            aux_total = aux_total + aux
        x = RMSNorm(cfg.rms_norm_eps, name="norm")(x)

        if cfg.tie_word_embeddings:
            head_kernel = embed.embedding.T.astype(dtype)
        else:
            # same param path as nn.Dense(name="lm_head") would declare
            head_kernel = _HeadKernel(cfg.hidden_size, cfg.vocab_size,
                                      name="lm_head")().astype(dtype)

        if return_logits:
            return x @ head_kernel
        labels = batch.get("labels")
        if labels is None:
            labels = default_lm_labels(ids)
        if cfg.loss_chunk and T % cfg.loss_chunk == 0:
            from ..sequence.fpdt import chunked_lm_loss
            loss = chunked_lm_loss(x, head_kernel, labels,
                                   chunk=cfg.loss_chunk)
        else:
            if cfg.loss_chunk:
                from .gpt2 import _warn_loss_chunk_fallback
                _warn_loss_chunk_fallback(T, cfg.loss_chunk)
            loss = causal_lm_loss(x @ head_kernel, labels)
        aux_coef = getattr(cfg, "moe_aux_loss_coef", 0.0)
        if aux_coef:
            loss = loss + aux_coef * aux_total
        return loss


# ------------------------------------------------------------------ #
# Pipeline decomposition (reference: PipelineModule layer specs —
# pipe/module.py; the gpt2 decomposition is the template)
# ------------------------------------------------------------------ #
class LlamaPipeEmbed(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, train: bool = False):
        ids = x["input_ids"] if isinstance(x, dict) else x
        return nn.Embed(self.cfg.vocab_size, self.cfg.hidden_size,
                        dtype=self.cfg.compute_dtype,
                        name="embed_tokens")(ids)


class LlamaPipeBlock(nn.Module):
    """Block with the pipeline body contract ``(x, train) -> x`` (dense
    aux loss is zero and dropped; MoE blocks are not pipeline-decomposed
    here). Honors ``cfg.remat``/``remat_policy`` like the flat model."""
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, train: bool = False):
        block = LlamaBlock
        if self.cfg.remat or self.cfg.remat_policy:
            policy = getattr(jax.checkpoint_policies,
                             self.cfg.remat_policy) \
                if self.cfg.remat_policy else None
            block = nn.remat(LlamaBlock, static_argnums=(2,),
                             policy=policy)
        out, _aux = block(self.cfg, name="block")(x, train)
        return out


class LlamaPipeFinalNorm(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, train: bool = False):
        return RMSNorm(self.cfg.rms_norm_eps, name="norm")(x)


class LlamaPipeHead(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, train: bool = False):
        kernel = _HeadKernel(self.cfg.hidden_size, self.cfg.vocab_size,
                             name="lm_head")()
        return x @ kernel.astype(x.dtype)


def llama_pipeline_layers(cfg: LlamaConfig):
    """(layers, loss_fn) for ``PipelineModule``: embed, n_layer
    homogeneous blocks, final RMSNorm, untied LM head."""
    if cfg.tie_word_embeddings:
        raise ValueError(
            "llama_pipeline_layers supports untied embeddings only (a "
            "tied head would need a TiedLayerSpec pair like gpt2's)")
    if cfg.loss_chunk:
        from ..utils.logging import logger
        logger.warning(
            "llama_pipeline_layers: cfg.loss_chunk is not applied — the "
            "pipeline loss head computes full logits (the chunked loss "
            "needs the fused head+loss layer of the flat model)")
    from ..runtime.pipe.module import LayerSpec
    from .gpt2 import lm_loss_fn
    layers = [
        LayerSpec(LlamaPipeEmbed, cfg),
        *[LayerSpec(LlamaPipeBlock, cfg) for _ in range(cfg.n_layer)],
        LayerSpec(LlamaPipeFinalNorm, cfg),
        LayerSpec(LlamaPipeHead, cfg),
    ]
    return layers, lm_loss_fn


def llama_zeropp_layered_spec(cfg: LlamaConfig):
    """Layered loss decomposition for the ZeRO++ scan-over-layers gather
    (``runtime/zero/zeropp.py``); see ``gpt2.gpt2_zeropp_layered_spec``
    for the contract. Dense blocks only — MoE/custom-attention models
    fall back to the whole-tree gather (``models/layered.py`` gates)."""
    dtype = cfg.compute_dtype
    outer_keys = ("embed_tokens", "norm") if cfg.tie_word_embeddings \
        else ("embed_tokens", "norm", "lm_head")

    def embed(outer, batch, key, train):
        # root module: params sit at the tree top (no name nesting)
        return nn.Embed(cfg.vocab_size, cfg.hidden_size,
                        dtype=dtype).apply(
            {"params": outer["embed_tokens"]}, batch["input_ids"])

    def block(layer, x, batch, key, train):
        out, _aux = LlamaBlock(cfg).apply({"params": layer}, x, train)
        return out

    def head(outer, x, batch):
        x = RMSNorm(cfg.rms_norm_eps).apply({"params": outer["norm"]}, x)
        if cfg.tie_word_embeddings:
            head_kernel = outer["embed_tokens"]["embedding"].T \
                .astype(dtype)
        else:
            head_kernel = outer["lm_head"]["kernel"].astype(dtype)
        ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = default_lm_labels(ids)
        T = ids.shape[1]
        if cfg.loss_chunk and T % cfg.loss_chunk == 0:
            from ..sequence.fpdt import chunked_lm_loss
            return chunked_lm_loss(x, head_kernel, labels,
                                   chunk=cfg.loss_chunk)
        return causal_lm_loss(x @ head_kernel, labels)

    return {
        "model_name": "llama",
        "layer_prefix": "layers_",
        "n_layer": cfg.n_layer,
        "outer_keys": outer_keys,
        "embed": embed,
        "block": block,
        "head": head,
    }


def llama_flat_to_pipeline(params, cfg: LlamaConfig):
    """Flat ``LlamaForCausalLM`` tree (training run or
    ``checkpoint.hf_loader``) → ``PipelineModule`` layout; see
    ``gpt2.gpt2_flat_to_pipeline`` for the contract."""
    from ._pipe_util import stack_flat_layers
    block_tree = stack_flat_layers(
        params, "layers_", cfg.n_layer,
        required=["embed_tokens", "norm", "lm_head"], model_name="llama")
    return {
        "pre": {"layer_0": {"embed_tokens": dict(params["embed_tokens"])}},
        "blocks": {"block": block_tree},
        "post": {"layer_0": {"norm": dict(params["norm"])},
                 "layer_1": {"lm_head": dict(params["lm_head"])}},
    }


def llama_tp_spec_fn(path, leaf):
    """Megatron-style TP rules (reference: AutoTP policy for HF Llama,
    module_inject/auto_tp.py — shard qkv/gate/up column-wise, o/down
    row-wise, vocab dims of embed/lm_head)."""
    names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
    joined = "/".join(str(n) for n in names)
    if leaf.ndim < 2:
        return PartitionSpec()
    if "embed_tokens" in joined or "lm_head" in joined:
        return PartitionSpec(None, TENSOR_AXIS)
    if any(n in joined for n in ("q_proj", "k_proj", "v_proj",
                                 "gate_proj", "up_proj")):
        return PartitionSpec(None, TENSOR_AXIS)  # column parallel
    if any(n in joined for n in ("o_proj", "down_proj")):
        return PartitionSpec(TENSOR_AXIS, None)  # row parallel
    # stacked MoE expert tensors (w1/w2/w3, [E, ...]) belong to
    # mixtral_tp_spec_fn, which handles the expert leading dim
    return PartitionSpec()
