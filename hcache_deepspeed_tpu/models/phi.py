"""Phi model family (microsoft/phi-1 / phi-1.5 / phi-2) in flax.linen.

Reference analog: the phi policy in
``deepspeed/inference/v2/engine_factory.py:69`` +
``model_implementations/phi/``. Architecture: parallel attention + MLP
branches off one shared input LayerNorm, **partial** rotary embeddings
(only the first ``rotary_dim`` of each head is rotated), biased
q/k/v/dense projections, biased GELU fc1/fc2 MLP, final LayerNorm, and
an untied LM head **with bias**.
"""

from dataclasses import dataclass

import flax.linen as nn
import jax.numpy as jnp

from ..ops.flash_attention import attention as flash_attention
from ..ops.rope import apply_rope, rope_frequencies
from .gpt2 import causal_lm_loss, default_lm_labels


@dataclass(frozen=True)
class PhiConfig:
    vocab_size: int = 51200
    hidden_size: int = 2560
    intermediate_size: int = 10240
    n_layer: int = 32
    n_head: int = 32
    max_positions: int = 2048
    layer_norm_epsilon: float = 1e-5
    rope_theta: float = 10000.0
    partial_rotary_factor: float = 0.4
    dtype: str = "float32"
    remat: bool = False
    use_flash: bool = True
    tie_word_embeddings: bool = False   # phi's head is untied (+ bias)

    @property
    def head_dim(self):
        return self.hidden_size // self.n_head

    @property
    def rotary_dim(self):
        # HF: int(partial_rotary_factor * head_dim), rounded to even
        rd = int(self.partial_rotary_factor * self.head_dim)
        return rd - rd % 2

    @property
    def n_kv_head(self):
        return self.n_head   # MHA

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


def phi_2(**kw):
    defaults = dict(dtype="bfloat16", remat=True)
    defaults.update(kw)
    return PhiConfig(**defaults)


def phi_tiny(**kw):
    defaults = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    n_layer=2, n_head=4, max_positions=128,
                    partial_rotary_factor=0.5)
    defaults.update(kw)
    return PhiConfig(**defaults)


def partial_rope(x, cos, sin, positions=None, rotary_dim=None):
    """Rotate the first ``rotary_dim`` features of each head, pass the
    rest through (HF PhiAttention's rotary slice)."""
    rot, rest = x[..., :rotary_dim], x[..., rotary_dim:]
    rot = apply_rope(rot, cos, sin, positions)
    return jnp.concatenate([rot, rest], axis=-1)


class PhiAttention(nn.Module):
    cfg: PhiConfig

    @nn.compact
    def __call__(self, x, train: bool):
        cfg = self.cfg
        B, T, C = x.shape
        H, D = cfg.n_head, cfg.head_dim
        q = nn.Dense(C, dtype=x.dtype, name="q_proj")(x)
        k = nn.Dense(C, dtype=x.dtype, name="k_proj")(x)
        v = nn.Dense(C, dtype=x.dtype, name="v_proj")(x)
        q = q.reshape(B, T, H, D)
        k = k.reshape(B, T, H, D)
        v = v.reshape(B, T, H, D)
        cos, sin = rope_frequencies(cfg.rotary_dim, cfg.max_positions,
                                    cfg.rope_theta)
        q = partial_rope(q, cos, sin, rotary_dim=cfg.rotary_dim)
        k = partial_rope(k, cos, sin, rotary_dim=cfg.rotary_dim)
        if cfg.use_flash:
            y = flash_attention(q, k, v, causal=True)
        else:
            from ..ops.flash_attention import reference_attention
            y = reference_attention(q, k, v, causal=True)
        return nn.Dense(C, dtype=x.dtype, name="dense")(
            y.reshape(B, T, C))


class PhiBlock(nn.Module):
    """Parallel residual off one shared LayerNorm (HF PhiDecoderLayer)."""
    cfg: PhiConfig

    @nn.compact
    def __call__(self, x, train: bool):
        cfg = self.cfg
        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=x.dtype,
                         name="input_layernorm")(x)
        attn = PhiAttention(cfg, name="self_attn")(h, train)
        up = nn.Dense(cfg.intermediate_size, dtype=x.dtype, name="fc1")(h)
        mlp = nn.Dense(cfg.hidden_size, dtype=x.dtype,
                       name="fc2")(nn.gelu(up))
        return x + attn + mlp


class PhiForCausalLM(nn.Module):
    cfg: PhiConfig

    @nn.compact
    def __call__(self, batch, train: bool = False,
                 return_logits: bool = False):
        cfg = self.cfg
        ids = batch["input_ids"]
        dtype = cfg.compute_dtype

        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=dtype,
                     name="embed_tokens")(ids)
        block = PhiBlock
        if cfg.remat:
            block = nn.remat(PhiBlock, static_argnums=(2,))
        for i in range(cfg.n_layer):
            x = block(cfg, name=f"layers_{i}")(x, train)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=dtype,
                         name="final_layernorm")(x)

        logits = nn.Dense(cfg.vocab_size, dtype=dtype,
                          name="lm_head")(x)   # biased, untied
        if return_logits:
            return logits
        labels = batch.get("labels")
        if labels is None:
            labels = default_lm_labels(ids)
        return causal_lm_loss(logits, labels)
