"""Registry of layered-loss specs for the ZeRO++ scan-over-layers step.

A layered spec decomposes a model's loss into
``embed(outer, batch, key, train) -> x``,
``block(layer_params, x, batch, key, train) -> x`` (one homogeneous
transformer block, scanned), and ``head(outer, x, batch) -> loss``, plus
the tree layout (``layer_prefix``/``n_layer``/``outer_keys``). The
ZeRO++ micro step (``runtime/zero/zeropp.py`` ``_build_layered``) builds
a software-pipelined fwd+bwd from it: layer *i*'s parameters gather as
one flat bucket at a time — prefetched one layer ahead of the block
compute when ``overlap_comm`` is on — and the backward re-gathers and
reduces layer by layer with the same one-ahead lag, so peak gathered
parameters stay bounded to depth+1 layers + the outer leaves — the
reference's stage-3 live-parameter contract
(``deepspeed/runtime/zero/partitioned_param_coordinator.py:285``,
``max_live_parameters``). See docs/zero_overlap.md.

The decomposition must be exact: the manual backward differentiates
``block`` per layer, so any cross-layer coupling outside the residual
stream would silently change gradients. ``zeropp_layered_spec``
therefore returns None whenever the decomposition would change
semantics (unknown model class, MoE/custom-attention llama, a param
tree with keys outside the spec's layout — e.g. LoRA-merged trees);
callers then fall back to the whole-tree gather.
"""

from typing import Any, Optional


def zeropp_layered_spec(module: Any, params_struct: Any) -> Optional[dict]:
    """Best-effort layered spec for ``module``, validated against the
    top-level keys of ``params_struct`` (any pytree shaped like the
    param tree — the engine passes its spec tree)."""
    if module is None or not isinstance(params_struct, dict):
        return None

    spec = None
    from .gpt2 import GPT2LMHeadModel, gpt2_zeropp_layered_spec
    from .llama import LlamaForCausalLM, llama_zeropp_layered_spec
    if isinstance(module, GPT2LMHeadModel):
        spec = gpt2_zeropp_layered_spec(module.cfg)
    elif isinstance(module, LlamaForCausalLM):
        # custom attention (ulysses/ring) and MoE blocks are built into
        # the flat forward; the dense decomposition would drop them
        if module.attention_fn is None and module.mlp_cls is None:
            spec = llama_zeropp_layered_spec(module.cfg)
    if spec is None:
        return None

    expected = set(spec["outer_keys"]) | {
        f"{spec['layer_prefix']}{i}" for i in range(spec["n_layer"])}
    if set(params_struct.keys()) != expected:
        return None
    return spec
