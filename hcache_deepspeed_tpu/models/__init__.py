"""Model zoo (flax.linen, TPU-first)."""

from .gpt2 import (GPT2Config, GPT2LMHeadModel, causal_lm_loss,  # noqa: F401
                   gpt2_125m, gpt2_tiny, gpt2_tp_spec_fn)
from .llama import (LlamaConfig, LlamaForCausalLM, llama2_7b,  # noqa: F401
                    llama2_13b, llama3_8b, llama_tiny, llama_tp_spec_fn)
