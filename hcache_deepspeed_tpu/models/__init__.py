"""Model zoo (flax.linen, TPU-first)."""
