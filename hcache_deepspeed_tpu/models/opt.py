"""OPT model family (facebook/opt-*) in flax.linen.

Reference analog: the OPT kernel-injection policy
(``deepspeed/module_inject/containers/opt.py``) and the v2 engine
factory's opt mapping (``inference/v2/engine_factory.py:69``,
``model_implementations/opt/``). Architecture (pre-norm variants,
opt-1.3b+): LayerNorm, learned position embeddings with the OPT +2
offset, separate biased q/k/v/out projections, ReLU fc1/fc2 MLP, tied
LM head. Param names mirror the HF layout (``self_attn.q_proj``,
``fc1``, ``self_attn_layer_norm``, ``final_layer_norm``) so trained
checkpoints map one-to-one.
"""

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.flash_attention import attention as flash_attention
from .gpt2 import causal_lm_loss, default_lm_labels

#: OPT reserves the first two rows of the position table (HF
#: OPTLearnedPositionalEmbedding hard-codes the same constant)
POSITION_OFFSET = 2


@dataclass(frozen=True)
class OPTConfig:
    vocab_size: int = 50272
    hidden_size: int = 768
    ffn_dim: int = 3072
    n_layer: int = 12
    n_head: int = 12
    max_positions: int = 2048
    layer_norm_epsilon: float = 1e-5
    dtype: str = "float32"
    remat: bool = False
    use_flash: bool = True

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    # engine-facing aliases (ragged engine generic surface)
    @property
    def n_kv_head(self):
        return self.n_head

    @property
    def tie_word_embeddings(self):
        return True          # OPT ties embed_tokens / LM head

    @property
    def head_dim(self):
        return self.hidden_size // self.n_head

    @property
    def n_embd(self):
        return self.hidden_size


def opt_125m(**kw):
    return OPTConfig(**kw)


def opt_tiny(**kw):
    defaults = dict(vocab_size=256, hidden_size=64, ffn_dim=128,
                    n_layer=2, n_head=4, max_positions=128)
    defaults.update(kw)
    return OPTConfig(**defaults)


class OPTAttention(nn.Module):
    cfg: OPTConfig

    @nn.compact
    def __call__(self, x, train: bool):
        cfg = self.cfg
        B, T, C = x.shape
        H, D = cfg.n_head, cfg.head_dim
        q = nn.Dense(C, dtype=x.dtype, name="q_proj")(x)
        k = nn.Dense(C, dtype=x.dtype, name="k_proj")(x)
        v = nn.Dense(C, dtype=x.dtype, name="v_proj")(x)
        q = q.reshape(B, T, H, D)
        k = k.reshape(B, T, H, D)
        v = v.reshape(B, T, H, D)
        if cfg.use_flash:
            y = flash_attention(q, k, v, causal=True)
        else:
            from ..ops.flash_attention import reference_attention
            y = reference_attention(q, k, v, causal=True)
        return nn.Dense(C, dtype=x.dtype,
                        name="out_proj")(y.reshape(B, T, C))


class OPTBlock(nn.Module):
    cfg: OPTConfig

    @nn.compact
    def __call__(self, x, train: bool):
        cfg = self.cfg
        eps = cfg.layer_norm_epsilon
        h = nn.LayerNorm(epsilon=eps, dtype=x.dtype,
                         name="self_attn_layer_norm")(x)
        x = x + OPTAttention(cfg, name="self_attn")(h, train)
        h = nn.LayerNorm(epsilon=eps, dtype=x.dtype,
                         name="final_layer_norm")(x)
        h = nn.relu(nn.Dense(cfg.ffn_dim, dtype=x.dtype, name="fc1")(h))
        return x + nn.Dense(cfg.hidden_size, dtype=x.dtype,
                            name="fc2")(h)


class OPTForCausalLM(nn.Module):
    """Same batch contract as GPT2LMHeadModel / LlamaForCausalLM."""
    cfg: OPTConfig

    @nn.compact
    def __call__(self, batch, train: bool = False,
                 return_logits: bool = False):
        cfg = self.cfg
        ids = batch["input_ids"]
        B, T = ids.shape
        dtype = cfg.compute_dtype

        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=dtype,
                         name="embed_tokens")
        pos = nn.Embed(cfg.max_positions + POSITION_OFFSET,
                       cfg.hidden_size, dtype=dtype,
                       name="embed_positions")
        x = embed(ids) + pos(jnp.arange(T)[None, :] + POSITION_OFFSET)

        block = OPTBlock
        if cfg.remat:
            block = nn.remat(OPTBlock, static_argnums=(2,))
        for i in range(cfg.n_layer):
            x = block(cfg, name=f"layers_{i}")(x, train)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=dtype,
                         name="final_layer_norm")(x)

        logits = embed.attend(x)   # OPT ties the LM head
        if return_logits:
            return logits
        labels = batch.get("labels")
        if labels is None:
            labels = default_lm_labels(ids)
        return causal_lm_loss(logits, labels)
