"""GPT-2 family in flax.linen — the phase-2 end-to-end model
(BASELINE config 1: ZeRO-1 GPT-2 125M).

Written TPU-first: static shapes, bf16-friendly, remat-able blocks, and
tensor-parallel logical sharding rules exposed via ``tp_spec_fn`` so the
same module runs pure-DP, ZeRO-sharded, or Megatron-style TP without code
changes. The causal-LM loss is computed in fp32.
"""

from dataclasses import dataclass, field
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ..ops.flash_attention import attention as flash_attention
from ..parallel.topology import TENSOR_AXIS


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    #: MLP hidden width (0 = the GPT-2 default of 4*n_embd); settable so
    #: a row-pruned + dimension-reduced export (compression/structured
    #: redundancy_clean) can be rebuilt as a genuinely smaller model
    n_inner: int = 0
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    dtype: str = "float32"
    remat: bool = False
    #: jax.checkpoint_policies name for per-block remat (e.g.
    #: "dots_with_no_batch_dims_saveable" keeps matmul outputs and only
    #: recomputes elementwise ops — far cheaper than full remat while
    #: still bounding live activations); implies remat when set
    remat_policy: str = ""
    use_flash: bool = True
    #: flash kernel tile sizes (0 = kernel default of 512); bench-vetted
    #: per shape — exposed so configs can tune MXU occupancy vs VMEM
    flash_block_q: int = 0
    flash_block_k: int = 0
    #: > 0: compute the LM loss in sequence chunks of this size without
    #: materializing the full [B, T, V] fp32 logits (FPDT chunked-loss
    #: trade: one extra head GEMM per chunk in backward)
    loss_chunk: int = 0

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    # engine-facing aliases (the ragged inference engine's generic
    # surface: n_kv_head/head_dim/max_positions)
    @property
    def n_kv_head(self):
        return self.n_head  # MHA

    @property
    def tie_word_embeddings(self):
        return True          # GPT-2 ties wte / LM head

    @property
    def head_dim(self):
        return self.n_embd // self.n_head

    @property
    def max_positions(self):
        return self.n_positions


def gpt2_125m(**kw):
    return GPT2Config(**kw)


def gpt2_tiny(**kw):
    """Test-scale model (reference tests' SimpleModel analog for LM tasks)."""
    defaults = dict(vocab_size=256, n_positions=128, n_embd=64, n_layer=2,
                    n_head=4)
    defaults.update(kw)
    return GPT2Config(**defaults)


class CausalSelfAttention(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, mask, train: bool):
        cfg = self.cfg
        B, T, C = x.shape
        H = cfg.n_head
        qkv = nn.Dense(3 * C, dtype=x.dtype, name="c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, C // H)
        k = k.reshape(B, T, H, C // H)
        v = v.reshape(B, T, H, C // H)
        use_dropout = train and cfg.dropout > 0
        if cfg.use_flash and mask is None and not use_dropout:
            y = flash_attention(
                q, k, v, causal=True,
                block_q=getattr(cfg, "flash_block_q", 0),
                block_k=getattr(cfg, "flash_block_k", 0)).reshape(B, T, C)
        else:
            att = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
                C // H).astype(x.dtype)
            causal = jnp.tril(jnp.ones((T, T), dtype=bool))
            big_neg = jnp.finfo(jnp.float32).min
            att = jnp.where(causal[None, None], att.astype(jnp.float32),
                            big_neg)
            if mask is not None:
                att = jnp.where(mask[:, None, None, :], att, big_neg)
            att = jax.nn.softmax(att, axis=-1).astype(x.dtype)
            if use_dropout:
                att = nn.Dropout(cfg.dropout, deterministic=False)(att)
            y = jnp.einsum("bhqk,bkhd->bqhd", att, v).reshape(B, T, C)
        return nn.Dense(C, dtype=x.dtype, name="c_proj")(y)


class MLP(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, train: bool):
        C = x.shape[-1]
        h = nn.Dense(self.cfg.n_inner or 4 * C, dtype=x.dtype,
                     name="c_fc")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(C, dtype=x.dtype, name="c_proj")(h)
        if train and self.cfg.dropout > 0:
            h = nn.Dropout(self.cfg.dropout, deterministic=False)(h)
        return h


class Block(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, mask, train: bool):
        cfg = self.cfg
        ln1 = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=x.dtype,
                           name="ln_1")
        ln2 = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=x.dtype,
                           name="ln_2")
        x = x + CausalSelfAttention(cfg, name="attn")(ln1(x), mask, train)
        x = x + MLP(cfg, name="mlp")(ln2(x), train)
        return x


class GPT2LMHeadModel(nn.Module):
    """Batch contract: {"input_ids": [B, T] int32, optional "labels" [B, T]
    (-100 = ignore), optional "attention_mask" [B, T]}. Returns the mean
    causal-LM loss (fp32 scalar); labels default to input_ids shifted."""
    cfg: GPT2Config

    @nn.compact
    def __call__(self, batch, train: bool = False,
                 return_logits: bool = False, pld_theta=None):
        cfg = self.cfg
        ids = batch["input_ids"]
        mask = batch.get("attention_mask")
        dtype = cfg.compute_dtype
        B, T = ids.shape

        wte = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=dtype, name="wte")
        wpe = nn.Embed(cfg.n_positions, cfg.n_embd, dtype=dtype, name="wpe")
        x = wte(ids) + wpe(jnp.arange(T)[None, :])
        if train and cfg.dropout > 0:
            x = nn.Dropout(cfg.dropout, deterministic=False)(x)

        block = Block
        if cfg.remat or cfg.remat_policy:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy) \
                if cfg.remat_policy else None
            block = nn.remat(Block, static_argnums=(3,), policy=policy)
        use_pld = pld_theta is not None and train
        if use_pld:
            if not self.has_rng("dropout"):
                # a fixed fallback key would drop the SAME layer subset
                # every step — stochastic depth needs a fresh key
                raise ValueError(
                    "progressive layer drop requires a 'dropout' rng: "
                    "model.apply(..., rngs={'dropout': key})")
            pld_key = self.make_rng("dropout")
        for i in range(cfg.n_layer):
            blk = block(cfg, name=f"h_{i}")
            if use_pld:
                # progressive layer drop: deeper layers drop more
                # (compression/progressive_layer_drop.py ramp)
                from ..compression.progressive_layer_drop import pld_layer
                keep = 1.0 - ((i + 1) / cfg.n_layer) * (1.0 - pld_theta)
                x = pld_layer(lambda h, blk=blk: blk(h, mask, train), x,
                              keep, jax.random.fold_in(pld_key, i))
            else:
                x = blk(x, mask, train)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=dtype,
                         name="ln_f")(x)
        if return_logits:
            return wte.attend(x)  # tied LM head (GPT-2 ties wte/lm_head)

        labels = batch.get("labels")
        if labels is None:
            labels = default_lm_labels(ids)
        if cfg.loss_chunk:
            if T % cfg.loss_chunk == 0:
                from ..sequence.fpdt import chunked_lm_loss
                head = wte.embedding.astype(dtype).T
                return chunked_lm_loss(x, head, labels,
                                       chunk=cfg.loss_chunk)
            _warn_loss_chunk_fallback(T, cfg.loss_chunk)
        return causal_lm_loss(wte.attend(x), labels)


def _warn_loss_chunk_fallback(T, chunk):
    """The chunked path exists to avoid the [B, T, V] fp32 logits; a
    silent fallback would OOM at exactly the scale the flag targets."""
    from ..utils.logging import logger
    logger.warning(
        "loss_chunk=%d does not divide T=%d — falling back to the "
        "full-logits loss (materializes [B, T, V] fp32). Pad the "
        "sequence or pick a divisor.", chunk, T)


def default_lm_labels(ids):
    """Next-token labels from input ids: shift left, ignore final position."""
    return jnp.pad(ids[:, 1:], ((0, 0), (0, 1)), constant_values=-100)


def causal_lm_loss(logits, labels):
    """Mean cross-entropy over non-ignored (-100) positions, fp32."""
    logits = logits.astype(jnp.float32)
    valid = labels != -100
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None],
                               axis=-1).squeeze(-1)
    nll = jnp.where(valid, nll, 0.0)
    return nll.sum() / jnp.maximum(valid.sum(), 1)


def gpt2_tp_spec_fn(path, leaf):
    """Megatron-style TP rules for this module tree (reference: the AutoTP
    policy idea, module_inject/auto_tp.py — column-split c_attn/c_fc,
    row-split c_proj, vocab-split embeddings)."""
    names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
    joined = "/".join(str(n) for n in names)
    if leaf.ndim < 2:
        return PartitionSpec()
    if "wte" in joined or "wpe" in joined:
        return PartitionSpec(None, TENSOR_AXIS)
    if "c_attn" in joined or "c_fc" in joined:
        return PartitionSpec(None, TENSOR_AXIS)  # column parallel
    if "c_proj" in joined:
        return PartitionSpec(TENSOR_AXIS, None)  # row parallel
    return PartitionSpec()


# ------------------------------------------------------------------ #
# Pipeline-parallel layer factory (reference: PipelineModule usage —
# deepspeed/runtime/pipe/module.py:86; GPT2 layer decomposition follows
# the Megatron-on-DeepSpeed examples' GPT2ModelPipe)
# ------------------------------------------------------------------ #
class TiedEmbed(nn.Module):
    """One embedding table usable as input embed ('embed') or tied LM head
    ('attend'); both modes share identical param structure so a
    ``TiedLayerSpec`` slot can serve first and last pipeline layers
    (reference: tied-weight sync, pipe/engine.py:275)."""
    vocab_size: int
    features: int
    dtype: Any = jnp.float32
    mode: str = "embed"

    @nn.compact
    def __call__(self, x, train: bool = False):
        emb = nn.Embed(self.vocab_size, self.features, dtype=self.dtype,
                       name="weight")
        if self.mode == "embed":
            ids = x["input_ids"] if isinstance(x, dict) else x
            return emb(ids)
        return emb.attend(x)


class GPT2PosEmbed(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, train: bool = False):
        T = x.shape[1]
        wpe = nn.Embed(self.cfg.n_positions, self.cfg.n_embd,
                       dtype=self.cfg.compute_dtype, name="wpe")
        x = x + wpe(jnp.arange(T)[None, :])
        if train and self.cfg.dropout > 0:
            x = nn.Dropout(self.cfg.dropout, deterministic=False)(x)
        return x


class GPT2PipeBlock(nn.Module):
    """Block with the pipeline body contract ``(x, train) -> x``.
    Honors ``cfg.remat``/``remat_policy`` like the flat model."""
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, train: bool = False):
        block = Block
        if self.cfg.remat or self.cfg.remat_policy:
            policy = getattr(jax.checkpoint_policies,
                             self.cfg.remat_policy) \
                if self.cfg.remat_policy else None
            block = nn.remat(Block, static_argnums=(3,), policy=policy)
        return block(self.cfg, name="block")(x, None, train)


class GPT2FinalNorm(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.LayerNorm(epsilon=self.cfg.layer_norm_epsilon,
                            dtype=self.cfg.compute_dtype, name="ln_f")(x)


def lm_loss_fn(logits, batch):
    """Pipeline loss head: labels from the batch (shifted ids fallback)."""
    ids = batch["input_ids"]
    labels = batch.get("labels")
    if labels is None:
        labels = default_lm_labels(ids)
    return causal_lm_loss(logits, labels)


def gpt2_flat_to_pipeline(params, cfg: GPT2Config):
    """Flat ``GPT2LMHeadModel`` param tree → ``PipelineModule`` layout.

    Reference analog: ``PipelineModule.load_state_dir`` + the layer
    checkpoint files — loading a non-pipeline checkpoint into a pipeline
    run. Here it is a pure tree reshape: per-layer ``h_i`` subtrees stack
    into the body's leading layer dim, the tied embedding fills the
    ``wte`` slot, and positional/final layers move to their pre/post
    spots (indices fixed by ``gpt2_pipeline_layers``'s spec list). Works
    on any flat source — a training run or
    ``checkpoint.hf_loader.convert_hf_state_dict``."""
    from ._pipe_util import stack_flat_layers
    block_tree = stack_flat_layers(params, "h_", cfg.n_layer,
                                   required=["wte", "wpe", "ln_f"],
                                   model_name="gpt2")
    return {
        # pre layer_0 is the tied embed (lives under tied/), layer_1 wpe
        "pre": {"layer_1": {"wpe": dict(params["wpe"])}},
        "post": {"layer_0": {"ln_f": dict(params["ln_f"])}},
        "tied": {"wte": {"weight": dict(params["wte"])}},
        "blocks": {"block": block_tree},
    }


def gpt2_zeropp_layered_spec(cfg: GPT2Config):
    """Layered loss decomposition for the ZeRO++ scan-over-layers gather
    (``runtime/zero/zeropp.py``): outer leaves (tied embedding, position
    embedding, final norm) gather once; block layers gather one at a
    time inside the scan body. Numerics match ``GPT2LMHeadModel`` —
    every piece reuses the flat model's own modules/loss functions.
    Reference memory contract: stage-3 live params bounded per-module
    (``partitioned_param_coordinator.py:285``)."""
    dtype = cfg.compute_dtype

    def embed(outer, batch, key, train):
        x = TiedEmbed(cfg.vocab_size, cfg.n_embd, dtype=dtype,
                      mode="embed").apply(
            {"params": {"weight": outer["wte"]}}, batch)
        rngs = {"dropout": key} if (train and cfg.dropout > 0) else None
        return GPT2PosEmbed(cfg).apply({"params": {"wpe": outer["wpe"]}},
                                       x, train, rngs=rngs)

    def block(layer, x, batch, key, train):
        mask = batch.get("attention_mask")
        rngs = {"dropout": key} if (train and cfg.dropout > 0) else None
        return Block(cfg).apply({"params": layer}, x, mask, train,
                                rngs=rngs)

    def head(outer, x, batch):
        x = GPT2FinalNorm(cfg).apply({"params": {"ln_f": outer["ln_f"]}},
                                     x)
        ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = default_lm_labels(ids)
        T = ids.shape[1]
        if cfg.loss_chunk and T % cfg.loss_chunk == 0:
            from ..sequence.fpdt import chunked_lm_loss
            head_kernel = outer["wte"]["embedding"].astype(dtype).T
            return chunked_lm_loss(x, head_kernel, labels,
                                   chunk=cfg.loss_chunk)
        logits = TiedEmbed(cfg.vocab_size, cfg.n_embd, dtype=dtype,
                           mode="attend").apply(
            {"params": {"weight": outer["wte"]}}, x)
        return causal_lm_loss(logits, labels)

    return {
        "model_name": "gpt2",
        "layer_prefix": "h_",
        "n_layer": cfg.n_layer,
        "outer_keys": ("wte", "wpe", "ln_f"),
        "embed": embed,
        "block": block,
        "head": head,
    }


def gpt2_pipeline_layers(cfg: GPT2Config):
    """(layers, loss_fn) for ``PipelineModule``: tied embed/head, positional
    embed, n_layer homogeneous blocks, final norm."""
    from ..runtime.pipe.module import LayerSpec, TiedLayerSpec
    dtype = cfg.compute_dtype
    layers = [
        TiedLayerSpec("wte", TiedEmbed, cfg.vocab_size, cfg.n_embd,
                      dtype=dtype, mode="embed"),
        LayerSpec(GPT2PosEmbed, cfg),
        *[LayerSpec(GPT2PipeBlock, cfg) for _ in range(cfg.n_layer)],
        LayerSpec(GPT2FinalNorm, cfg),
        TiedLayerSpec("wte", TiedEmbed, cfg.vocab_size, cfg.n_embd,
                      dtype=dtype, mode="attend"),
    ]
    return layers, lm_loss_fn
