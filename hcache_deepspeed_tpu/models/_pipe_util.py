"""Shared helpers for per-family pipeline decompositions."""

import jax
import jax.numpy as jnp


def stack_flat_layers(params, layer_prefix, n_layer, required,
                      model_name):
    """Validate a flat param tree and stack its per-layer subtrees into
    the pipeline body's leading layer dim.

    ``required``: non-layer keys that must exist. Rejects both missing
    layers and layers beyond ``n_layer`` (checkpoint/config mismatch)."""
    missing = [k for k in list(required) +
               [f"{layer_prefix}{i}" for i in range(n_layer)]
               if k not in params]
    if missing:
        raise ValueError(f"flat {model_name} tree is missing {missing}")

    def layer_index(key):
        suffix = key[len(layer_prefix):]
        return int(suffix) if suffix.isdigit() else -1

    extra = [k for k in params if k.startswith(layer_prefix)
             and layer_index(k) >= n_layer]
    if extra:
        raise ValueError(
            f"flat {model_name} tree has layers beyond "
            f"n_layer={n_layer}: {extra} (checkpoint/config layer-count "
            "mismatch)")
    return jax.tree.map(lambda *xs: jnp.stack(xs),
                        *[params[f"{layer_prefix}{i}"]
                          for i in range(n_layer)])
