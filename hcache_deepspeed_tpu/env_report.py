"""Environment / capability report.

Reference analog: ``bin/ds_report`` → ``deepspeed/env_report.py`` — op
compatibility table + version/platform summary. Here the "ops" are the
Pallas kernel registry plus platform capabilities.
"""

import sys


GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"


def collect_report():
    import jax

    from .platform import get_platform
    from . import ops as ops_pkg
    from .version import __version__

    plat = get_platform()
    report = {
        "version": __version__,
        "jax_version": jax.__version__,
        "platform": type(plat).__name__,
        "device_kind": plat.device_kind(),
        "device_count": plat.device_count(),
        "process_count": plat.process_count(),
        "supports_pallas": plat.supports_pallas(),
        "supports_host_offload": plat.supports_host_offload(),
        "peak_bf16_tflops": plat.peak_tflops("bfloat16"),
        "op_table": ops_pkg.op_report(),
    }
    return report


def main(argv=None):
    report = collect_report()
    print("-" * 60)
    print("hcache_deepspeed_tpu environment report (hds_report)")
    print("-" * 60)
    for key in ("version", "jax_version", "platform", "device_kind",
                "device_count", "process_count", "peak_bf16_tflops"):
        print(f"{key:.<32} {report[key]}")
    print("-" * 60)
    print("capability / op compatibility")
    print("-" * 60)
    for cap in ("supports_pallas", "supports_host_offload"):
        print(f"{cap:.<32} {GREEN_OK if report[cap] else RED_NO}")
    print(report["op_table"])
    return 0


if __name__ == "__main__":
    sys.exit(main())
