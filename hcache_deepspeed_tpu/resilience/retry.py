"""Recovery policies: bounded retry, circuit breaker, progress watchdog.

All three are *clock-agnostic and deterministic*: backoff delays come
from a policy + a caller-owned seeded RNG (so a virtual-clock chaos run
replays bit-identically), the breaker and the watchdog count scheduler
steps (ticks), not wall seconds — the same discipline that makes the
serving simulation a pure function of its trace.
"""

from collections import deque
from dataclasses import dataclass
from enum import Enum
from typing import Dict, Optional

import numpy as np


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff + seeded jitter.

    ``delay(attempt, rng)`` prices the sleep before retry ``attempt``
    (1-based): ``base * mult**(attempt-1)`` capped at ``max_s``, plus
    up to ``jitter_frac`` of that drawn from the caller's RNG — jitter
    decorrelates retry storms across lanes while staying replayable.
    """

    max_attempts: int = 3
    backoff_base_s: float = 0.005
    backoff_mult: float = 2.0
    backoff_max_s: float = 0.25
    jitter_frac: float = 0.25

    def delay(self, attempt: int,
              rng: Optional[np.random.Generator] = None) -> float:
        base = min(self.backoff_base_s *
                   self.backoff_mult ** max(attempt - 1, 0),
                   self.backoff_max_s)
        if rng is not None and self.jitter_frac > 0.0:
            base *= 1.0 + self.jitter_frac * float(rng.random())
        return base


def call_with_retry(fn, policy: RetryPolicy, clock=None, rng=None,
                    retryable=(Exception,), on_retry=None):
    """Run ``fn()`` under ``policy``. Between attempts sleeps
    ``clock.sleep(delay)`` (no-op without a clock). ``on_retry(exc,
    attempt, delay)`` observes each retry; the final failure re-raises.
    """
    attempt = 0
    while True:
        try:
            return fn()
        except retryable as exc:
            attempt += 1
            if attempt >= policy.max_attempts:
                raise
            delay = policy.delay(attempt, rng)
            if on_retry is not None:
                on_retry(exc, attempt, delay)
            if clock is not None:
                clock.sleep(delay)


class BreakerState(Enum):
    CLOSED = 0      # normal: calls flow
    OPEN = 1        # tripped: calls blocked until cooldown elapses
    HALF_OPEN = 2   # cooldown over: one probe allowed through


class CircuitBreaker:
    """Step-counted circuit breaker.

    ``threshold`` failures inside a sliding ``window`` of ticks trip it
    OPEN; after ``cooldown`` ticks it goes HALF_OPEN and ``allow``
    admits a single probe — a probe success closes the breaker, a probe
    failure re-opens it for another cooldown. The serving scheduler
    keys restore-vs-recompute routing off ``allow``.
    """

    def __init__(self, threshold: int = 3, window: int = 32,
                 cooldown: int = 16):
        self.threshold = max(1, threshold)
        self.window = max(1, window)
        self.cooldown = max(1, cooldown)
        self.state = BreakerState.CLOSED
        self.trips = 0
        self._failures = deque()
        self._opened_at = 0
        self._probe_out = False

    def allow(self, tick: int) -> bool:
        if self.state == BreakerState.CLOSED:
            return True
        if self.state == BreakerState.OPEN:
            if tick - self._opened_at >= self.cooldown:
                self.state = BreakerState.HALF_OPEN
                self._probe_out = False
            else:
                return False
        # HALF_OPEN: exactly one probe until its verdict arrives
        if self._probe_out:
            return False
        self._probe_out = True
        return True

    def record_failure(self, tick: int) -> bool:
        """Returns True when this failure *trips* the breaker."""
        if self.state == BreakerState.HALF_OPEN:
            self.state = BreakerState.OPEN
            self._opened_at = tick
            self.trips += 1
            self._failures.clear()
            return True
        self._failures.append(tick)
        while self._failures and tick - self._failures[0] > self.window:
            self._failures.popleft()
        if self.state == BreakerState.CLOSED and \
                len(self._failures) >= self.threshold:
            self.state = BreakerState.OPEN
            self._opened_at = tick
            self.trips += 1
            self._failures.clear()
            return True
        return False

    def record_success(self, tick: int) -> None:
        if self.state == BreakerState.HALF_OPEN:
            self.state = BreakerState.CLOSED
        self._probe_out = False
        self._failures.clear()


class Watchdog:
    """Stuck-progress detector over keyed work items (restore lanes).

    ``note(key, tick)`` records progress; ``stuck(key, tick)`` is True
    once ``limit`` ticks pass with no note — the scheduler then aborts
    the lane and re-enters via recompute (or fails typed).
    """

    def __init__(self, limit: int = 8):
        self.limit = max(1, limit)
        self._last: Dict = {}
        self.aborts = 0

    def note(self, key, tick: int) -> None:
        self._last[key] = tick

    def drop(self, key) -> None:
        self._last.pop(key, None)

    def stuck(self, key, tick: int) -> bool:
        last = self._last.get(key)
        if last is None:
            # first sighting counts as progress (arming the timer)
            self._last[key] = tick
            return False
        return tick - last > self.limit
