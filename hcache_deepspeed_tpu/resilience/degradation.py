"""Graceful-degradation ladder for the serving scheduler.

Under a fault storm the right move is rarely "keep admitting at full
rate": every admitted request deepens the recovery debt (more KV to
restore, more retries contending for the link). The ladder maps two
signals — recent fault rate and KV pressure — onto escalating,
*reversible* actions:

====================  ==============================================
level                 action (each level includes the ones below)
====================  ==============================================
NORMAL                nothing
SHED                  reject the lowest-priority queued request per
                      step (typed reason ``"shed_degraded"``)
CAP_TOKENS            cap ``max_new_tokens`` of new admissions
PAUSE_ADMISSIONS      admit nothing; serve only what is resident
====================  ==============================================

Escalation is **fault-gated**: with zero faults in the window the
ladder stays at NORMAL regardless of KV pressure — ordinary overload
is the scheduler's preemption machinery's job, and a fault-free run
behaves exactly as before this layer existed. KV pressure *amplifies*
escalation during a fault storm (a storm while the pool is saturated
is the dangerous quadrant). De-escalation requires ``calm_steps``
consecutive steps below the level's threshold (hysteresis — no
flapping).
"""

from collections import deque
from dataclasses import dataclass
from enum import IntEnum


class DegradationLevel(IntEnum):
    NORMAL = 0
    SHED = 1
    CAP_TOKENS = 2
    PAUSE_ADMISSIONS = 3


@dataclass(frozen=True)
class LadderConfig:
    #: sliding window (scheduler steps) the fault rate is computed over
    window: int = 16
    #: faults-per-step thresholds for each escalation level
    shed_rate: float = 0.25
    cap_rate: float = 0.50
    pause_rate: float = 0.75
    #: KV utilization above which thresholds are scaled down (pressure
    #: amplifies a storm); 1.0 disables the amplification
    kv_pressure: float = 0.90
    kv_amplify: float = 0.5
    #: new-admission token cap at CAP_TOKENS and above
    cap_max_new_tokens: int = 8
    #: consecutive calm steps required to step one level down
    calm_steps: int = 4


class DegradationLadder:
    def __init__(self, config: LadderConfig = None):
        self.config = config or LadderConfig()
        self.level = DegradationLevel.NORMAL
        self._faults = deque()
        self._calm = 0
        self.degraded_steps = 0

    def observe(self, step: int, faults: int, kv_utilization: float,
                queue_depth: int) -> DegradationLevel:
        """Feed one step's signals; returns the level to apply to the
        *next* scheduling decisions."""
        cfg = self.config
        if faults:
            self._faults.append((step, faults))
        while self._faults and step - self._faults[0][0] >= cfg.window:
            self._faults.popleft()
        rate = sum(n for _, n in self._faults) / cfg.window
        scale = 1.0
        if kv_utilization >= cfg.kv_pressure and queue_depth > 0:
            scale = cfg.kv_amplify
        if rate <= 0.0:
            target = DegradationLevel.NORMAL
        elif rate >= cfg.pause_rate * scale:
            target = DegradationLevel.PAUSE_ADMISSIONS
        elif rate >= cfg.cap_rate * scale:
            target = DegradationLevel.CAP_TOKENS
        elif rate >= cfg.shed_rate * scale:
            target = DegradationLevel.SHED
        else:
            target = DegradationLevel.NORMAL
        if target > self.level:
            self.level = target
            self._calm = 0
        elif target < self.level:
            self._calm += 1
            if self._calm >= cfg.calm_steps:
                self.level = DegradationLevel(self.level - 1)
                self._calm = 0
        else:
            self._calm = 0
        if self.level > DegradationLevel.NORMAL:
            self.degraded_steps += 1
        return self.level

    def gauges(self) -> dict:
        """Read-only exposition/SLO context: the current ladder level
        and the cumulative degraded-step count. The SLO tracker
        (``telemetry.slo``) consumes the level per step (via
        ``ServingMetrics.on_step`` → ``note_degradation``) so burn-rate
        dashboards can tell "budget burning under overload" from
        "budget burning because we are shedding on purpose"."""
        return {"degradation_level": float(int(self.level)),
                "degraded_steps": float(self.degraded_steps)}
