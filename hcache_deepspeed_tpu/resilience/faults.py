"""Deterministic, seeded fault injection.

Reference analog: none inside DeepSpeed — the reference's failure story
is "elasticity restarts the job". Production serving needs the opposite
discipline: every failure mode must be *injectable* (so recovery code
is exercised, not hoped for), *deterministic* (so a chaos run replays
bit-identically from its seed — the same property the virtual-clock
simulation gives the scheduler), and *free when off* (the hooks ride
hot paths: the ragged ``put``, the restore chunk lane, the block
allocator).

Design:

* **Named sites.** Each hook names the operation it guards
  (:data:`SITES`). A :class:`FaultPlan` binds rules to sites; sites
  without rules cost one dict lookup and nothing else, and with no
  plan installed the hook is a single attribute check
  (``injector.enabled``) — the same zero-cost-when-disabled contract
  as the telemetry tracer.
* **Deterministic streams.** Every site owns its own
  ``numpy.random.Generator`` seeded from ``(plan.seed, crc32(site))``,
  and fires are decided per *hit* (the site's own call counter). The
  firing sequence is therefore a pure function of (plan, per-site call
  sequence) — independent of wall clock, thread timing, and of what
  any *other* site did. Two runs of the same seeded trace produce the
  same faults at the same hits: the chaos determinism gate asserts
  exactly this.
* **Typed errors.** A fired rule raises :class:`InjectedFault`
  carrying the site, the hit index and the call context (notably the
  offending ``uid`` when the caller knows it) — the recovery layers
  key their policies off this type and attribute blame from it.
"""

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple
from zlib import crc32

import numpy as np

#: the named fault sites wired through the stack. Hooks may fire other
#: (dotted) site names — a plan simply never matches them — but these
#: are the ones the chaos harness covers by default.
SITES = (
    "engine.prefill",   # ragged put containing prompt tokens
    "engine.decode",    # ragged put of decode lanes only
    "restore.ship",     # host->device latent chunk ship (restore lane)
    "restore.replay",   # QKV replay dispatch consuming a shipped chunk
    "alloc.blocks",     # KV block allocation
    "host.latents",     # host latent store absorption
    "ckpt.write",       # checkpoint state persistence
    "ckpt.read",        # checkpoint state restoration
    # replica failure domains (fired by the serving fleet, once per
    # live replica per fleet step, ctx carries the replica id)
    "replica.crash",          # replica dies: engine + KV lost
    "replica.hang",           # replica stops stepping (heals later)
    "replica.net_partition",  # router cannot reach it (it keeps
                              # serving residents; heals later)
)


class InjectedFault(RuntimeError):
    """A fault fired by the injector. ``uid`` (when the call context
    carried one) attributes blame to a single request so the scheduler
    can quarantine it instead of failing the whole batch."""

    def __init__(self, site: str, kind: str = "injected", hit: int = 0,
                 ctx: Optional[Dict] = None):
        self.site = site
        self.kind = kind
        self.hit = hit
        self.ctx = dict(ctx or {})
        self.uid = self.ctx.get("uid")
        super().__init__(
            f"injected fault at {site} (hit #{hit}, kind={kind}, "
            f"ctx={self.ctx})")


@dataclass(frozen=True)
class FaultRule:
    """When a site fires.

    ``at_hits`` fires deterministically at those 1-based call indices;
    ``probability`` fires per hit from the site's seeded stream. Both
    may combine; ``max_faults`` bounds the total fires of this rule
    (the knob that turns "flaky" into "flaky then heals" — what the
    retry/backoff path needs to be able to succeed).
    """

    site: str
    probability: float = 0.0
    at_hits: Tuple[int, ...] = ()
    max_faults: Optional[int] = None
    kind: str = "injected"

    def to_dict(self) -> Dict:
        return {"site": self.site, "probability": self.probability,
                "at_hits": list(self.at_hits),
                "max_faults": self.max_faults, "kind": self.kind}

    @classmethod
    def from_dict(cls, d: Dict) -> "FaultRule":
        return cls(site=d["site"],
                   probability=float(d.get("probability", 0.0)),
                   at_hits=tuple(d.get("at_hits", ())),
                   max_faults=d.get("max_faults"),
                   kind=d.get("kind", "injected"))


@dataclass
class FaultPlan:
    """A seeded set of fault rules — the replayable chaos scenario.
    Serializes to/from plain dicts so a chaos artifact can embed the
    exact plan it ran."""

    rules: List[FaultRule] = field(default_factory=list)
    seed: int = 0

    def to_dict(self) -> Dict:
        return {"seed": self.seed,
                "rules": [r.to_dict() for r in self.rules]}

    @classmethod
    def from_dict(cls, d: Dict) -> "FaultPlan":
        return cls(seed=int(d.get("seed", 0)),
                   rules=[FaultRule.from_dict(r)
                          for r in d.get("rules", ())])


class FaultInjector:
    """Evaluates a :class:`FaultPlan` at named sites.

    ``fire(site, **ctx)`` raises :class:`InjectedFault` when a rule
    decides this hit fails; otherwise it returns (and costs one dict
    lookup for un-ruled sites). ``enabled`` is False for the planless
    injector, so hot-path hooks guard with one attribute check.
    """

    def __init__(self, plan: Optional[FaultPlan] = None):
        self.plan = plan
        self._rules: Dict[str, List[FaultRule]] = {}
        self._rng: Dict[str, np.random.Generator] = {}
        self.hits: Dict[str, int] = {}
        self.fired: Dict[str, int] = {}
        self._rule_fired: Dict[int, int] = {}
        self._lock = threading.Lock()
        #: optional observer called with the fault *before* it raises
        #: (the scheduler/metrics layer counts faults through this)
        self.on_fault = None
        if plan is not None:
            for rule in plan.rules:
                self._rules.setdefault(rule.site, []).append(rule)
            for site in self._rules:
                self._rng[site] = np.random.default_rng(
                    [plan.seed & 0x7FFFFFFF, crc32(site.encode())])
        self.enabled = bool(self._rules)

    @property
    def total_fired(self) -> int:
        with self._lock:
            return sum(self.fired.values())

    def fire(self, site: str, **ctx) -> None:
        """Count a hit at ``site``; raise if the plan says it fails."""
        if not self.enabled:
            return
        rules = self._rules.get(site)
        if not rules:
            return
        with self._lock:
            hit = self.hits.get(site, 0) + 1
            self.hits[site] = hit
            fault = None
            for i, rule in enumerate(rules):
                key = id(rule)
                fired = self._rule_fired.get(key, 0)
                decide = hit in rule.at_hits
                if not decide and rule.probability > 0.0:
                    # the draw happens on every hit so the stream stays
                    # aligned with the hit counter (determinism)
                    decide = bool(self._rng[site].random() <
                                  rule.probability)
                if decide and (rule.max_faults is None or
                               fired < rule.max_faults):
                    self._rule_fired[key] = fired + 1
                    self.fired[site] = self.fired.get(site, 0) + 1
                    fault = InjectedFault(site, kind=rule.kind, hit=hit,
                                          ctx=ctx)
                    break
        if fault is not None:
            try:
                from ..telemetry.tracer import get_tracer
                get_tracer().instant("resilience.fault", site=site,
                                     hit=fault.hit, kind=fault.kind,
                                     uid=fault.uid)
            except Exception:
                pass
            if self.on_fault is not None:
                self.on_fault(fault)
            raise fault

    def summary(self) -> Dict:
        # locked: the injector is shared across the server loop, the
        # fleet pump and the chaos driver; dict() copies here raced
        # concurrent fire() mutation (HDS-L002)
        with self._lock:
            return {"hits": dict(self.hits),
                    "fired": dict(self.fired),
                    "total_fired": sum(self.fired.values())}


#: planless, permanently-disabled injector — the default the hooks see
_NULL_INJECTOR = FaultInjector(None)
_current = _NULL_INJECTOR


def get_injector() -> FaultInjector:
    return _current


def install(plan_or_injector) -> FaultInjector:
    """Install a plan (or prebuilt injector) as the process-wide
    injector the site hooks consult. Returns the injector."""
    global _current
    inj = (plan_or_injector
           if isinstance(plan_or_injector, FaultInjector)
           else FaultInjector(plan_or_injector))
    _current = inj
    return inj


def uninstall() -> None:
    global _current
    _current = _NULL_INJECTOR


@contextmanager
def injected(plan_or_injector):
    """``with injected(plan) as inj:`` — scoped installation; always
    uninstalls, even when the body raises."""
    inj = install(plan_or_injector)
    try:
        yield inj
    finally:
        uninstall()
