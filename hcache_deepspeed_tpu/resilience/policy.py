"""The one knob bundle the serving scheduler consumes.

Defaults are chosen so a fault-free run is *bit-identical* to the
pre-resilience scheduler: retries/breaker/watchdog only ever engage on
a fault or a stalled lane, the ladder is fault-gated, and deadline
enforcement only affects requests that actually set a deadline.
"""

from dataclasses import dataclass, field

from .degradation import LadderConfig
from .retry import RetryPolicy


@dataclass
class ResiliencePolicy:
    #: restore-lane chunk-ship retry (exponential backoff, seeded jitter)
    retry: RetryPolicy = field(default_factory=RetryPolicy)
    #: restore-path circuit breaker (counts scheduler steps)
    breaker_threshold: int = 3
    breaker_window: int = 32
    breaker_cooldown: int = 12
    #: steps without chunk progress before an open lane is aborted
    watchdog_steps: int = 12
    #: per-request restore failures (retry exhaustion / lane aborts /
    #: recompute faults) before the request fails typed
    max_restore_failures: int = 3
    #: graceful-degradation ladder config
    ladder: LadderConfig = field(default_factory=LadderConfig)
    #: fail requests whose absolute deadline has passed (typed
    #: ``"deadline_exceeded"``); requests without a deadline never fail
    enforce_deadlines: bool = True
    #: seed for the retry-jitter stream (kept separate from the fault
    #: plan's seed so recovery timing and fault timing decorrelate)
    seed: int = 0
