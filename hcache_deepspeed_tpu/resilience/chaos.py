"""Chaos harness: seeded fault plans over the virtual-clock simulation.

One call = one fully deterministic serving run with faults injected at
every named site, returning the replayable event log plus an invariant
report. The invariants are the robustness contract this subsystem
ships:

1. **terminal-state completeness** — every submitted request ends in
   exactly one terminal state (DONE / REJECTED / FAILED), exactly once
   in the scheduler's ``done`` map;
2. **zero KV leaks** — the block allocator returns to its pre-trace
   free count (quarantines, lane aborts and deadline kills all freed
   their blocks);
3. **restore accounting** — engine ``restore_stats`` agree with the
   scheduler's counters;
4. **determinism** — two runs of the same seed produce byte-identical
   event logs (compare ``ChaosResult.event_digest``).

The harness is pure CPU (SimulatedEngine + VirtualClock), so all of
this is tier-1-testable; ``inference/benchmark.py``'s ``serve_loop
--chaos`` mode wraps it into the CHAOS_SERVE.jsonl artifact.
"""

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..telemetry.critical_path import (attribute, closure, connected,
                                       CLOSURE_TOL)
from ..telemetry.flight import get_flight_recorder
from .faults import FaultPlan, FaultRule, injected
from .policy import ResiliencePolicy


def default_fault_plan(seed: int = 0) -> FaultPlan:
    """Faults at every named serving-path site. ``max_faults`` bounds
    every rule so the storm eventually heals — retries and breaker
    probes can succeed and the trace always drains.

    The ``restore.ship`` rule fires a deterministic 9-hit burst: with
    the default retry budget (3 attempts) that is exactly three
    consecutive retry-exhausted lane aborts — enough to trip the
    breaker (threshold 3) and force the crossover recompute re-entry
    path, which the chaos acceptance gate asserts on.
    """
    return FaultPlan(seed=seed, rules=[
        FaultRule("engine.decode", probability=0.02, max_faults=3),
        FaultRule("engine.prefill", probability=0.03, max_faults=3),
        FaultRule("restore.ship", at_hits=tuple(range(1, 10)),
                  probability=0.05, max_faults=12),
        FaultRule("restore.replay", at_hits=(2,), probability=0.08,
                  max_faults=3),
        FaultRule("alloc.blocks", at_hits=(7,), probability=0.01,
                  max_faults=2),
        FaultRule("host.latents", at_hits=(11,), probability=0.005,
                  max_faults=2),
    ])


@dataclass
class ChaosResult:
    seed: int
    plan: Dict
    requests: List[Dict]
    events: List
    event_digest: str
    metrics: Dict
    fault_summary: Dict
    invariants: Dict
    ok: bool = False
    violations: List[str] = field(default_factory=list)


def _digest(events) -> str:
    payload = json.dumps(events, sort_keys=True,
                         separators=(",", ":")).encode()
    return hashlib.sha256(payload).hexdigest()


def _trace_gates(reqs, violations: List[str],
                 tol: float = CLOSURE_TOL) -> Dict:
    """Causal-trace continuity invariants over a finished trace: every
    terminal request's span DAG must be connected (no orphan spans —
    even across crash evacuations and tier handoffs) and its additive
    attribution must close against the measured E2E latency within
    ``tol``. Appends violations in place; returns the invariant block
    the artifacts record."""
    connected_all, max_residual, traced = True, 0.0, 0
    for r in reqs:
        ctx = getattr(r, "trace", None)
        if ctx is None:
            continue
        traced += 1
        ok, reason = connected(ctx)
        if not ok:
            connected_all = False
            violations.append(
                f"request {r.uid}: trace DAG not connected: {reason}")
        e2e = None if r.finished_at is None \
            else r.finished_at - r.arrival_time
        cok, residual = closure(ctx, e2e, tol=tol)
        if residual != float("inf"):
            max_residual = max(max_residual, residual)
        if not cok:
            violations.append(
                f"request {r.uid}: attribution closure failed "
                f"(residual {residual!r} > {tol})")
    return {"traced_requests": traced,
            "connected": connected_all,
            "max_closure_residual": round(max_residual, 9),
            "closure_tol": tol}


def _trace_row(r) -> Dict:
    """Per-request trace fields for the artifact rows: id, continuity
    verdicts, and the additive TTFT/E2E attribution (seconds)."""
    ctx = getattr(r, "trace", None)
    if ctx is None:
        return {}
    ok, _ = connected(ctx)
    e2e = None if r.finished_at is None \
        else r.finished_at - r.arrival_time
    _, residual = closure(ctx, e2e)
    out = {"trace": ctx.trace_id,
           "trace_connected": ok,
           "trace_hops": ctx.hops,
           "trace_closure_residual":
               None if residual == float("inf")
               else round(residual, 9),
           "e2e_attr": {k: round(v, 9) for k, v in
                        sorted(attribute(ctx).items())}}
    if r.first_token_at is not None:
        out["ttft_attr"] = {
            k: round(v, 9) for k, v in
            sorted(attribute(ctx, until=r.first_token_at).items())}
    return out


def _flight_on_violations(kind: str, seed: int,
                          violations: List[str]) -> None:
    """A failed chaos invariant IS an anomaly: dump a postmortem
    bundle so the failure ships with its context."""
    if not violations:
        return
    get_flight_recorder().dump(
        "chaos_invariant",
        "; ".join(violations[:3]) +
        (f" (+{len(violations) - 3} more)"
         if len(violations) > 3 else ""),
        source=f"chaos:{kind}", step=0, t=0.0,
        snapshot={"kind": kind, "seed": seed,
                  "violations": list(violations)})


def build_chaos_trace(seed: int, n_requests: int, vocab: int,
                      prompt_lo: int = 8, prompt_hi: int = 24,
                      max_new: int = 8, rps: float = 40.0,
                      deadline_frac: float = 0.25,
                      deadline_slack_s: float = 0.25):
    """Seeded request trace: mixed priorities, a deadline-carrying
    minority, Poisson arrivals. Returns a list of Requests."""
    from ..serving import Request
    rng = np.random.default_rng([seed, 0x7A0])
    arrive = np.cumsum(rng.exponential(1.0 / rps, n_requests))
    reqs = []
    for i in range(n_requests):
        plen = int(rng.integers(prompt_lo, prompt_hi + 1))
        prompt = [int(t) for t in rng.integers(0, vocab, (plen,))]
        deadline = None
        if rng.random() < deadline_frac:
            deadline = float(arrive[i]) + deadline_slack_s
        reqs.append(Request(
            uid=i, prompt=prompt, max_new_tokens=max_new,
            arrival_time=float(arrive[i]),
            priority=int(rng.integers(0, 3)),
            deadline=deadline))
    return reqs


def default_fleet_fault_plan(seed: int = 0) -> FaultPlan:
    """Replica-level failure domains on top of a thinned engine-level
    storm. Sites fire once per live replica per fleet step (hit
    counters are per-site, fleet-global), so ``at_hits`` pins faults
    to deterministic (step, replica) coordinates for a fixed fleet
    size. One crash, a hang and a partition per run by default —
    enough to exercise evacuation, breaker trip/heal and re-routing
    while the trace still drains."""
    return FaultPlan(seed=seed, rules=[
        FaultRule("replica.crash", at_hits=(90,), max_faults=1),
        FaultRule("replica.hang", at_hits=(40,), probability=0.002,
                  max_faults=2),
        FaultRule("replica.net_partition", at_hits=(150,),
                  probability=0.002, max_faults=2),
        FaultRule("engine.decode", probability=0.01, max_faults=2),
        FaultRule("restore.ship", probability=0.02, max_faults=4),
    ])


@dataclass
class FleetChaosResult:
    seed: int
    n_replicas: int
    plan: Dict
    requests: List[Dict]
    event_digest: str
    fleet_summary: Dict
    migrations: List[Dict]
    invariants: Dict
    ok: bool = False
    violations: List[str] = field(default_factory=list)


def run_fleet_chaos(seed: int = 0, n_replicas: int = 3,
                    n_requests: int = 48,
                    fault_plan: Optional[FaultPlan] = None,
                    policy: Optional[ResiliencePolicy] = None,
                    num_blocks: int = 12, block_size: int = 8,
                    max_lanes: int = 4, max_tracked: int = 8,
                    max_context: int = 64, max_new: int = 10,
                    rps: float = 400.0,
                    drain_replica: Optional[int] = None,
                    drain_at_step: int = 60) -> FleetChaosResult:
    """One deterministic fleet chaos run: a seeded multi-tenant trace
    spread over ``n_replicas`` virtual-clock ``SimulatedEngine``
    replicas, with replica crash/hang/partition faults (plus a thinned
    engine-level storm) injected from the plan. Optionally starts a
    graceful drain of ``drain_replica`` once ``drain_at_step`` fleet
    steps have run.

    Invariants checked (the fleet robustness contract):

    1. exactly-one-terminal-state per request *across the whole
       fleet* — terminal everywhere-counted exactly once (replica done
       maps + the fleet's own terminal map);
    2. zero KV-block leaks and zero tracked sequences on every
       *surviving* (non-DEAD) replica;
    3. migration accounting balance — every eviction reached exactly
       one terminal mode (landed / recompute-landed / expired /
       cancelled / failed), nothing left in transit;
    4. per-replica restore accounting (engine restore_stats vs
       scheduler counters) on surviving replicas;
    5. determinism — the digest over the fleet event log + every
       replica's scheduler event log is a pure function of the seed
       (the caller runs twice and compares digests).
    """
    from ..inference.config import RaggedInferenceEngineConfig
    from ..serving import (FleetConfig, ReplicaState, RouterConfig,
                           ServerConfig, ServingFleet, SimulatedEngine,
                           VirtualClock)

    plan = fault_plan if fault_plan is not None \
        else default_fleet_fault_plan(seed)
    policy = policy or ResiliencePolicy(seed=seed)

    def make_engine():
        return SimulatedEngine(RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": max_tracked,
                           "max_ragged_batch_size": 256,
                           "max_ragged_sequence_count": max_lanes,
                           "max_context": max_context},
            kv_cache={"block_size": block_size,
                      "num_blocks": num_blocks},
            hcache={"enable_latents": True}))

    fleet = ServingFleet(
        engines=[make_engine() for _ in range(n_replicas)],
        clock=VirtualClock(),
        config=FleetConfig(
            n_replicas=n_replicas,
            server=ServerConfig(max_queue_depth=n_requests + 1,
                                kv_demand_fraction=float("inf")),
            router=RouterConfig()),
        resilience=policy)
    reqs = build_chaos_trace(seed, n_requests,
                             fleet.replicas[0].engine.vocab_size,
                             max_new=max_new, rps=rps,
                             prompt_hi=min(24,
                                           max_context - max_new - 1))
    with injected(plan) as inj:
        if drain_replica is None:
            fleet.run_trace(reqs)
        else:
            # drive arrivals manually so the drain starts mid-trace
            arrivals = sorted(reqs, key=lambda r: (r.arrival_time,
                                                   r.uid))
            drained = False
            steps = 0
            while arrivals or fleet.has_work:
                now = fleet.clock.now()
                while arrivals and arrivals[0].arrival_time <= now:
                    fleet.submit(request=arrivals.pop(0))
                if not fleet.has_work and arrivals:
                    fleet.clock.advance_to(arrivals[0].arrival_time)
                    continue
                if not drained and fleet.step_idx >= drain_at_step \
                        and fleet.replicas[drain_replica].state \
                        is ReplicaState.UP:
                    fleet.drain(drain_replica)
                    drained = True
                fleet.step()
                steps += 1
                if steps > 1_000_000:
                    raise RuntimeError("fleet chaos livelock:\n"
                                       + fleet.snapshot())
        fault_fired = dict(inj.fired)

    violations: List[str] = []
    # 1. exactly-one-terminal-state across the whole fleet
    terminal = {"DONE", "REJECTED", "FAILED"}
    for r in reqs:
        if r.state.name not in terminal:
            violations.append(
                f"request {r.uid} ended non-terminal: {r.state.name}")
        holders = sum(1 for rep in fleet.replicas
                      if r.uid in rep.scheduler.done)
        holders += 1 if r.uid in fleet.done else 0
        if holders != 1:
            violations.append(
                f"request {r.uid} terminal in {holders} places "
                "(must be exactly 1)")
    # 2. zero leaks on every surviving replica
    for rep in fleet.replicas:
        if rep.state is ReplicaState.DEAD:
            continue
        free = rep.engine.state.free_blocks
        if free != rep.initial_free_blocks:
            violations.append(
                f"replica {rep.id}: block leak "
                f"({rep.initial_free_blocks} free before, {free} "
                "after)")
        tracked = rep.engine.state.n_tracked_sequences
        if tracked != 0:
            violations.append(
                f"replica {rep.id}: {tracked} sequences still "
                "tracked post-trace")
    # 3. migration accounting balance
    if fleet.in_transit:
        violations.append(
            f"{len(fleet.in_transit)} migrations still in transit "
            "post-trace")
    c = fleet.counters
    landed = (c["landings"] + c["recompute_landings"] +
              c["expired_in_transit"] + c["cancelled_in_transit"] +
              c["failed_in_transit"])
    if c["evictions"] != landed:
        violations.append(
            f"migration imbalance: {c['evictions']} evictions vs "
            f"{landed} terminal migrations ({dict(c)})")
    # 4. per-replica restore accounting (surviving replicas)
    for rep in fleet.replicas:
        if rep.state is ReplicaState.DEAD:
            continue
        rs = rep.engine.restore_stats
        sched = rep.scheduler
        if rs["restores"] != sched.total_restores:
            violations.append(
                f"replica {rep.id}: restore_stats.restores "
                f"{rs['restores']} != scheduler total_restores "
                f"{sched.total_restores}")
    # 5. causal-trace continuity: connected cross-replica span DAGs
    # (crash evacuations included) + attribution closure
    trace_inv = _trace_gates(reqs, violations)
    _flight_on_violations("fleet", seed, violations)

    digest = _digest(fleet.event_log())
    result = FleetChaosResult(
        seed=seed, n_replicas=n_replicas, plan=plan.to_dict(),
        requests=[{
            "uid": r.uid, "state": r.state.name, "error": r.error,
            "reject_reason": r.reject_reason,
            "priority": r.priority, "deadline": r.deadline,
            "tokens": len(r.tokens_out),
            "replica": r.replica,
            "preemptions": r.n_preemptions,
            "restores": r.n_restores,
            "recomputes": r.n_recomputes,
            "migrations": r.n_migrations,
            **_trace_row(r),
        } for r in reqs],
        event_digest=digest,
        fleet_summary=fleet.summary(),
        migrations=[m.to_row() for m in fleet.migrations],
        invariants={
            "terminal_states": sorted({r.state.name for r in reqs}),
            "replica_states": {str(rep.id): rep.state.name
                               for rep in fleet.replicas},
            "fault_fired": fault_fired,
            "counters": dict(fleet.counters),
            "migration_balance_ok": fleet.migration_balance_ok,
            "migration_overlap_ratio":
                round(fleet.migration_overlap_ratio, 6),
            "trace": trace_inv,
        },
        violations=violations,
        ok=not violations)
    return result


def default_disagg_fault_plan(seed: int = 0) -> FaultPlan:
    """Tier-scoped failure domains for the disaggregated fleet.

    ``replica.crash`` fires once per live replica per fleet step in
    replica order, so for a 2-prefill + 2-decode fleet the ``at_hits``
    below deterministically kill one PREFILL replica mid-storm (hit
    141 ≡ replica 0 while 4 are alive, landing in the trace window
    where it holds queued AND mid-prompt chunked work — the requeue
    path, not an empty-replica death) and later one DECODE replica
    (hit 200 lands on replica 2 among the 3 survivors) — the two
    tier failure modes the disagg invariants gate: mid-prompt work
    requeues to the surviving prefill replica, decode state re-ships
    its surviving latents (or recomputes) onto the rest of the decode
    tier. A thinned engine/restore storm rides along."""
    return FaultPlan(seed=seed, rules=[
        FaultRule("replica.crash", at_hits=(141, 200), max_faults=2),
        FaultRule("engine.decode", probability=0.008, max_faults=2),
        FaultRule("restore.ship", probability=0.015, max_faults=4),
    ])


@dataclass
class DisaggChaosResult:
    seed: int
    n_prefill: int
    n_decode: int
    plan: Dict
    requests: List[Dict]
    event_digest: str
    fleet_summary: Dict
    tier_summary: Dict
    handoffs: List[Dict]
    invariants: Dict
    ok: bool = False
    violations: List[str] = field(default_factory=list)


def run_disagg_chaos(seed: int = 0, n_prefill: int = 2,
                     n_decode: int = 2, n_requests: int = 48,
                     fault_plan: Optional[FaultPlan] = None,
                     policy: Optional[ResiliencePolicy] = None,
                     num_blocks: int = 14, block_size: int = 8,
                     max_lanes: int = 4, max_tracked: int = 10,
                     max_context: int = 64, max_new: int = 10,
                     rps: float = 400.0,
                     prefill_chunk: int = 8) -> DisaggChaosResult:
    """One deterministic disaggregated-fleet chaos run: the seeded
    trace from :func:`build_chaos_trace` over an N-prefill + M-decode
    :class:`~..serving.DisaggregatedFleet` with chunked prefill on
    (so mid-prompt crash windows exist) and tier-scoped replica
    faults. Invariants are the fleet set plus the tier contract:

    1. every base fleet-chaos invariant (exactly-one-terminal-state
       across the fleet, zero leaks on survivors, migration
       accounting balance, per-replica restore accounting);
    2. every handoff reached a terminal migration mode — the tier
       link never strands a request;
    3. post-trace, no live PREFILL replica holds decode state (the
       disaggregation contract survived the storm);
    4. determinism — the event digest is a pure function of the seed
       (callers run twice and compare).
    """
    from ..inference.config import RaggedInferenceEngineConfig
    from ..serving import (DisaggConfig, DisaggregatedFleet,
                           FleetConfig, ReplicaRole, ReplicaState,
                           RequestState, RouterConfig, ServerConfig,
                           SimulatedEngine, VirtualClock)

    plan = fault_plan if fault_plan is not None \
        else default_disagg_fault_plan(seed)
    policy = policy or ResiliencePolicy(seed=seed)

    def make_engine():
        return SimulatedEngine(RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": max_tracked,
                           "max_ragged_batch_size": 256,
                           "max_ragged_sequence_count": max_lanes,
                           "max_context": max_context,
                           "prefill_chunk": prefill_chunk},
            kv_cache={"block_size": block_size,
                      "num_blocks": num_blocks},
            hcache={"enable_latents": True}))

    n = n_prefill + n_decode
    fleet = DisaggregatedFleet(
        engines=[make_engine() for _ in range(n)],
        clock=VirtualClock(),
        config=FleetConfig(
            n_replicas=n,
            server=ServerConfig(max_queue_depth=n_requests + 1,
                                kv_demand_fraction=float("inf"),
                                prefill_chunk=prefill_chunk,
                                preempt_restore_grace=1),
            router=RouterConfig()),
        disagg=DisaggConfig(n_prefill=n_prefill, n_decode=n_decode),
        resilience=policy)
    reqs = build_chaos_trace(seed, n_requests,
                             fleet.replicas[0].engine.vocab_size,
                             max_new=max_new, rps=rps,
                             prompt_hi=min(24,
                                           max_context - max_new - 1))
    with injected(plan) as inj:
        fleet.run_trace(reqs)
        fault_fired = dict(inj.fired)

    violations: List[str] = []
    terminal = {"DONE", "REJECTED", "FAILED"}
    for r in reqs:
        if r.state.name not in terminal:
            violations.append(
                f"request {r.uid} ended non-terminal: {r.state.name}")
        holders = sum(1 for rep in fleet.replicas
                      if r.uid in rep.scheduler.done)
        holders += 1 if r.uid in fleet.done else 0
        if holders != 1:
            violations.append(
                f"request {r.uid} terminal in {holders} places")
    for rep in fleet.replicas:
        if rep.state is ReplicaState.DEAD:
            continue
        if rep.engine.state.free_blocks != rep.initial_free_blocks:
            violations.append(
                f"replica {rep.id}: block leak "
                f"({rep.initial_free_blocks} -> "
                f"{rep.engine.state.free_blocks})")
        if rep.engine.state.n_tracked_sequences != 0:
            violations.append(
                f"replica {rep.id}: sequences still tracked")
        rs = rep.engine.restore_stats
        if rs["restores"] != rep.scheduler.total_restores:
            violations.append(
                f"replica {rep.id}: restore accounting mismatch")
    if fleet.in_transit:
        violations.append(
            f"{len(fleet.in_transit)} migrations still in transit")
    if not fleet.migration_balance_ok:
        violations.append(
            f"migration imbalance: {dict(fleet.counters)}")
    # tier contract: every handoff terminal; no decode state stranded
    # on a live prefill replica
    handoffs = [m for m in fleet.migrations if m.reason == "handoff"]
    for m in handoffs:
        if not m.mode:
            violations.append(f"handoff {m.uid} never terminal")
    for rep in fleet.replicas:
        if rep.role is not ReplicaRole.PREFILL or \
                rep.state is ReplicaState.DEAD:
            continue
        s = rep.scheduler
        stranded = [u for u, q in list(s.running.items()) +
                    list(s.suspended.items())
                    if q.state in (RequestState.DECODE,
                                   RequestState.SUSPENDED)]
        if stranded:
            violations.append(
                f"prefill replica {rep.id} still holds decode "
                f"state: {stranded}")
    # trace continuity across the tier link: a handoff must leave one
    # connected DAG spanning both tiers, closure intact
    trace_inv = _trace_gates(reqs, violations)
    _flight_on_violations("disagg", seed, violations)

    digest = _digest(fleet.event_log())
    crashed_tiers = sorted({rep.role.name for rep in fleet.replicas
                            if rep.state is ReplicaState.DEAD})
    result = DisaggChaosResult(
        seed=seed, n_prefill=n_prefill, n_decode=n_decode,
        plan=plan.to_dict(),
        requests=[{
            "uid": r.uid, "state": r.state.name, "error": r.error,
            "reject_reason": r.reject_reason,
            "priority": r.priority, "deadline": r.deadline,
            "tokens": len(r.tokens_out), "replica": r.replica,
            "handoffs": r.n_handoffs,
            "colocated_fallback": r.colocated_fallback,
            "preemptions": r.n_preemptions,
            "restores": r.n_restores,
            "recomputes": r.n_recomputes,
            "migrations": r.n_migrations,
            **_trace_row(r),
        } for r in reqs],
        event_digest=digest,
        fleet_summary=fleet.summary(),
        tier_summary=fleet.tier_summary(),
        handoffs=[m.to_row() for m in handoffs],
        invariants={
            "terminal_states": sorted({r.state.name for r in reqs}),
            "replica_states": {str(rep.id): rep.state.name
                               for rep in fleet.replicas},
            "replica_roles": {str(rep.id): rep.role.name
                              for rep in fleet.replicas},
            "crashed_tiers": crashed_tiers,
            "fault_fired": fault_fired,
            "counters": dict(fleet.counters),
            "migration_balance_ok": fleet.migration_balance_ok,
            "handoff_overlap_ratio":
                round(fleet.handoff_overlap_ratio, 6),
            "prefill_chunks": sum(
                rep.server.metrics.counters["prefill_chunks"]
                for rep in fleet.replicas),
            "trace": trace_inv,
        },
        violations=violations,
        ok=not violations)
    return result


def run_chaos(seed: int = 0, n_requests: int = 32,
              fault_plan: Optional[FaultPlan] = None,
              policy: Optional[ResiliencePolicy] = None,
              num_blocks: int = 12, block_size: int = 8,
              max_lanes: int = 4, max_tracked: int = 8,
              max_context: int = 64, max_new: int = 10,
              rps: float = 60.0,
              restore_chunks_per_step: int = 1) -> ChaosResult:
    """One deterministic chaos run. Everything — trace, faults, retry
    jitter, token streams — is a pure function of ``seed``."""
    from ..inference.config import RaggedInferenceEngineConfig
    from ..serving import (ServerConfig, ServingServer, SimulatedEngine,
                           VirtualClock)

    plan = fault_plan if fault_plan is not None \
        else default_fault_plan(seed)
    policy = policy or ResiliencePolicy(seed=seed)
    engine = SimulatedEngine(RaggedInferenceEngineConfig(
        state_manager={"max_tracked_sequences": max_tracked,
                       "max_ragged_batch_size": 256,
                       "max_ragged_sequence_count": max_lanes,
                       "max_context": max_context},
        kv_cache={"block_size": block_size, "num_blocks": num_blocks},
        hcache={"enable_latents": True}))
    initial_free = engine.state.free_blocks
    server = ServingServer(
        engine, clock=VirtualClock(),
        config=ServerConfig(max_queue_depth=n_requests + 1,
                            kv_demand_fraction=float("inf"),
                            restore_chunks_per_step=
                            restore_chunks_per_step),
        resilience=policy)
    reqs = build_chaos_trace(seed, n_requests, engine.vocab_size,
                             max_new=max_new, rps=rps,
                             prompt_hi=min(24, max_context - max_new - 1))
    with injected(plan):
        server.run_trace(reqs)

    sched = server.scheduler
    violations: List[str] = []
    # 1. terminal-state completeness
    terminal = {"DONE", "REJECTED", "FAILED"}
    for r in reqs:
        if r.state.name not in terminal:
            violations.append(
                f"request {r.uid} ended non-terminal: {r.state.name}")
        if r.uid not in sched.done:
            violations.append(f"request {r.uid} missing from done map")
    if len(sched.done) != len(reqs):
        violations.append(
            f"done map holds {len(sched.done)} entries for "
            f"{len(reqs)} requests")
    # 2. zero KV leaks
    final_free = engine.state.free_blocks
    if final_free != initial_free:
        violations.append(
            f"block leak: {initial_free} free before, {final_free} "
            "after")
    if engine.state.n_tracked_sequences != 0:
        violations.append(
            f"{engine.state.n_tracked_sequences} sequences still "
            "tracked post-trace")
    # 3. restore accounting
    rs = engine.restore_stats
    if rs["restores"] != sched.total_restores:
        violations.append(
            f"restore_stats.restores {rs['restores']} != scheduler "
            f"total_restores {sched.total_restores}")
    if rs["chunks_issued"] > rs["restores"] * engine.N_LAYER:
        violations.append("more chunks issued than lanes could hold")
    # 5. causal-trace continuity + attribution closure
    trace_inv = _trace_gates(reqs, violations)
    _flight_on_violations("chaos", seed, violations)

    events = [list(e) for e in sched.events]
    m = server.metrics.summary()
    result = ChaosResult(
        seed=seed, plan=plan.to_dict(),
        requests=[{
            "uid": r.uid, "state": r.state.name, "error": r.error,
            "reject_reason": r.reject_reason,
            "priority": r.priority,
            "deadline": r.deadline,
            "tokens": len(r.tokens_out),
            "preemptions": r.n_preemptions,
            "restores": r.n_restores,
            "recomputes": r.n_recomputes,
            "restore_failures": r.n_restore_failures,
            **_trace_row(r),
        } for r in reqs],
        events=events,
        event_digest=_digest(events),
        metrics=m,
        fault_summary=server.scheduler.fault_summary(),
        invariants={
            "terminal_states": sorted({r.state.name for r in reqs}),
            "initial_free_blocks": initial_free,
            "final_free_blocks": final_free,
            "tracked_after": engine.state.n_tracked_sequences,
            "restore_stats": dict(rs),
            "breaker_trips": sched.breaker.trips,
            "degraded_steps": sched.ladder.degraded_steps,
            "trace": trace_inv,
        },
        violations=violations,
        ok=not violations)
    return result


# ------------------------------------------------------------------ #
# fabric scope: literal kill-a-process over the process transport
# ------------------------------------------------------------------ #
@dataclass
class FabricChaosResult:
    seed: int
    n_replicas: int
    victim: int
    requests: List[Dict]
    event_digest: str
    fleet_summary: Dict
    wire: Dict
    invariants: Dict
    #: harvested cross-process telemetry: per-worker spans/counters
    #: plus the parent-side harvest accounting. Wall-clock context —
    #: rides OUTSIDE event_digest, like flight-recorder spans.
    telemetry: Dict = field(default_factory=dict)
    ok: bool = False
    violations: List[str] = field(default_factory=list)


def run_fabric_chaos(seed: int = 0, n_replicas: int = 3,
                     n_requests: int = 24,
                     kill_at_step: int = 12,
                     num_blocks: int = 12, block_size: int = 8,
                     max_lanes: int = 4, max_tracked: int = 8,
                     max_context: int = 64, max_new: int = 10,
                     rps: float = 400.0) -> FabricChaosResult:
    """Fabric-scope chaos: the replica crash is a LITERAL process
    kill. The fleet runs on :class:`~..fabric.ProcessTransport` (one
    supervised worker process per replica, migrations crossing real
    sockets); at fleet step ``kill_at_step`` the busiest replica's
    worker is ``SIGKILL``-ed and the fleet discovers the death through
    its liveness pass — from the survivors' view, exactly as an
    operator would. No fault injector runs: the dead process IS the
    fault.

    Invariants (the never-dropped contract, now across real process
    boundaries):

    1. exactly one terminal state per request across the whole fleet —
       the kill may fail individual requests only through the priced
       crash path, never by silently dropping them;
    2. zero KV-block leaks / zero tracked sequences on survivors;
    3. migration accounting balance (evacuations included);
    4. the crash is observed end-to-end: transport ``kills == 1``,
       fleet ``replica_crashes >= 1``, victim DEAD, at least one
       request finished AFTER the kill (the fleet kept serving);
    5. wire accounting recorded beside the virtual clock: measured
       bytes/s present whenever any crossing happened.
    """
    from ..fabric import ProcessTransport
    from ..inference.config import RaggedInferenceEngineConfig
    from ..serving import (FleetConfig, ReplicaState, RouterConfig,
                           ServerConfig, ServingFleet, SimulatedEngine,
                           VirtualClock)

    def make_engine():
        return SimulatedEngine(RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": max_tracked,
                           "max_ragged_batch_size": 256,
                           "max_ragged_sequence_count": max_lanes,
                           "max_context": max_context},
            kv_cache={"block_size": block_size,
                      "num_blocks": num_blocks},
            hcache={"enable_latents": True}))

    transport = ProcessTransport()
    fleet = ServingFleet(
        engines=[make_engine() for _ in range(n_replicas)],
        clock=VirtualClock(),
        config=FleetConfig(
            n_replicas=n_replicas,
            server=ServerConfig(max_queue_depth=n_requests + 1,
                                kv_demand_fraction=float("inf")),
            router=RouterConfig(),
            transport=transport))
    reqs = build_chaos_trace(seed, n_requests,
                             fleet.replicas[0].engine.vocab_size,
                             max_new=max_new, rps=rps,
                             prompt_hi=min(24,
                                           max_context - max_new - 1))
    victim = -1
    done_before_kill = 0
    with transport:
        arrivals = sorted(reqs, key=lambda r: (r.arrival_time, r.uid))
        steps = 0
        while arrivals or fleet.has_work:
            now = fleet.clock.now()
            while arrivals and arrivals[0].arrival_time <= now:
                fleet.submit(request=arrivals.pop(0))
            if not fleet.has_work and arrivals:
                fleet.clock.advance_to(arrivals[0].arrival_time)
                continue
            if victim < 0 and fleet.step_idx >= kill_at_step:
                # deterministic victim: the busiest live replica
                # (ties to the lowest id)
                live = [r for r in fleet.replicas
                        if r.state is ReplicaState.UP]
                victim = max(live, key=lambda r:
                             (len(r.scheduler.running), -r.id)).id
                done_before_kill = sum(
                    1 for r in reqs if r.state.name == "DONE")
                transport.kill(victim)
                # the kill() path harvested the victim best-effort
                # just before the SIGKILL — its last-known spans and
                # counters land in the postmortem bundle (outside the
                # digest, which stays harvest-invariant)
                vt = transport.worker_telemetry.get(victim, {})
                get_flight_recorder().dump(
                    "worker_kill",
                    f"fabric chaos SIGKILL replica {victim}",
                    source="chaos:fabric", step=fleet.step_idx,
                    t=fleet.clock.now(),
                    snapshot={"kind": "fabric", "seed": seed,
                              "victim": victim},
                    spans=list(vt.get("events") or []),
                    attachments={
                        "counters": dict(vt.get("counters") or {}),
                        "metrics": list(vt.get("metrics") or []),
                        "rss_max_bytes": int(
                            vt.get("rss_max_bytes", 0)),
                        "clock_offset_us": float(
                            vt.get("clock_offset_us", 0.0)),
                        "harvests": int(vt.get("harvests", 0)),
                    })
            fleet.step()
            steps += 1
            if steps > 1_000_000:
                raise RuntimeError("fabric chaos livelock:\n"
                                   + fleet.snapshot())

    violations: List[str] = []
    terminal = {"DONE", "REJECTED", "FAILED"}
    # 1. exactly-one-terminal-state, fleet-wide (never dropped)
    for r in reqs:
        if r.state.name not in terminal:
            violations.append(
                f"request {r.uid} ended non-terminal: {r.state.name}")
        holders = sum(1 for rep in fleet.replicas
                      if r.uid in rep.scheduler.done)
        holders += 1 if r.uid in fleet.done else 0
        if holders != 1:
            violations.append(
                f"request {r.uid} terminal in {holders} places "
                "(must be exactly 1)")
    # 2. zero leaks on survivors
    for rep in fleet.replicas:
        if rep.state is ReplicaState.DEAD:
            continue
        if rep.engine.state.free_blocks != rep.initial_free_blocks:
            violations.append(
                f"replica {rep.id}: block leak "
                f"({rep.initial_free_blocks} -> "
                f"{rep.engine.state.free_blocks})")
        if rep.engine.state.n_tracked_sequences != 0:
            violations.append(
                f"replica {rep.id}: "
                f"{rep.engine.state.n_tracked_sequences} sequences "
                "still tracked post-trace")
    # 3. migration balance
    if fleet.in_transit:
        violations.append(
            f"{len(fleet.in_transit)} migrations still in transit")
    c = fleet.counters
    landed = (c["landings"] + c["recompute_landings"] +
              c["expired_in_transit"] + c["cancelled_in_transit"] +
              c["failed_in_transit"])
    if c["evictions"] != landed:
        violations.append(
            f"migration imbalance: {c['evictions']} evictions vs "
            f"{landed} terminal migrations ({dict(c)})")
    # 4. the kill was real and the fleet survived it
    wire = transport.wire_stats()
    # close() ran the shutdown harvest: survivors' final streams plus
    # the victim's pre-kill last-known state are all on the handles
    telemetry = {"harvest": transport.telemetry_stats(),
                 "workers": {int(rid): dict(tel) for rid, tel in
                             transport.worker_telemetry.items()}}
    if wire["kills"] != 1:
        violations.append(f"expected exactly 1 kill, saw "
                          f"{wire['kills']}")
    if c["replica_crashes"] < 1:
        violations.append("liveness pass never observed the kill as "
                          "a replica crash")
    if victim < 0 or fleet.replicas[victim].state \
            is not ReplicaState.DEAD:
        violations.append(f"victim replica {victim} is not DEAD")
    done_after = sum(1 for r in reqs if r.state.name == "DONE")
    if done_after <= done_before_kill:
        violations.append(
            "no request finished after the kill — the fleet did not "
            "keep serving")
    if wire["bootstrap_mismatches"]:
        violations.append(
            f"{wire['bootstrap_mismatches']} bootstrap digest "
            "mismatches (serialize() snapshot is not a faithful "
            "process-side bootstrap)")
    # 5. measured wire recorded whenever bytes crossed
    if wire["deliveries"] > wire["local_fallbacks"] and \
            wire["measured_wire_bytes_per_s"] <= 0:
        violations.append("crossings happened but no measured wire "
                          "throughput was recorded")
    trace_inv = _trace_gates(reqs, violations)
    _flight_on_violations("fabric", seed, violations)

    return FabricChaosResult(
        seed=seed, n_replicas=n_replicas, victim=victim,
        requests=[{
            "uid": r.uid, "state": r.state.name, "error": r.error,
            "tokens": len(r.tokens_out), "replica": r.replica,
            "migrations": r.n_migrations,
            "recomputes": r.n_recomputes,
            **_trace_row(r),
        } for r in reqs],
        event_digest=_digest(fleet.event_log()),
        fleet_summary=fleet.summary(),
        wire=wire,
        telemetry=telemetry,
        invariants={
            "terminal_states": sorted({r.state.name for r in reqs}),
            "replica_states": {str(rep.id): rep.state.name
                               for rep in fleet.replicas},
            "counters": dict(fleet.counters),
            "done_before_kill": done_before_kill,
            "done_after": done_after,
            "trace": trace_inv,
        },
        violations=violations,
        ok=not violations)


# ----------------------------------------------------------------- #
# autoscale chaos: scale events as a first-class failure domain
# ----------------------------------------------------------------- #
def default_autoscale_fault_plan(seed: int = 0) -> FaultPlan:
    """One fault per scale-event failure domain: the FIRST scale-up
    bootstrap aborts (``scale.bootstrap``), the FIRST retirement's
    drain victim crashes mid-drain (``scale.drain``), and the first
    pre-warm broadcast is dropped (``scale.prewarm``, non-fatal). The
    control loop must recover from all three with every request still
    reaching exactly one terminal state."""
    return FaultPlan(seed=seed, rules=[
        FaultRule("scale.bootstrap", at_hits=(1,), max_faults=1),
        FaultRule("scale.drain", at_hits=(1,), max_faults=1),
        FaultRule("scale.prewarm", at_hits=(1,), max_faults=1),
    ])


@dataclass
class AutoscaleChaosResult:
    seed: int
    plan: Dict
    requests: List[Dict]
    event_digest: str
    fleet_summary: Dict
    autoscale: Dict
    invariants: Dict
    ok: bool = False
    violations: List[str] = field(default_factory=list)


def run_autoscale_chaos(seed: int = 0, n_requests: int = 360,
                        horizon_s: float = 10.0,
                        fault_plan: Optional[FaultPlan] = None,
                        start_replicas: int = 2,
                        max_replicas: int = 4) -> AutoscaleChaosResult:
    """One deterministic autoscaled chaos run: the bursty multi-tenant
    trace drives the control loop over a virtual-clock fleet while
    every scale-event failure domain fires from the plan — a scale-up
    killed mid-bootstrap (clean abort back to the prior fleet shape),
    a replica crashed mid-drain-retirement (degrades into the crash
    evacuation path), and a faulted pre-warm broadcast (the new
    replica joins cold).

    Invariants (the scale-event robustness contract):

    1. exactly-one-terminal-state per request at fleet scope;
    2. zero KV/tracked leaks on every surviving replica — including
       STOPPED (retired) ones, whose pools must be intact;
    3. fleet-scope migration balance including retired replicas'
       evacuations and all pre-warm broadcasts;
    4. the flap bound: direction reversals never exceed the
       configured ``max_flaps``;
    5. every injected scale fault left its mark (abort counted,
       retirement crash event, pre-warm fault event);
    6. determinism — the caller runs twice and compares
       ``event_digest`` byte-for-byte;
    7. causal-trace continuity (connected DAGs, closure) for every
       request, scale events included.
    """
    from ..inference.config import RaggedInferenceEngineConfig
    from ..serving import (AutoscaleConfig, Autoscaler, FleetConfig,
                           PrefixReuseConfig, ReplicaState,
                           ServerConfig, ServingFleet,
                           SimulatedEngine, VirtualClock,
                           build_autoscale_trace)
    from ..serving.spec import SLOModeConfig

    plan = fault_plan if fault_plan is not None \
        else default_autoscale_fault_plan(seed)

    def make_engine():
        return SimulatedEngine(RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8,
                           "max_ragged_batch_size": 256,
                           "max_ragged_sequence_count": 4,
                           "max_context": 64},
            kv_cache={"block_size": 8, "num_blocks": 12},
            hcache={"enable_latents": True}))

    fleet = ServingFleet(
        engine_factory=make_engine,
        clock=VirtualClock(),
        config=FleetConfig(
            n_replicas=start_replicas,
            server=ServerConfig(max_queue_depth=n_requests + 1,
                                kv_demand_fraction=float("inf"),
                                slo_mode=SLOModeConfig()),
            prefix=PrefixReuseConfig(broadcast=True,
                                     min_adopt_tokens=4)))
    asc_cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=max_replicas,
        hot_steps=2, calm_steps=30, cooldown_steps=20,
        flap_window_steps=40, max_flaps=2)
    asc = Autoscaler(fleet, asc_cfg)
    reqs = build_autoscale_trace(seed=seed, n_requests=n_requests,
                                 horizon_s=horizon_s,
                                 new_tokens=(8, 14))
    with injected(plan) as inj:
        asc.run(reqs)
        fault_fired = dict(inj.fired)

    violations: List[str] = []
    # 1. exactly-one-terminal-state per request, fleet scope
    terminal = {"DONE", "REJECTED", "FAILED"}
    for r in reqs:
        if r.state.name not in terminal:
            violations.append(
                f"request {r.uid} ended non-terminal: {r.state.name}")
        holders = sum(1 for rep in fleet.replicas
                      if r.uid in rep.scheduler.done)
        holders += 1 if r.uid in fleet.done else 0
        if holders != 1:
            violations.append(
                f"request {r.uid} terminal in {holders} places "
                "(must be exactly 1)")
    # 2. zero leaks on every surviving replica (STOPPED included:
    # a retired pool must be intact)
    for rep in fleet.replicas:
        if rep.state is ReplicaState.DEAD:
            continue
        free = rep.engine.state.free_blocks
        if free != rep.initial_free_blocks:
            violations.append(
                f"replica {rep.id} ({rep.state.name}): block leak "
                f"({rep.initial_free_blocks} before, {free} after)")
        tracked = rep.engine.state.n_tracked_sequences
        if tracked != 0:
            violations.append(
                f"replica {rep.id}: {tracked} sequences still "
                "tracked post-trace")
    # 3. fleet-scope migration balance, retired replicas included
    if fleet.in_transit:
        violations.append(
            f"{len(fleet.in_transit)} migrations still in transit")
    if not fleet.migration_balance_ok:
        violations.append(
            f"migration imbalance: {dict(fleet.counters)}")
    # 4. flap bound
    if asc.flaps > asc_cfg.max_flaps:
        violations.append(
            f"flap bound violated: {asc.flaps} > "
            f"{asc_cfg.max_flaps}")
    # 5. every injected scale fault left its mark
    c = fleet.counters
    if fault_fired.get("scale.bootstrap", 0) and \
            c["scale_up_aborts"] < 1:
        violations.append("scale.bootstrap fired but no scale-up "
                          "abort was counted")
    event_names = [e[1] for e in fleet.events]
    if fault_fired.get("scale.drain", 0) and \
            "retire_crash" not in event_names:
        violations.append("scale.drain fired but no retire_crash "
                          "event was logged")
    if fault_fired.get("scale.prewarm", 0) and \
            "prewarm_fault" not in event_names:
        violations.append("scale.prewarm fired but no prewarm_fault "
                          "event was logged")
    if c["scale_ups"] < 1:
        violations.append("no successful scale-up happened under "
                          "chaos")
    if c["retires_completed"] < 1:
        violations.append("no drain-retirement completed under "
                          "chaos")
    # 7. causal-trace continuity across scale events
    trace_inv = _trace_gates(reqs, violations)
    _flight_on_violations("autoscale", seed, violations)

    return AutoscaleChaosResult(
        seed=seed, plan=plan.to_dict(),
        requests=[{
            "uid": r.uid, "state": r.state.name, "error": r.error,
            "tokens": len(r.tokens_out), "replica": r.replica,
            "migrations": r.n_migrations,
            "recomputes": r.n_recomputes,
            **_trace_row(r),
        } for r in reqs],
        event_digest=_digest(fleet.event_log()),
        fleet_summary=fleet.summary(),
        autoscale=asc.summary(),
        invariants={
            "terminal_states": sorted({r.state.name for r in reqs}),
            "replica_states": {str(rep.id): rep.state.name
                               for rep in fleet.replicas},
            "fault_fired": fault_fired,
            "counters": dict(fleet.counters),
            "autoscale_counters": dict(asc.counters),
            "flaps": asc.flaps,
            "flap_bound": asc_cfg.max_flaps,
            "migration_balance_ok": fleet.migration_balance_ok,
            "trace": trace_inv,
        },
        violations=violations,
        ok=not violations)
