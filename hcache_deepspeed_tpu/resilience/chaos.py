"""Chaos harness: seeded fault plans over the virtual-clock simulation.

One call = one fully deterministic serving run with faults injected at
every named site, returning the replayable event log plus an invariant
report. The invariants are the robustness contract this subsystem
ships:

1. **terminal-state completeness** — every submitted request ends in
   exactly one terminal state (DONE / REJECTED / FAILED), exactly once
   in the scheduler's ``done`` map;
2. **zero KV leaks** — the block allocator returns to its pre-trace
   free count (quarantines, lane aborts and deadline kills all freed
   their blocks);
3. **restore accounting** — engine ``restore_stats`` agree with the
   scheduler's counters;
4. **determinism** — two runs of the same seed produce byte-identical
   event logs (compare ``ChaosResult.event_digest``).

The harness is pure CPU (SimulatedEngine + VirtualClock), so all of
this is tier-1-testable; ``inference/benchmark.py``'s ``serve_loop
--chaos`` mode wraps it into the CHAOS_SERVE.jsonl artifact.
"""

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from .faults import FaultPlan, FaultRule, injected
from .policy import ResiliencePolicy


def default_fault_plan(seed: int = 0) -> FaultPlan:
    """Faults at every named serving-path site. ``max_faults`` bounds
    every rule so the storm eventually heals — retries and breaker
    probes can succeed and the trace always drains.

    The ``restore.ship`` rule fires a deterministic 9-hit burst: with
    the default retry budget (3 attempts) that is exactly three
    consecutive retry-exhausted lane aborts — enough to trip the
    breaker (threshold 3) and force the crossover recompute re-entry
    path, which the chaos acceptance gate asserts on.
    """
    return FaultPlan(seed=seed, rules=[
        FaultRule("engine.decode", probability=0.02, max_faults=3),
        FaultRule("engine.prefill", probability=0.03, max_faults=3),
        FaultRule("restore.ship", at_hits=tuple(range(1, 10)),
                  probability=0.05, max_faults=12),
        FaultRule("restore.replay", at_hits=(2,), probability=0.08,
                  max_faults=3),
        FaultRule("alloc.blocks", at_hits=(7,), probability=0.01,
                  max_faults=2),
        FaultRule("host.latents", at_hits=(11,), probability=0.005,
                  max_faults=2),
    ])


@dataclass
class ChaosResult:
    seed: int
    plan: Dict
    requests: List[Dict]
    events: List
    event_digest: str
    metrics: Dict
    fault_summary: Dict
    invariants: Dict
    ok: bool = False
    violations: List[str] = field(default_factory=list)


def _digest(events) -> str:
    payload = json.dumps(events, sort_keys=True,
                         separators=(",", ":")).encode()
    return hashlib.sha256(payload).hexdigest()


def build_chaos_trace(seed: int, n_requests: int, vocab: int,
                      prompt_lo: int = 8, prompt_hi: int = 24,
                      max_new: int = 8, rps: float = 40.0,
                      deadline_frac: float = 0.25,
                      deadline_slack_s: float = 0.25):
    """Seeded request trace: mixed priorities, a deadline-carrying
    minority, Poisson arrivals. Returns a list of Requests."""
    from ..serving import Request
    rng = np.random.default_rng([seed, 0x7A0])
    arrive = np.cumsum(rng.exponential(1.0 / rps, n_requests))
    reqs = []
    for i in range(n_requests):
        plen = int(rng.integers(prompt_lo, prompt_hi + 1))
        prompt = [int(t) for t in rng.integers(0, vocab, (plen,))]
        deadline = None
        if rng.random() < deadline_frac:
            deadline = float(arrive[i]) + deadline_slack_s
        reqs.append(Request(
            uid=i, prompt=prompt, max_new_tokens=max_new,
            arrival_time=float(arrive[i]),
            priority=int(rng.integers(0, 3)),
            deadline=deadline))
    return reqs


def default_fleet_fault_plan(seed: int = 0) -> FaultPlan:
    """Replica-level failure domains on top of a thinned engine-level
    storm. Sites fire once per live replica per fleet step (hit
    counters are per-site, fleet-global), so ``at_hits`` pins faults
    to deterministic (step, replica) coordinates for a fixed fleet
    size. One crash, a hang and a partition per run by default —
    enough to exercise evacuation, breaker trip/heal and re-routing
    while the trace still drains."""
    return FaultPlan(seed=seed, rules=[
        FaultRule("replica.crash", at_hits=(90,), max_faults=1),
        FaultRule("replica.hang", at_hits=(40,), probability=0.002,
                  max_faults=2),
        FaultRule("replica.net_partition", at_hits=(150,),
                  probability=0.002, max_faults=2),
        FaultRule("engine.decode", probability=0.01, max_faults=2),
        FaultRule("restore.ship", probability=0.02, max_faults=4),
    ])


@dataclass
class FleetChaosResult:
    seed: int
    n_replicas: int
    plan: Dict
    requests: List[Dict]
    event_digest: str
    fleet_summary: Dict
    migrations: List[Dict]
    invariants: Dict
    ok: bool = False
    violations: List[str] = field(default_factory=list)


def run_fleet_chaos(seed: int = 0, n_replicas: int = 3,
                    n_requests: int = 48,
                    fault_plan: Optional[FaultPlan] = None,
                    policy: Optional[ResiliencePolicy] = None,
                    num_blocks: int = 12, block_size: int = 8,
                    max_lanes: int = 4, max_tracked: int = 8,
                    max_context: int = 64, max_new: int = 10,
                    rps: float = 400.0,
                    drain_replica: Optional[int] = None,
                    drain_at_step: int = 60) -> FleetChaosResult:
    """One deterministic fleet chaos run: a seeded multi-tenant trace
    spread over ``n_replicas`` virtual-clock ``SimulatedEngine``
    replicas, with replica crash/hang/partition faults (plus a thinned
    engine-level storm) injected from the plan. Optionally starts a
    graceful drain of ``drain_replica`` once ``drain_at_step`` fleet
    steps have run.

    Invariants checked (the fleet robustness contract):

    1. exactly-one-terminal-state per request *across the whole
       fleet* — terminal everywhere-counted exactly once (replica done
       maps + the fleet's own terminal map);
    2. zero KV-block leaks and zero tracked sequences on every
       *surviving* (non-DEAD) replica;
    3. migration accounting balance — every eviction reached exactly
       one terminal mode (landed / recompute-landed / expired /
       cancelled / failed), nothing left in transit;
    4. per-replica restore accounting (engine restore_stats vs
       scheduler counters) on surviving replicas;
    5. determinism — the digest over the fleet event log + every
       replica's scheduler event log is a pure function of the seed
       (the caller runs twice and compares digests).
    """
    from ..inference.config import RaggedInferenceEngineConfig
    from ..serving import (FleetConfig, ReplicaState, RouterConfig,
                           ServerConfig, ServingFleet, SimulatedEngine,
                           VirtualClock)

    plan = fault_plan if fault_plan is not None \
        else default_fleet_fault_plan(seed)
    policy = policy or ResiliencePolicy(seed=seed)

    def make_engine():
        return SimulatedEngine(RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": max_tracked,
                           "max_ragged_batch_size": 256,
                           "max_ragged_sequence_count": max_lanes,
                           "max_context": max_context},
            kv_cache={"block_size": block_size,
                      "num_blocks": num_blocks},
            hcache={"enable_latents": True}))

    fleet = ServingFleet(
        engines=[make_engine() for _ in range(n_replicas)],
        clock=VirtualClock(),
        config=FleetConfig(
            n_replicas=n_replicas,
            server=ServerConfig(max_queue_depth=n_requests + 1,
                                kv_demand_fraction=float("inf")),
            router=RouterConfig()),
        resilience=policy)
    reqs = build_chaos_trace(seed, n_requests,
                             fleet.replicas[0].engine.vocab_size,
                             max_new=max_new, rps=rps,
                             prompt_hi=min(24,
                                           max_context - max_new - 1))
    with injected(plan) as inj:
        if drain_replica is None:
            fleet.run_trace(reqs)
        else:
            # drive arrivals manually so the drain starts mid-trace
            arrivals = sorted(reqs, key=lambda r: (r.arrival_time,
                                                   r.uid))
            drained = False
            steps = 0
            while arrivals or fleet.has_work:
                now = fleet.clock.now()
                while arrivals and arrivals[0].arrival_time <= now:
                    fleet.submit(request=arrivals.pop(0))
                if not fleet.has_work and arrivals:
                    fleet.clock.advance_to(arrivals[0].arrival_time)
                    continue
                if not drained and fleet.step_idx >= drain_at_step \
                        and fleet.replicas[drain_replica].state \
                        is ReplicaState.UP:
                    fleet.drain(drain_replica)
                    drained = True
                fleet.step()
                steps += 1
                if steps > 1_000_000:
                    raise RuntimeError("fleet chaos livelock:\n"
                                       + fleet.snapshot())
        fault_fired = dict(inj.fired)

    violations: List[str] = []
    # 1. exactly-one-terminal-state across the whole fleet
    terminal = {"DONE", "REJECTED", "FAILED"}
    for r in reqs:
        if r.state.name not in terminal:
            violations.append(
                f"request {r.uid} ended non-terminal: {r.state.name}")
        holders = sum(1 for rep in fleet.replicas
                      if r.uid in rep.scheduler.done)
        holders += 1 if r.uid in fleet.done else 0
        if holders != 1:
            violations.append(
                f"request {r.uid} terminal in {holders} places "
                "(must be exactly 1)")
    # 2. zero leaks on every surviving replica
    for rep in fleet.replicas:
        if rep.state is ReplicaState.DEAD:
            continue
        free = rep.engine.state.free_blocks
        if free != rep.initial_free_blocks:
            violations.append(
                f"replica {rep.id}: block leak "
                f"({rep.initial_free_blocks} free before, {free} "
                "after)")
        tracked = rep.engine.state.n_tracked_sequences
        if tracked != 0:
            violations.append(
                f"replica {rep.id}: {tracked} sequences still "
                "tracked post-trace")
    # 3. migration accounting balance
    if fleet.in_transit:
        violations.append(
            f"{len(fleet.in_transit)} migrations still in transit "
            "post-trace")
    c = fleet.counters
    landed = (c["landings"] + c["recompute_landings"] +
              c["expired_in_transit"] + c["cancelled_in_transit"] +
              c["failed_in_transit"])
    if c["evictions"] != landed:
        violations.append(
            f"migration imbalance: {c['evictions']} evictions vs "
            f"{landed} terminal migrations ({dict(c)})")
    # 4. per-replica restore accounting (surviving replicas)
    for rep in fleet.replicas:
        if rep.state is ReplicaState.DEAD:
            continue
        rs = rep.engine.restore_stats
        sched = rep.scheduler
        if rs["restores"] != sched.total_restores:
            violations.append(
                f"replica {rep.id}: restore_stats.restores "
                f"{rs['restores']} != scheduler total_restores "
                f"{sched.total_restores}")

    digest = _digest(fleet.event_log())
    result = FleetChaosResult(
        seed=seed, n_replicas=n_replicas, plan=plan.to_dict(),
        requests=[{
            "uid": r.uid, "state": r.state.name, "error": r.error,
            "reject_reason": r.reject_reason,
            "priority": r.priority, "deadline": r.deadline,
            "tokens": len(r.tokens_out),
            "replica": r.replica,
            "preemptions": r.n_preemptions,
            "restores": r.n_restores,
            "recomputes": r.n_recomputes,
            "migrations": r.n_migrations,
        } for r in reqs],
        event_digest=digest,
        fleet_summary=fleet.summary(),
        migrations=[m.to_row() for m in fleet.migrations],
        invariants={
            "terminal_states": sorted({r.state.name for r in reqs}),
            "replica_states": {str(rep.id): rep.state.name
                               for rep in fleet.replicas},
            "fault_fired": fault_fired,
            "counters": dict(fleet.counters),
            "migration_balance_ok": fleet.migration_balance_ok,
            "migration_overlap_ratio":
                round(fleet.migration_overlap_ratio, 6),
        },
        violations=violations,
        ok=not violations)
    return result


def run_chaos(seed: int = 0, n_requests: int = 32,
              fault_plan: Optional[FaultPlan] = None,
              policy: Optional[ResiliencePolicy] = None,
              num_blocks: int = 12, block_size: int = 8,
              max_lanes: int = 4, max_tracked: int = 8,
              max_context: int = 64, max_new: int = 10,
              rps: float = 60.0,
              restore_chunks_per_step: int = 1) -> ChaosResult:
    """One deterministic chaos run. Everything — trace, faults, retry
    jitter, token streams — is a pure function of ``seed``."""
    from ..inference.config import RaggedInferenceEngineConfig
    from ..serving import (ServerConfig, ServingServer, SimulatedEngine,
                           VirtualClock)

    plan = fault_plan if fault_plan is not None \
        else default_fault_plan(seed)
    policy = policy or ResiliencePolicy(seed=seed)
    engine = SimulatedEngine(RaggedInferenceEngineConfig(
        state_manager={"max_tracked_sequences": max_tracked,
                       "max_ragged_batch_size": 256,
                       "max_ragged_sequence_count": max_lanes,
                       "max_context": max_context},
        kv_cache={"block_size": block_size, "num_blocks": num_blocks},
        hcache={"enable_latents": True}))
    initial_free = engine.state.free_blocks
    server = ServingServer(
        engine, clock=VirtualClock(),
        config=ServerConfig(max_queue_depth=n_requests + 1,
                            kv_demand_fraction=float("inf"),
                            restore_chunks_per_step=
                            restore_chunks_per_step),
        resilience=policy)
    reqs = build_chaos_trace(seed, n_requests, engine.vocab_size,
                             max_new=max_new, rps=rps,
                             prompt_hi=min(24, max_context - max_new - 1))
    with injected(plan):
        server.run_trace(reqs)

    sched = server.scheduler
    violations: List[str] = []
    # 1. terminal-state completeness
    terminal = {"DONE", "REJECTED", "FAILED"}
    for r in reqs:
        if r.state.name not in terminal:
            violations.append(
                f"request {r.uid} ended non-terminal: {r.state.name}")
        if r.uid not in sched.done:
            violations.append(f"request {r.uid} missing from done map")
    if len(sched.done) != len(reqs):
        violations.append(
            f"done map holds {len(sched.done)} entries for "
            f"{len(reqs)} requests")
    # 2. zero KV leaks
    final_free = engine.state.free_blocks
    if final_free != initial_free:
        violations.append(
            f"block leak: {initial_free} free before, {final_free} "
            "after")
    if engine.state.n_tracked_sequences != 0:
        violations.append(
            f"{engine.state.n_tracked_sequences} sequences still "
            "tracked post-trace")
    # 3. restore accounting
    rs = engine.restore_stats
    if rs["restores"] != sched.total_restores:
        violations.append(
            f"restore_stats.restores {rs['restores']} != scheduler "
            f"total_restores {sched.total_restores}")
    if rs["chunks_issued"] > rs["restores"] * engine.N_LAYER:
        violations.append("more chunks issued than lanes could hold")

    events = [list(e) for e in sched.events]
    m = server.metrics.summary()
    result = ChaosResult(
        seed=seed, plan=plan.to_dict(),
        requests=[{
            "uid": r.uid, "state": r.state.name, "error": r.error,
            "reject_reason": r.reject_reason,
            "priority": r.priority,
            "deadline": r.deadline,
            "tokens": len(r.tokens_out),
            "preemptions": r.n_preemptions,
            "restores": r.n_restores,
            "recomputes": r.n_recomputes,
            "restore_failures": r.n_restore_failures,
        } for r in reqs],
        events=events,
        event_digest=_digest(events),
        metrics=m,
        fault_summary=server.scheduler.fault_summary(),
        invariants={
            "terminal_states": sorted({r.state.name for r in reqs}),
            "initial_free_blocks": initial_free,
            "final_free_blocks": final_free,
            "tracked_after": engine.state.n_tracked_sequences,
            "restore_stats": dict(rs),
            "breaker_trips": sched.breaker.trips,
            "degraded_steps": sched.ladder.degraded_steps,
        },
        violations=violations,
        ok=not violations)
    return result
