"""Resilience layer: deterministic fault injection + recovery policies.

No reference analog in DeepSpeed — its failure story is elasticity
(restart the job world). A serving stack needs per-request failure
semantics instead: inject any failure deterministically
(``faults``), retry/bound/trip around it (``retry``), degrade
gracefully under a storm (``degradation``), and prove the whole thing
with seeded chaos runs over the virtual-clock simulation (``chaos``) —
at engine scope (``run_chaos``) and at fleet scope
(``run_fleet_chaos``: replica crash/hang/partition failure domains
over the N-replica serving fleet, with migration accounting and
fleet-wide terminal-state invariants).
``policy.ResiliencePolicy`` is the knob bundle the serving scheduler
consumes; the fault-site hooks live in the engine, restore pipeline,
block allocator, host latent store and checkpoint engine.
"""

from .degradation import (DegradationLadder,  # noqa: F401
                          DegradationLevel, LadderConfig)
from .faults import (SITES, FaultInjector, FaultPlan,  # noqa: F401
                     FaultRule, InjectedFault, get_injector, injected,
                     install, uninstall)
from .policy import ResiliencePolicy  # noqa: F401
from .retry import (BreakerState, CircuitBreaker,  # noqa: F401
                    RetryPolicy, Watchdog, call_with_retry)

from .chaos import (AutoscaleChaosResult,  # noqa: F401
                    ChaosResult, DisaggChaosResult,
                    FabricChaosResult, FleetChaosResult,
                    build_chaos_trace, default_autoscale_fault_plan,
                    default_fault_plan,
                    default_disagg_fault_plan,
                    default_fleet_fault_plan, run_autoscale_chaos,
                    run_chaos, run_disagg_chaos, run_fabric_chaos,
                    run_fleet_chaos)
