"""TPU platform implementation.

The TPU analog of the reference's ``accelerator/cuda_accelerator.py``: it maps
the small Platform surface onto JAX/XLA. Collectives ride ICI within a slice
and DCN across slices — both are reached through ``jax.lax`` collectives over
mesh axes, so ``communication_backend_name`` names the transport rather than a
library (the reference returns 'nccl' and routes through torch.distributed).
"""

import contextlib

import jax

from .abstract import Platform

# Peak dense-matmul bf16 TFLOP/s per *jax device*, by TPU generation
# (public specs). v2/v3 expose one TensorCore per device (half a chip);
# v4 onward expose the whole chip (megacore / single core), so the
# per-device peak is the full chip figure: v4 275, v5e 197, v5p 459,
# v6e 918.
_PEAK_BF16_TFLOPS = {
    "v2": 22.5,
    "v3": 61.5,
    "v4": 275.0,
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0,
    "v6e": 918.0,
}


class TPUPlatform(Platform):
    name = "tpu"

    def device_count(self):
        return jax.device_count()

    def local_device_count(self):
        return jax.local_device_count()

    def process_count(self):
        return jax.process_count()

    def process_index(self):
        return jax.process_index()

    def communication_backend_name(self):
        return "xla-ici-dcn"

    def supports_host_offload(self):
        return True

    def supports_pallas(self):
        return True

    def device_kind(self):
        devs = jax.devices()
        return devs[0].device_kind if devs else "unknown"

    def peak_tflops(self, dtype="bfloat16"):
        kind = self.device_kind().lower()
        for key, tflops in _PEAK_BF16_TFLOPS.items():
            if key in kind:
                if dtype in ("float32", "fp32"):
                    return tflops / 2
                return tflops
        return 0.0

    def memory_stats(self, device=None):
        device = device or jax.local_devices()[0]
        stats = device.memory_stats() or {}
        return {
            "bytes_in_use": stats.get("bytes_in_use", 0),
            "bytes_limit": stats.get("bytes_limit", 0),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use", 0),
        }

    def profiler_start(self, log_dir):
        jax.profiler.start_trace(log_dir)

    def profiler_stop(self):
        jax.profiler.stop_trace()

    def annotate(self, name):
        return jax.profiler.TraceAnnotation(name)


class CPUPlatform(TPUPlatform):
    """Host-only platform (CI, unit tests on a forced multi-device CPU mesh).

    Reference analog: ``accelerator/cpu_accelerator.py`` — used so the whole
    runtime can execute without accelerator hardware.
    """
    name = "cpu"

    def communication_backend_name(self):
        return "xla-host"

    def supports_host_offload(self):
        return False  # arrays already live in host memory

    def supports_pallas(self):
        return False  # interpret mode only

    def peak_tflops(self, dtype="bfloat16"):
        return 0.0

    def memory_stats(self, device=None):
        try:
            import psutil
            vm = psutil.virtual_memory()
            return {
                "bytes_in_use": vm.used,
                "bytes_limit": vm.total,
                "peak_bytes_in_use": 0,
            }
        except Exception:
            return {"bytes_in_use": 0, "bytes_limit": 0, "peak_bytes_in_use": 0}

    def profiler_start(self, log_dir):
        with contextlib.suppress(Exception):
            jax.profiler.start_trace(log_dir)

    def profiler_stop(self):
        with contextlib.suppress(Exception):
            jax.profiler.stop_trace()
