"""Platform detection and singleton access.

Reference analog: ``accelerator/real_accelerator.py:51`` ``get_accelerator()``
— env override first (there ``DS_ACCELERATOR``, here ``HDS_PLATFORM``), then
auto-detection. Detection here simply asks JAX for its default backend, since
the PJRT plugin system already did the probing.
"""

import os

from .abstract import Platform
from .tpu import CPUPlatform, TPUPlatform

_PLATFORMS = {
    "tpu": TPUPlatform,
    "cpu": CPUPlatform,
}

_platform = None


def get_platform() -> Platform:
    global _platform
    if _platform is None:
        override = os.environ.get("HDS_PLATFORM")
        if override:
            if override not in _PLATFORMS:
                raise ValueError(
                    f"HDS_PLATFORM={override!r} not in {sorted(_PLATFORMS)}")
            _platform = _PLATFORMS[override]()
        else:
            import jax
            backend = jax.default_backend()
            # Any non-CPU PJRT backend (tpu, or a tunnelled TPU plugin) gets
            # the TPU platform; CPU gets the host platform.
            _platform = CPUPlatform() if backend == "cpu" else TPUPlatform()
    return _platform


def set_platform(name_or_platform):
    """Force the platform (tests)."""
    global _platform
    if isinstance(name_or_platform, Platform):
        _platform = name_or_platform
    else:
        _platform = _PLATFORMS[name_or_platform]()
    return _platform


__all__ = ["Platform", "TPUPlatform", "CPUPlatform", "get_platform", "set_platform"]
