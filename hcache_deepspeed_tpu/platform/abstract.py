"""Platform abstraction.

TPU-native re-design of the reference's accelerator abstraction
(``accelerator/abstract_accelerator.py:10`` ``DeepSpeedAccelerator``, ~70
abstract methods). JAX already abstracts devices, streams and RNG, so the
surface here is deliberately small: we keep only what expresses *capability*
differences between platforms (memory stats, host-offload support, collective
transport, profiler, op-registry routing). Everything stream/event/graph
shaped in the reference dissolves into XLA.
"""

from abc import ABC, abstractmethod


class Platform(ABC):
    """A hardware platform seen by the framework."""

    #: short name, e.g. "tpu", "cpu"
    name: str = None

    # ------------------------------------------------------------------ #
    # Device topology
    # ------------------------------------------------------------------ #
    @abstractmethod
    def device_count(self):
        """Total addressable devices across all hosts."""

    @abstractmethod
    def local_device_count(self):
        """Devices attached to this host."""

    @abstractmethod
    def process_count(self):
        """Number of controller processes (hosts)."""

    @abstractmethod
    def process_index(self):
        """This controller's index."""

    def is_available(self):
        return self.device_count() > 0

    # ------------------------------------------------------------------ #
    # Capability probes (reference: communication_backend_name(),
    # supports_* predicates on DeepSpeedAccelerator)
    # ------------------------------------------------------------------ #
    @abstractmethod
    def communication_backend_name(self):
        """Transport used for collectives ('xla-ici-dcn', 'xla-host', ...)."""

    def supports_bf16_matmul(self):
        return True

    def supports_host_offload(self):
        """Can arrays live in host memory and be streamed to device?"""
        return False

    def supports_pallas(self):
        """Can Pallas kernels compile natively (not interpret mode)?"""
        return False

    # ------------------------------------------------------------------ #
    # Memory (reference: memory_stats / see_memory_usage surface)
    # ------------------------------------------------------------------ #
    @abstractmethod
    def memory_stats(self, device=None):
        """dict with at least bytes_in_use / bytes_limit when known."""

    def total_memory(self, device=None):
        return self.memory_stats(device).get("bytes_limit", 0)

    def available_memory(self, device=None):
        stats = self.memory_stats(device)
        return stats.get("bytes_limit", 0) - stats.get("bytes_in_use", 0)

    # ------------------------------------------------------------------ #
    # Hardware peak numbers (used by the flops profiler / MFU reporting)
    # ------------------------------------------------------------------ #
    def peak_tflops(self, dtype="bfloat16"):
        """Peak matmul TFLOP/s per device for ``dtype``; 0 if unknown."""
        return 0.0

    # ------------------------------------------------------------------ #
    # Profiler (reference: range_push/pop NVTX + torch profiler hooks)
    # ------------------------------------------------------------------ #
    @abstractmethod
    def profiler_start(self, log_dir):
        ...

    @abstractmethod
    def profiler_stop(self):
        ...

    def annotate(self, name):
        """Context manager adding a named range to profiler traces."""
        import contextlib
        return contextlib.nullcontext()

    # ------------------------------------------------------------------ #
    # Synchronisation
    # ------------------------------------------------------------------ #
    def synchronize(self, tree=None):
        """Block until async dispatch for ``tree`` (or all work) completes."""
        import jax
        if tree is not None:
            jax.block_until_ready(tree)
        else:
            import jax.numpy as jnp
            jnp.zeros(()).block_until_ready()
