"""``python -m hcache_deepspeed_tpu.perf`` — the observatory CLI.

Subcommands:

* ``index [--out PATH] [--git] [--root DIR]`` — rebuild the committed
  ``PERF_TRAJECTORY.json`` from the root artifacts (``--git`` adds
  producer-PR attribution; slower, used for the committed index).
* ``check --against PERF_TRAJECTORY.json [FILE...]`` — regression
  gate: parse each FILE (default: every indexable root artifact) and
  fail (exit 5) if any headline metric regressed beyond tolerance.
  ``--self-test`` instead proves the gate trips on synthetic
  regressions (tier-1 runs this; exit 6 on failure).
* ``lint [--root DIR]`` — fail (exit 7) if any source file writes an
  artifact-style filename the registry has no schema for.
* ``freshness [--max-age-days N]`` — print the wedged-relay gauge
  (exit 0 always; the relay being down is not a code regression).
"""

import argparse
import json
import os
import sys


def _cmd_index(args) -> int:
    from .registry import write_index
    index = write_index(path=args.out, root=args.root,
                        with_git=args.git, now=args.now)
    n_pts = sum(len(v) for v in index["series"].values())
    print(f"indexed {len(index['artifacts'])} artifacts -> "
          f"{len(index['series'])} series / {n_pts} points; "
          f"unindexed={index['unindexed']}")
    fresh = index["freshness"]
    print(f"freshness: last chip measurement "
          f"{fresh['last_chip_measurement_utc']} "
          f"({fresh['staleness_days']} days old, "
          f"stale={fresh['stale']})")
    return 0


def _cmd_check(args) -> int:
    from .check import (check_artifact, check_headline,
                        freshness_alarm, regressions, self_test)
    from .registry import build_index, load_index, repo_root
    if args.self_test:
        return 0 if self_test(verbose=True) else 6
    root = args.root or repo_root()
    baseline = load_index(path=args.against, root=root)
    failed = False
    if args.files:
        # per-file mode: gate fresh run outputs before they land
        for path in args.files:
            try:
                verdicts = check_artifact(path, baseline)
            except Exception as exc:  # noqa: BLE001 — report, go on
                print(f"{os.path.basename(path)}: ERROR {exc!r}")
                failed = True
                continue
            regs = regressions(verdicts)
            gated = [v for v in verdicts
                     if v.status != "no-baseline"]
            if regs:
                failed = True
                for v in regs:
                    print(f"{os.path.basename(path)}: REGRESSION "
                          f"{v.metric}: {v.detail}")
            elif args.verbose:
                print(f"{os.path.basename(path)}: ok "
                      f"({len(gated)} headline metrics)")
    else:
        # repo mode: the tree's best evidence per metric must still
        # reach the committed headline (history is not re-judged)
        fresh = build_index(root, now=args.now)
        for v in check_headline(fresh, baseline):
            if v.status == "regression":
                failed = True
                print(f"REGRESSION {v.metric}: {v.detail}")
            elif args.verbose:
                print(f"{v.metric}: {v.status} ({v.new_value})")
    alarm = freshness_alarm(baseline, args.max_age_days)
    if alarm:
        print(f"freshness: WARNING {alarm}")
    if failed:
        print("perf check: FAILED")
        return 5
    print("perf check: ok")
    return 0


def _cmd_lint(args) -> int:
    from .registry import lint_sources
    violations = lint_sources(root=args.root)
    for v in violations:
        print(v)
    if violations:
        print(f"perf lint: {len(violations)} violation(s)")
        return 7
    print("perf lint: ok")
    return 0


def _cmd_freshness(args) -> int:
    from .check import freshness_alarm
    from .registry import load_index
    index = load_index(path=args.against, root=args.root)
    print(json.dumps(index["freshness"]))
    alarm = freshness_alarm(index, args.max_age_days)
    if alarm:
        print(f"WARNING: {alarm}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "python -m hcache_deepspeed_tpu.perf",
        description="perf-artifact registry + regression sentinel")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detect)")
    p.add_argument("--now", type=float, default=None,
                   help="freshness reference time (UTC epoch "
                        "seconds); injects the ONE sanctioned wall-"
                        "clock default in registry.build_index, "
                        "making index/check runs reproducible")
    sub = p.add_subparsers(dest="cmd", required=True)

    pi = sub.add_parser("index", help="rebuild PERF_TRAJECTORY.json")
    pi.add_argument("--out", default=None)
    pi.add_argument("--git", action="store_true",
                    help="attribute each artifact to its producing "
                         "commit (slower)")
    pi.set_defaults(fn=_cmd_index)

    pc = sub.add_parser("check", help="regression gate")
    pc.add_argument("--against", default=None,
                    help="baseline index (default: committed "
                         "PERF_TRAJECTORY.json)")
    pc.add_argument("--self-test", action="store_true",
                    help="prove the gate trips on synthetic "
                         "regressions (no repo state needed)")
    pc.add_argument("--max-age-days", type=float, default=2.0)
    pc.add_argument("--verbose", action="store_true")
    pc.add_argument("files", nargs="*",
                    help="artifacts to gate (default: all indexable "
                         "root artifacts)")
    pc.set_defaults(fn=_cmd_check)

    pl = sub.add_parser("lint",
                        help="no source-written artifact without a "
                             "schema")
    pl.set_defaults(fn=_cmd_lint)

    pf = sub.add_parser("freshness", help="wedged-relay gauge")
    pf.add_argument("--against", default=None)
    pf.add_argument("--max-age-days", type=float, default=2.0)
    pf.set_defaults(fn=_cmd_freshness)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
