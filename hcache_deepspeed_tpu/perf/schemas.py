"""Artifact-family schemas for the committed perf evidence.

Every perf artifact this repo commits at its root (bench JSON
wrappers, ``*_JSONL`` phase streams, chip logs, the dead-relay state
file) belongs to exactly one **family** declared here: a filename
pattern plus a parser that turns the file into typed
:class:`MetricPoint` rows. The registry (``perf.registry``) walks the
root through :func:`classify`; the golden-schema tier-1 test walks the
same way and fails when a committed artifact matches no family and is
not allowlisted in ``perf/KNOWN_UNINDEXED`` — so future PRs cannot
silently add unindexed evidence files, and ``perf lint`` applies the
same rule to artifact names written by source code.

Parsers are deliberately tolerant of the artifacts' real-world warts
(log lines interleaved into JSONL streams, rows embedded in a captured
``tail`` field, zero-byte files from interrupted chip sessions) but
STRICT about classification: an unknown name is an error, a known name
that fails to parse is an error, an empty file is recorded as
``status="empty"`` — visible, never silently skipped.
"""

import json
import re
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

#: the bench dead-relay convention (bench.py ``_error_payload``): a
#: payload carrying ``stale: true`` has no fresh measurement and its
#: ``stale_utc`` timestamps the last real one
UTC_FMT = "%Y-%m-%dT%H:%M:%SZ"


def parse_utc(s: str) -> Optional[float]:
    try:
        return time.mktime(time.strptime(s, UTC_FMT)) - time.timezone
    except (ValueError, TypeError):
        return None


def staleness_days(utc: Optional[str], now: float) -> Optional[float]:
    t = parse_utc(utc) if utc else None
    if t is None:
        return None
    return max(0.0, (now - t) / 86400.0)


@dataclass
class MetricPoint:
    """One indexed measurement."""
    metric: str                  # e.g. "train.tokens_per_sec_per_chip"
    value: float
    file: str
    unit: str = ""
    phase: str = ""
    #: measurement timestamp when the artifact carries one
    utc: Optional[str] = None
    #: the bench dead-relay stale marker (True = the producing round
    #: had no fresh chip measurement; value is carried history)
    stale: bool = False
    tags: Dict[str, str] = field(default_factory=dict)

    def to_json(self) -> Dict:
        out = {"metric": self.metric, "value": self.value,
               "file": self.file}
        if self.unit:
            out["unit"] = self.unit
        if self.phase:
            out["phase"] = self.phase
        if self.utc:
            out["utc"] = self.utc
        if self.stale:
            out["stale"] = True
        if self.tags:
            out["tags"] = dict(self.tags)
        return out


@dataclass
class ParsedArtifact:
    file: str
    family: str
    status: str                      # "ok" | "empty" | "meta"
    points: List[MetricPoint] = field(default_factory=list)
    #: artifact-level note (e.g. why it yields no points)
    note: str = ""


# ----------------------------------------------------------------- #
# raw readers
# ----------------------------------------------------------------- #
def read_json(text: str):
    return json.loads(text)


def read_jsonl_rows(text: str) -> List[Dict]:
    """Every parseable JSON object line; the committed streams carry
    interleaved engine log lines (``[2026-08-01 ...] [INFO] ...``) that
    a strict reader would choke on."""
    rows = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(row, dict):
            rows.append(row)
    return rows


def json_lines_from_tail(tail: str) -> List[Dict]:
    """Result lines embedded in a captured subprocess ``tail`` blob
    (the BENCH_rNN / MULTICHIP_rNN wrapper format)."""
    return read_jsonl_rows(tail or "")


# ----------------------------------------------------------------- #
# family parsers — each returns a list of MetricPoint
# ----------------------------------------------------------------- #
def _bench_payload_points(payload: Dict, file: str) -> List[MetricPoint]:
    """Points from one bench.py result line (fresh or dead-relay)."""
    pts: List[MetricPoint] = []
    if not isinstance(payload, dict):
        return pts
    stale = bool(payload.get("stale"))
    utc = payload.get("stale_utc") or \
        (payload.get("extra") or {}).get("utc")
    extra = payload.get("extra") or {}
    value = payload.get("value")
    if "metric" in payload and isinstance(value, (int, float)):
        cfg = str(extra.get("config", ""))
        tags = {"config": cfg} if cfg else {}
        if value:
            pts.append(MetricPoint(
                "train.tokens_per_sec_per_chip", float(value), file,
                unit=payload.get("unit", "tokens/sec"),
                phase="train-bench", utc=utc, stale=stale, tags=tags))
        if isinstance(extra.get("mfu"), (int, float)) and extra["mfu"]:
            pts.append(MetricPoint(
                "train.mfu", float(extra["mfu"]), file,
                phase="train-bench", utc=utc, stale=stale, tags=tags))
        if isinstance(payload.get("vs_baseline"), (int, float)) and \
                payload["vs_baseline"]:
            pts.append(MetricPoint(
                "train.vs_baseline", float(payload["vs_baseline"]),
                file, phase="train-bench", utc=utc, stale=stale,
                tags=tags))
        sd = extra.get("staleness_days")
        if isinstance(sd, (int, float)):
            pts.append(MetricPoint("bench.staleness_days", float(sd),
                                   file, unit="days",
                                   phase="dead-relay", utc=utc,
                                   stale=True))
    # dead-relay history rides under extra.last_measured {best,last}
    lm = extra.get("last_measured") or {}
    for which in ("best", "last"):
        rec = lm.get(which)
        if isinstance(rec, dict) and rec.get("value"):
            pts.append(MetricPoint(
                f"train.{which}_measured_tokens_per_sec",
                float(rec["value"]), file, unit="tokens/sec",
                phase="chip-history", utc=rec.get("utc"), stale=stale,
                tags={"config": str(rec.get("config", ""))}))
            if rec.get("mfu"):
                pts.append(MetricPoint(
                    f"train.{which}_measured_mfu", float(rec["mfu"]),
                    file, phase="chip-history", utc=rec.get("utc"),
                    stale=stale))
    return pts


def parse_bench_wrapper(text: str, file: str) -> List[MetricPoint]:
    """BENCH_rNN.json: {n, cmd, rc, tail} with the result line inside
    ``tail``."""
    doc = read_json(text)
    pts: List[MetricPoint] = []
    fresh = 0
    for row in json_lines_from_tail(doc.get("tail", "")):
        pts.extend(_bench_payload_points(row, file))
        if row.get("value") and "error" not in row:
            fresh += 1
    # every round is indexable even when the relay was dead and the
    # payload carried nothing (value 0.0, no history): the outcome
    # gauge is the record
    pts.append(MetricPoint("bench.round_had_fresh_measurement",
                           1.0 if fresh else 0.0, file,
                           phase="bench-round"))
    rnd = doc.get("n")
    if isinstance(rnd, int):
        for p in pts:
            p.tags.setdefault("round", str(rnd))
    return pts


def parse_bench_result(text: str, file: str) -> List[MetricPoint]:
    """Single bench payload (BENCH_FRESH/BENCH_LOCAL/VET_*): either a
    result line or a vet-error record ({config, error, mfu: null})."""
    doc = read_json(text)
    if "metric" not in doc and "error" in doc:
        # vet error: indexed as a zero-valued outcome gauge so the
        # failed-config evidence is queryable, not just archived
        return [MetricPoint("vet.ok", 0.0, file, phase="config-vet",
                            tags={"config": str(doc.get("config", ""))})]
    pts = _bench_payload_points(doc, file)
    if doc.get("metric") and not doc.get("error"):
        cfg = str((doc.get("extra") or {}).get("config", ""))
        pts.append(MetricPoint("vet.ok", 1.0, file, phase="config-vet",
                               tags={"config": cfg} if cfg else {}))
    return pts


def parse_train_curve(text: str, file: str) -> List[MetricPoint]:
    doc = read_json(text)
    utc = doc.get("utc")
    pts = []
    for rec in doc.get("results", []):
        cfg = str(rec.get("config", ""))
        if rec.get("tokens_per_sec"):
            pts.append(MetricPoint(
                "train.curve_tokens_per_sec",
                float(rec["tokens_per_sec"]), file, unit="tokens/sec",
                phase="train-curve", utc=utc, tags={"config": cfg}))
        if rec.get("mfu"):
            pts.append(MetricPoint(
                "train.curve_mfu", float(rec["mfu"]), file,
                phase="train-curve", utc=utc, tags={"config": cfg}))
    return pts


def parse_multichip(text: str, file: str) -> List[MetricPoint]:
    doc = read_json(text)
    ok = bool(doc.get("ok")) and not doc.get("skipped")
    return [MetricPoint("multichip.dryrun_ok", 1.0 if ok else 0.0,
                        file, phase="multichip-dryrun",
                        tags={"n_devices":
                              str(doc.get("n_devices", ""))})]


def parse_baseline_meta(text: str, file: str) -> List[MetricPoint]:
    read_json(text)          # must parse; carries no metric points
    return []


def parse_zero_overlap(text: str, file: str) -> List[MetricPoint]:
    rows = read_jsonl_rows(text)
    pts: List[MetricPoint] = []
    for row in rows:
        phase = row.get("phase", "")
        if phase == "summary":
            utc = row.get("utc")
            for key, metric in (
                    ("gather_overlap_ratio_on",
                     "zero_overlap.gather_overlap_ratio"),
                    ("reduce_overlap_ratio_on",
                     "zero_overlap.reduce_overlap_ratio"),
                    ("prefetch_on_gather_pairs",
                     "zero_overlap.gather_pairs"),
                    ("native_async_pairs",
                     "zero_overlap.native_async_pairs"),
                    ("qrs_wire_fraction_of_fp32",
                     "zero_overlap.qrs_wire_fraction_of_fp32"),
                    ("structural_overlap_ratio_decomposed",
                     "zero_overlap.structural_overlap_ratio"),
                    ("domino_decomposed_overlapped_pairs",
                     "domino.decomposed_overlapped_pairs"),
                    ("hier_structural_overlap_ratio",
                     "zero_overlap.hier_structural_overlap_ratio"),
                    ("hier_interaxis_wire_fraction",
                     "zero_overlap.hier_interaxis_wire_fraction"),
                    ("hier_longhaul_gather_fraction",
                     "zero_overlap.hier_longhaul_gather_fraction"),
                    ("hier_pod_wire_seconds_inter",
                     "zero_overlap.hier_pod_wire_seconds_inter"),
                    ("hier_pod_wire_seconds_intra",
                     "zero_overlap.hier_pod_wire_seconds_intra"),
                    ("domino_hier_overlapped_pairs",
                     "domino.hier_overlapped_pairs"),
                    ("hier_pipelined_structural_ratio",
                     "zero_overlap.hier_pipelined_structural_ratio"),
                    ("hier_pipelined_cross_axis_pairs",
                     "zero_overlap.hier_pipelined_cross_axis_pairs"),
                    ("wire_cal_gbps_inter",
                     "zero_overlap.wire_cal_gbps_inter"),
                    ("wire_cal_gbps_intra",
                     "zero_overlap.wire_cal_gbps_intra"),
                    ("wire_cal_divergence_inter",
                     "zero_overlap.wire_cal_divergence_inter"),
                    ("wire_cal_divergence_intra",
                     "zero_overlap.wire_cal_divergence_intra"),
                    ("fused_subsumed_pairs",
                     "zero_overlap.fused_subsumed_pairs"),
                    ("fused_mid_gather_leaves",
                     "zero_overlap.fused_mid_gather_leaves"),
                    ("fused_wallclock_speedup",
                     "zero_overlap.fused_wallclock_speedup")):
                if isinstance(row.get(key), (int, float)):
                    pts.append(MetricPoint(metric, float(row[key]),
                                           file, phase=phase, utc=utc))
            for key, metric in (
                    ("bitwise_parity", "zero_overlap.bitwise_parity"),
                    ("qrs_bitwise_depth_parity",
                     "zero_overlap.qrs_bitwise_depth_parity"),
                    ("qrs_trajectory_within_tol",
                     "zero_overlap.qrs_trajectory_within_tol"),
                    ("decomposed_bitwise_vs_native",
                     "zero_overlap.decomposed_bitwise_vs_native"),
                    ("decomposed_qwire_bitwise",
                     "zero_overlap.decomposed_qwire_bitwise"),
                    ("domino_decomposed_value_parity",
                     "domino.decomposed_value_parity"),
                    ("hier_bitwise_vs_native",
                     "zero_overlap.hier_bitwise_vs_native"),
                    ("hier_bitwise_vs_flat",
                     "zero_overlap.hier_bitwise_vs_flat"),
                    ("hier_qwire_bitwise",
                     "zero_overlap.hier_qwire_bitwise"),
                    ("hier_longhaul_trajectory_within_tol",
                     "zero_overlap.hier_longhaul_trajectory_within_tol"),
                    ("domino_hier_value_parity",
                     "domino.hier_value_parity"),
                    ("hier_hpz_unified_bitwise",
                     "zero_overlap.hier_hpz_unified_bitwise"),
                    ("hier_hpz_secondary_on_mesh",
                     "zero_overlap.hier_hpz_secondary_on_mesh"),
                    ("hier_pipelined_bitwise",
                     "zero_overlap.hier_pipelined_bitwise"),
                    ("hier_16dev_parity",
                     "zero_overlap.hier_16dev_parity"),
                    ("wire_cal_shape_ok",
                     "zero_overlap.wire_cal_shape_ok"),
                    ("fused_parity_plain",
                     "zero_overlap.fused_parity_plain"),
                    ("fused_parity_qwire",
                     "zero_overlap.fused_parity_qwire"),
                    ("fused_audit_gate",
                     "zero_overlap.fused_audit_gate"),
                    ("fused_le_unfused_largest",
                     "zero_overlap.fused_le_unfused_largest"),
                    ("mesh3d_bookkeeping_ok",
                     "zero_overlap.mesh3d_bookkeeping_ok"),
                    ("fused_16dev_parity",
                     "zero_overlap.fused_16dev_parity")):
                if key in row:
                    pts.append(MetricPoint(metric,
                                           1.0 if row[key] else 0.0,
                                           file, phase=phase, utc=utc))
        elif phase in ("domino-audit", "domino-audit-int8") and \
                row.get("overlap"):
            suffix = "int8" if phase.endswith("int8") else "fp"
            if "derived_async_pairs" in row:
                pts.append(MetricPoint(
                    f"domino.derived_async_pairs_{suffix}",
                    float(row["derived_async_pairs"]), file,
                    phase=phase))
    return pts


def parse_serve_loop(text: str, file: str) -> List[MetricPoint]:
    rows = read_jsonl_rows(text)
    pts: List[MetricPoint] = []
    for row in rows:
        if row.get("phase") == "serve-loop-summary":
            # the workload identity rides as the config tag so a
            # differently-shaped trace (smoke run, other rps) is never
            # gated against the committed acceptance trace
            tags = {"model": str(row.get("model", "")),
                    "config": (
                        f"{row.get('model', '')}"
                        f"-n{row.get('n_requests', '')}"
                        f"-rps{row.get('rps', '')}"
                        f"-p{row.get('prompt_len', '')}"
                        f"-new{row.get('max_new', '')}"
                        f"-kv{row.get('kv_blocks', '')}"
                        f"x{row.get('block_size', '')}"
                        f"-vc{int(bool(row.get('virtual_clock')))}")}
            for fam, lower in (("ttft_s", True), ("tpot_s", True),
                               ("queue_wait_s", True)):
                block = row.get(fam) or {}
                for q in ("p50", "p99"):
                    if isinstance(block.get(q), (int, float)):
                        pts.append(MetricPoint(
                            f"serve_loop.{fam}_{q}", float(block[q]),
                            file, unit="s", phase="serve-loop",
                            tags=tags))
            for key, metric in (
                    ("gen_tokens_per_sec",
                     "serve_loop.gen_tokens_per_sec"),
                    ("restore_overlap_ratio",
                     "serve_loop.restore_overlap_ratio"),
                    ("preemptions", "serve_loop.preemptions"),
                    ("restores", "serve_loop.restores"),
                    ("dropped", "serve_loop.dropped")):
                if isinstance(row.get(key), (int, float)):
                    pts.append(MetricPoint(metric, float(row[key]),
                                           file, phase="serve-loop",
                                           tags=tags))
            parity = row.get("parity") or {}
            if parity.get("checked"):
                pts.append(MetricPoint(
                    "serve_loop.restore_parity_ok",
                    1.0 if parity["ok"] == parity["checked"] else 0.0,
                    file, phase="serve-loop", tags=tags))
        elif row.get("phase") == "serve-loop-slo":
            for name, v in (row.get("burn_rates") or {}).items():
                pts.append(MetricPoint(
                    f"serve_loop.slo_{name}_burn_rate", float(v),
                    file, phase="serve-loop-slo"))
            if "prometheus_valid" in row:
                pts.append(MetricPoint(
                    "serve_loop.prometheus_snapshot_valid",
                    1.0 if row["prometheus_valid"] else 0.0, file,
                    phase="serve-loop-slo"))
    return pts


def parse_chaos_serve(text: str, file: str) -> List[MetricPoint]:
    rows = read_jsonl_rows(text)
    pts: List[MetricPoint] = []
    for row in rows:
        if row.get("phase") == "chaos-summary":
            pts.append(MetricPoint(
                "chaos.deterministic",
                1.0 if row.get("deterministic") else 0.0, file,
                phase="chaos-summary"))
            pts.append(MetricPoint(
                "chaos.invariants_ok",
                1.0 if row.get("invariants_ok") else 0.0, file,
                phase="chaos-summary"))
            pts.append(MetricPoint(
                "chaos.violations", float(len(row.get("violations",
                                                      []))),
                file, phase="chaos-summary"))
        elif row.get("phase") == "chaos-ckpt":
            pts.append(MetricPoint(
                "chaos.ckpt_fallback_ok",
                1.0 if row.get("fallback_ok") else 0.0, file,
                phase="chaos-ckpt"))
    return pts


def parse_fleet_serve(text: str, file: str) -> List[MetricPoint]:
    rows = read_jsonl_rows(text)
    pts: List[MetricPoint] = []
    for row in rows:
        phase = row.get("phase", "")
        if phase == "fleet-summary":
            for key, metric in (
                    ("deterministic", "fleet.deterministic"),
                    ("invariants_ok", "fleet.invariants_ok"),
                    ("migration_balance_ok",
                     "fleet.migration_balance_ok"),
                    ("span_counter_agreement",
                     "fleet.span_counter_agreement")):
                if key in row:
                    pts.append(MetricPoint(metric,
                                           1.0 if row[key] else 0.0,
                                           file, phase=phase))
            for key, metric in (
                    ("migration_overlap_ratio",
                     "fleet.migration_overlap_ratio"),
                    ("span_overlap_ratio",
                     "fleet.span_overlap_ratio"),
                    ("evictions", "fleet.evictions"),
                    ("landings", "fleet.landings"),
                    ("recompute_landings", "fleet.recompute_landings"),
                    ("expired_in_transit",
                     "fleet.expired_in_transit"),
                    ("replica_crashes", "fleet.replica_crashes")):
                if isinstance(row.get(key), (int, float)):
                    pts.append(MetricPoint(metric, float(row[key]),
                                           file, phase=phase))
            pts.append(MetricPoint(
                "fleet.violations",
                float(len(row.get("violations", []))), file,
                phase=phase))
        elif phase == "fleet-replica":
            tags = {"replica": str(row.get("replica", "")),
                    "state": str(row.get("state", ""))}
            for key, metric in (
                    ("mean_occupancy", "fleet.replica_mean_occupancy"),
                    ("kv_util_peak", "fleet.replica_kv_util_peak"),
                    ("restores", "fleet.replica_restores"),
                    ("preemptions", "fleet.replica_preemptions")):
                if isinstance(row.get(key), (int, float)):
                    pts.append(MetricPoint(metric, float(row[key]),
                                           file, phase=phase,
                                           tags=tags))
    return pts


def parse_disagg_serve(text: str, file: str) -> List[MetricPoint]:
    rows = read_jsonl_rows(text)
    pts: List[MetricPoint] = []
    for row in rows:
        phase = row.get("phase", "")
        if phase == "disagg-summary":
            for key, metric in (
                    ("deterministic", "disagg.deterministic"),
                    ("stream_parity", "disagg.stream_parity"),
                    ("invariants_ok", "disagg.invariants_ok"),
                    ("span_counter_agreement",
                     "disagg.span_counter_agreement")):
                if key in row:
                    pts.append(MetricPoint(metric,
                                           1.0 if row[key] else 0.0,
                                           file, phase=phase))
            for key, metric in (
                    ("handoff_overlap_ratio",
                     "disagg.handoff_overlap_ratio"),
                    ("handoffs", "disagg.handoffs"),
                    ("colocated_decodes", "disagg.colocated_decodes"),
                    ("decode_tier_tpot_p95",
                     "disagg.decode_tier_tpot_p95"),
                    ("decode_tier_tpot_p99",
                     "disagg.decode_tier_tpot_p99"),
                    ("colocated_tpot_p99",
                     "disagg.colocated_tpot_p99")):
                if isinstance(row.get(key), (int, float)):
                    pts.append(MetricPoint(metric, float(row[key]),
                                           file, phase=phase))
            d99 = row.get("decode_tier_tpot_p99")
            c99 = row.get("colocated_tpot_p99")
            if isinstance(d99, (int, float)) and \
                    isinstance(c99, (int, float)) and d99 > 0:
                # the headline: how much better the decode tier's
                # tail is than the equal-replica colocated baseline
                # (> 1.0 = disagg wins; the bench hard-gates it)
                pts.append(MetricPoint(
                    "disagg.decode_tpot_p99_speedup",
                    round(c99 / d99, 6), file, unit="x",
                    phase=phase))
            pts.append(MetricPoint(
                "disagg.violations",
                float(len(row.get("violations", []))), file,
                phase=phase))
        elif phase == "disagg-int8-wire":
            if "stream_parity_vs_fullwidth" in row:
                pts.append(MetricPoint(
                    "disagg.int8_wire_stream_parity",
                    1.0 if row["stream_parity_vs_fullwidth"]
                    else 0.0, file, phase=phase))
            if isinstance(row.get("wire_fraction"), (int, float)):
                pts.append(MetricPoint(
                    "disagg.int8_wire_fraction",
                    float(row["wire_fraction"]), file, phase=phase))
        elif phase == "disagg-chunked-prefill":
            if isinstance(row.get("prefill_chunks"), (int, float)):
                pts.append(MetricPoint(
                    "disagg.prefill_chunks",
                    float(row["prefill_chunks"]), file, phase=phase))
            if "invariants_ok" in row:
                pts.append(MetricPoint(
                    "disagg.chunked_invariants_ok",
                    1.0 if row["invariants_ok"] else 0.0, file,
                    phase=phase))
        elif phase == "disagg-chaos":
            for key, metric in (
                    ("deterministic", "disagg.chaos_deterministic"),
                    ("invariants_ok", "disagg.chaos_invariants_ok")):
                if key in row:
                    pts.append(MetricPoint(metric,
                                           1.0 if row[key] else 0.0,
                                           file, phase=phase))
        elif phase == "disagg-tier":
            tags = {"tier": str(row.get("tier", ""))}
            for key, metric in (
                    ("preemptions", "disagg.tier_preemptions"),
                    ("restores", "disagg.tier_restores"),
                    ("mean_occupancy",
                     "disagg.tier_mean_occupancy")):
                if isinstance(row.get(key), (int, float)):
                    pts.append(MetricPoint(metric, float(row[key]),
                                           file, phase=phase,
                                           tags=tags))
    return pts


def parse_request_trace(text: str, file: str) -> List[MetricPoint]:
    """REQUEST_TRACE.jsonl: fleet-wide causal-tracing gates — DAG
    connectivity, attribution closure, run/flight determinism, and
    the p99 TTFT attribution profile."""
    rows = read_jsonl_rows(text)
    pts: List[MetricPoint] = []
    for row in rows:
        phase = row.get("phase", "")
        if phase == "request-trace-summary":
            utc = row.get("utc")
            for key, metric in (
                    ("dag_connected", "request_trace.dag_connected"),
                    ("closure_ok", "request_trace.closure_ok"),
                    ("deterministic", "request_trace.deterministic"),
                    ("flight_deterministic",
                     "request_trace.flight_deterministic")):
                if key in row:
                    pts.append(MetricPoint(metric,
                                           1.0 if row[key] else 0.0,
                                           file, phase=phase, utc=utc))
            for key, metric in (
                    ("closure_max_residual",
                     "request_trace.closure_max_residual"),
                    ("flight_bundles", "request_trace.flight_bundles"),
                    ("handoffs", "request_trace.handoffs"),
                    ("crash_evacuations",
                     "request_trace.crash_evacuations"),
                    ("traced_requests",
                     "request_trace.traced_requests"),
                    ("ttft_p99_s", "request_trace.ttft_p99_s")):
                if isinstance(row.get(key), (int, float)):
                    pts.append(MetricPoint(metric, float(row[key]),
                                           file, phase=phase, utc=utc))
            # the headline p99-TTFT attribution profile: seconds per
            # phase at the 99th percentile across the traced requests
            for attr_phase, v in sorted(
                    (row.get("ttft_attr_p99_s") or {}).items()):
                if isinstance(v, (int, float)):
                    pts.append(MetricPoint(
                        f"request_trace.ttft_attr_{attr_phase}_p99_s",
                        float(v), file, unit="s", phase=phase,
                        utc=utc))
            pts.append(MetricPoint(
                "request_trace.violations",
                float(len(row.get("violations", []))), file,
                phase=phase, utc=utc))
        elif phase == "request-trace-leg":
            tags = {"leg": str(row.get("leg", ""))}
            for key, metric in (
                    ("deterministic", "request_trace.leg_deterministic"),
                    ("connected", "request_trace.leg_connected"),
                    ("flight_deterministic",
                     "request_trace.leg_flight_deterministic")):
                if key in row:
                    pts.append(MetricPoint(metric,
                                           1.0 if row[key] else 0.0,
                                           file, phase=phase,
                                           tags=tags))
            for key, metric in (
                    ("max_closure_residual",
                     "request_trace.leg_max_closure_residual"),
                    ("flight_bundles",
                     "request_trace.leg_flight_bundles")):
                if isinstance(row.get(key), (int, float)):
                    pts.append(MetricPoint(metric, float(row[key]),
                                           file, phase=phase,
                                           tags=tags))
    return pts


def _workload_tag(file: str) -> Dict[str, str]:
    """The workload identity is the filename stem — SERVE_7B_INT8 and
    SERVE_7B measure different programs and must never be compared as
    one series."""
    return {"workload": file.rsplit(".", 1)[0]}


def parse_serve_bench(text: str, file: str) -> List[MetricPoint]:
    """SERVE_* / DECODE_DIAG_* / LOOKUP_* / SWEEP_* phase rows from
    ``inference/benchmark.py``."""
    rows = read_jsonl_rows(text)
    tags = _workload_tag(file)
    pts: List[MetricPoint] = []
    for row in rows:
        phase = row.get("phase", "")
        if "error" in row:
            continue                     # OOM/fallback notes, not data
        rtags = dict(tags)
        if "batch" in row:
            rtags["batch"] = str(row["batch"])
        if "offered_rps" in row:
            rtags["offered_rps"] = str(row["offered_rps"])
        if "lanes" in row:
            rtags["lanes"] = str(row["lanes"])
        if "variant" in row:
            rtags["variant"] = str(row["variant"])
        if isinstance(row.get("tokens_per_sec"), (int, float)):
            pts.append(MetricPoint(
                f"serve.{phase}.tokens_per_sec",
                float(row["tokens_per_sec"]), file,
                unit="tokens/sec", phase=phase, tags=rtags))
        if isinstance(row.get("ms_per_step"), (int, float)):
            pts.append(MetricPoint(
                f"serve.{phase}.ms_per_step",
                float(row["ms_per_step"]), file, unit="ms",
                phase=phase, tags=rtags))
        if isinstance(row.get("ms_per_token"), (int, float)):
            pts.append(MetricPoint(
                f"serve.{phase}.ms_per_token",
                float(row["ms_per_token"]), file, unit="ms",
                phase=phase, tags=rtags))
        if isinstance(row.get("gen_tokens_per_sec"), (int, float)):
            pts.append(MetricPoint(
                f"serve.{phase}.gen_tokens_per_sec",
                float(row["gen_tokens_per_sec"]), file,
                unit="tokens/sec", phase=phase, tags=rtags))
        if isinstance(row.get("effective_rps"), (int, float)):
            pts.append(MetricPoint(
                f"serve.{phase}.effective_rps",
                float(row["effective_rps"]), file, unit="req/s",
                phase=phase, tags=rtags))
        # decode-diag stretch decomposition (hds_decode_diag rows)
        for key in ("marginal_ms_per_token", "fixed_ms_per_stretch",
                    "implied_gbps"):
            if isinstance(row.get(key), (int, float)):
                pts.append(MetricPoint(
                    f"serve.{phase}.{key}", float(row[key]), file,
                    phase=phase, tags=rtags))
    return pts


def parse_restore_bench(text: str, file: str) -> List[MetricPoint]:
    rows = read_jsonl_rows(text)
    tags = _workload_tag(file)
    pts: List[MetricPoint] = []
    for row in rows:
        phase = row.get("phase", "")
        rtags = dict(tags)
        if "batch" in row:
            rtags["batch"] = str(row["batch"])
        if "prompt_len" in row:
            rtags["prompt_len"] = str(row["prompt_len"])
        if phase == "hcache-restore" and \
                isinstance(row.get("speedup"), (int, float)):
            pts.append(MetricPoint("restore.speedup_e2e",
                                   float(row["speedup"]), file,
                                   phase=phase, tags=rtags))
        elif phase == "hcache-restore-marginal":
            for key, metric in (
                    ("speedup_replay", "restore.speedup_replay"),
                    ("speedup_e2e", "restore.speedup_e2e_marginal"),
                    ("link_gbps", "restore.link_gbps")):
                if isinstance(row.get(key), (int, float)):
                    pts.append(MetricPoint(metric, float(row[key]),
                                           file, phase=phase,
                                           tags=rtags))
        elif phase == "restore-crossover-summary":
            cl = row.get("crossover_prompt_len")
            if isinstance(cl, (int, float)):
                pts.append(MetricPoint(
                    "restore.crossover_prompt_len", float(cl), file,
                    phase=phase,
                    tags={"model": str(row.get("model", ""))}))
    return pts


def parse_spec_serve(text: str, file: str) -> List[MetricPoint]:
    """SPEC_SERVE.jsonl: scheduler-dispatched speculative decode +
    fleet-wide radix prefix reuse with latent prefix broadcast
    (``bench.py --spec-serve``). The summary row carries the headline
    gates; the phase rows carry their own verdicts as trajectory."""
    rows = read_jsonl_rows(text)
    pts: List[MetricPoint] = []

    def flag(metric, row, key, phase):
        if key in row:
            pts.append(MetricPoint(metric,
                                   1.0 if row[key] else 0.0, file,
                                   phase=phase))

    for row in rows:
        phase = row.get("phase", "")
        if phase == "spec-serve-summary":
            for key, metric in (
                    ("accepted_tokens_per_step",
                     "spec.accepted_tokens_per_step"),
                    ("reprefill_savings",
                     "spec.prefix_reprefill_savings"),
                    ("lookup_virtual_speedup",
                     "spec.lookup_virtual_speedup"),
                    ("mixed_virtual_speedup",
                     "spec.mixed_virtual_speedup"),
                    ("prefix_broadcasts", "spec.prefix_broadcasts"),
                    ("prefix_tokens_reused",
                     "spec.prefix_tokens_reused")):
                if isinstance(row.get(key), (int, float)):
                    pts.append(MetricPoint(metric, float(row[key]),
                                           file, phase=phase))
            flag("spec.stream_parity", row, "stream_parity", phase)
            flag("spec.deterministic", row, "deterministic", phase)
            flag("spec.invariants_ok", row, "invariants_ok", phase)
            pts.append(MetricPoint(
                "spec.violations",
                float(len(row.get("violations", []))), file,
                phase=phase))
        elif phase == "spec-lookup":
            flag("spec.lookup_stream_parity", row, "stream_parity",
                 phase)
        elif phase == "spec-prefix":
            flag("spec.prefix_stream_parity", row, "stream_parity",
                 phase)
        elif phase == "spec-slo":
            if isinstance(row.get("final_level"), (int, float)):
                pts.append(MetricPoint(
                    "spec.slo_final_level", float(row["final_level"]),
                    file, phase=phase))
    return pts


def parse_fabric_serve(text: str, file: str) -> List[MetricPoint]:
    """FABRIC_SERVE.jsonl: the deployment fabric audit (``bench.py
    --fabric``) — process-vs-in-memory transport parity plus the
    literal kill-a-process chaos leg. The boolean gates are hard
    (rel=0.0 in TOLERANCES); the measured wire throughput is
    wall-clock on whatever host ran the bench and is recorded as
    informational trajectory only."""
    rows = read_jsonl_rows(text)
    pts: List[MetricPoint] = []
    for row in rows:
        if row.get("phase") != "fabric-summary":
            continue
        phase = "fabric-summary"
        for key, metric in (
                ("deterministic", "fabric.deterministic"),
                ("stream_parity", "fabric.stream_parity"),
                ("digest_transport_invariant",
                 "fabric.digest_transport_invariant"),
                ("trace_connected", "fabric.trace_connected"),
                ("chaos_ok", "fabric.chaos_ok"),
                ("invariants_ok", "fabric.invariants_ok")):
            if key in row:
                pts.append(MetricPoint(metric,
                                       1.0 if row[key] else 0.0,
                                       file, phase=phase))
        for key, metric in (
                ("two_hop_deliveries", "fabric.two_hop_deliveries"),
                ("max_trace_hops", "fabric.max_trace_hops"),
                ("chaos_kills", "fabric.chaos_kills"),
                ("replica_crashes", "fabric.replica_crashes"),
                ("done_after_kill", "fabric.done_after_kill"),
                ("bootstrap_mismatches",
                 "fabric.bootstrap_mismatches"),
                ("measured_wire_bytes_per_s",
                 "fabric.measured_wire_bytes_per_s")):
            if isinstance(row.get(key), (int, float)):
                pts.append(MetricPoint(metric, float(row[key]),
                                       file, phase=phase))
        pts.append(MetricPoint(
            "fabric.violations",
            float(len(row.get("violations", []))), file,
            phase=phase))
    return pts


def parse_fabric_obs(text: str, file: str) -> List[MetricPoint]:
    """FABRIC_OBS.jsonl: the cross-process telemetry-plane audit
    (``bench.py --fabric-obs``) — harvest digest invariance, assembled
    cross-process timeline validity, SIGKILL postmortem telemetry, and
    the harvest-overhead budget. The boolean gates are hard (rel=0.0
    in TOLERANCES) and the overhead fraction is upper-bounded; the
    per-link wire percentiles are wall-clock on whatever host ran the
    bench and index as informational trajectory only."""
    rows = read_jsonl_rows(text)
    pts: List[MetricPoint] = []
    for row in rows:
        if row.get("phase") != "fabric-obs-summary":
            continue
        phase = "fabric-obs-summary"
        for key, metric in (
                ("deterministic", "fabric_obs.deterministic"),
                ("harvest_digest_invariant",
                 "fabric_obs.harvest_digest_invariant"),
                ("timeline_valid", "fabric_obs.timeline_valid"),
                ("postmortem_has_telemetry",
                 "fabric_obs.postmortem_has_telemetry"),
                ("chaos_ok", "fabric_obs.chaos_ok"),
                ("invariants_ok", "fabric_obs.invariants_ok")):
            if key in row:
                pts.append(MetricPoint(metric,
                                       1.0 if row[key] else 0.0,
                                       file, phase=phase))
        for key, metric in (
                ("harvests", "fabric_obs.harvests"),
                ("harvest_failures", "fabric_obs.harvest_failures"),
                ("harvest_overhead_fraction",
                 "fabric_obs.harvest_overhead_fraction"),
                ("worker_rows", "fabric_obs.worker_rows"),
                ("worker_spans", "fabric_obs.worker_spans"),
                ("cross_worker_arrows",
                 "fabric_obs.cross_worker_arrows"),
                ("wire_latency_p50_s",
                 "fabric_obs.wire_latency_p50_s"),
                ("wire_latency_p99_s",
                 "fabric_obs.wire_latency_p99_s"),
                ("wire_bytes_per_s_p50",
                 "fabric_obs.wire_bytes_per_s_p50"),
                ("wire_bytes_per_s_p99",
                 "fabric_obs.wire_bytes_per_s_p99")):
            if isinstance(row.get(key), (int, float)):
                pts.append(MetricPoint(metric, float(row[key]),
                                       file, phase=phase))
        pts.append(MetricPoint(
            "fabric_obs.violations",
            float(len(row.get("violations", []))), file,
            phase=phase))
    return pts


def parse_autoscale_serve(text: str, file: str) -> List[MetricPoint]:
    """AUTOSCALE_SERVE.jsonl: the elastic-autoscaling audit
    (``bench.py --autoscale``) — the hysteresis control loop vs static
    fleets on the bursty multi-tenant trace, scale-event chaos, and
    the process-mode spawn/reap leg. The boolean gates are hard
    (rel=0.0 in TOLERANCES); SLO attainment and the cost-savings
    fraction are the headline trajectory."""
    rows = read_jsonl_rows(text)
    pts: List[MetricPoint] = []
    for row in rows:
        if row.get("phase") != "autoscale-summary":
            continue
        phase = "autoscale-summary"
        for key, metric in (
                ("deterministic", "autoscale.deterministic"),
                ("slo_vs_static_ok", "autoscale.slo_vs_static_ok"),
                ("cost_vs_static_ok", "autoscale.cost_vs_static_ok"),
                ("scale_events_span_verified",
                 "autoscale.scale_events_span_verified"),
                ("chaos_deterministic",
                 "autoscale.chaos_deterministic"),
                ("chaos_invariants_ok",
                 "autoscale.chaos_invariants_ok"),
                ("process_ok", "autoscale.process_ok"),
                ("trace_connected", "autoscale.trace_connected"),
                ("invariants_ok", "autoscale.invariants_ok")):
            if key in row:
                pts.append(MetricPoint(metric,
                                       1.0 if row[key] else 0.0,
                                       file, phase=phase))
        for key, metric in (
                ("slo_attainment", "autoscale.slo_attainment"),
                ("cost_savings_fraction",
                 "autoscale.cost_savings_fraction"),
                ("cost_replica_steps",
                 "autoscale.cost_replica_steps"),
                ("static_peak_cost", "autoscale.static_peak_cost"),
                ("scale_ups", "autoscale.scale_ups"),
                ("retires_completed",
                 "autoscale.retires_completed"),
                ("flaps", "autoscale.flaps")):
            if isinstance(row.get(key), (int, float)):
                pts.append(MetricPoint(metric, float(row[key]),
                                       file, phase=phase))
        pts.append(MetricPoint(
            "autoscale.violations",
            float(len(row.get("violations", []))), file,
            phase=phase))
    return pts


def parse_paged_vet(text: str, file: str) -> List[MetricPoint]:
    rows = read_jsonl_rows(text)
    pts = []
    for row in rows:
        if row.get("phase") != "paged-vet":
            continue
        tags = {"head_tile": str(row.get("head_tile", ""))}
        pts.append(MetricPoint("paged_vet.ok",
                               1.0 if row.get("ok") else 0.0, file,
                               phase="paged-vet", tags=tags))
        if isinstance(row.get("max_abs_err"), (int, float)):
            pts.append(MetricPoint("paged_vet.max_abs_err",
                                   float(row["max_abs_err"]), file,
                                   phase="paged-vet", tags=tags))
    return pts


def parse_last_measured(text: str, file: str) -> List[MetricPoint]:
    """.bench_last_measured.json: the chip-truth best/last record the
    dead-relay path reports from — the canonical freshness source."""
    doc = read_json(text)
    pts = []
    for which in ("best", "last"):
        rec = doc.get(which)
        if isinstance(rec, dict) and rec.get("value"):
            pts.append(MetricPoint(
                f"chip.{which}_tokens_per_sec", float(rec["value"]),
                file, unit="tokens/sec", phase="chip-truth",
                utc=rec.get("utc"),
                tags={"config": str(rec.get("config", ""))}))
            if rec.get("mfu"):
                pts.append(MetricPoint(
                    f"chip.{which}_mfu", float(rec["mfu"]), file,
                    phase="chip-truth", utc=rec.get("utc")))
    return pts


_DOMINO_PAIRS_RE = re.compile(
    r"(\d+)\s+native async pair|native[_ ]async[_ ]pairs\D*(\d+)",
    re.IGNORECASE)
_RELAY_LINE_RE = re.compile(r"^(UP|DOWN)(\(\w+\))?\s", re.MULTILINE)


def parse_chip_log(text: str, file: str) -> List[MetricPoint]:
    """Best-effort mining of free-form chip session logs: embedded
    bench result lines, Domino native-pair verdicts, relay up/down
    probes. Logs with none of those still index (presence is the
    point — the file is classified, not ignored)."""
    pts: List[MetricPoint] = []
    for row in read_jsonl_rows(text):
        if isinstance(row, dict) and "metric" in row:
            for p in _bench_payload_points(row, file):
                p.phase = p.phase or "chip-log"
                pts.append(p)
    if "DOMINO" in file.upper():
        m = _DOMINO_PAIRS_RE.search(text)
        if m:
            n = next(g for g in m.groups() if g is not None)
            pts.append(MetricPoint("domino.native_async_pairs_on_chip",
                                   float(n), file, phase="chip-log"))
    probes = _RELAY_LINE_RE.findall(text)
    if probes:
        down = sum(1 for state, _ in probes if state == "DOWN")
        pts.append(MetricPoint("relay.down_probe_fraction",
                               down / len(probes), file,
                               phase="relay-watch",
                               tags={"probes": str(len(probes))}))
    return pts


def parse_index_meta(text: str, file: str) -> List[MetricPoint]:
    """PERF_TRAJECTORY.json itself — parses, carries no points (it IS
    the index)."""
    read_json(text)
    return []


# ----------------------------------------------------------------- #
# the family table
# ----------------------------------------------------------------- #
@dataclass(frozen=True)
class ArtifactFamily:
    name: str
    pattern: str                           # regex over the basename
    parser: Callable[[str, str], List[MetricPoint]]
    description: str

    def matches(self, filename: str) -> bool:
        return re.match(self.pattern, filename) is not None


FAMILIES: List[ArtifactFamily] = [
    ArtifactFamily(
        "perf-index", r"^PERF_TRAJECTORY\.json$", parse_index_meta,
        "the committed perf index itself (meta, not an artifact)"),
    ArtifactFamily(
        "bench-wrapper", r"^BENCH_r\d+\.json$", parse_bench_wrapper,
        "driver-captured bench rounds: {n, cmd, rc, tail} with the "
        "result line inside tail"),
    ArtifactFamily(
        "bench-result", r"^(BENCH_FRESH|BENCH_LOCAL)\.json$",
        parse_bench_result,
        "single bench.py result line (fresh chip measurement)"),
    ArtifactFamily(
        "config-vet", r"^VET_[A-Z0-9_]+\.json$", parse_bench_result,
        "per-config chip vetting record (result line or typed error)"),
    ArtifactFamily(
        "baseline-meta", r"^BASELINE\.json$", parse_baseline_meta,
        "reference-target metadata (no metric points)"),
    ArtifactFamily(
        "train-curve", r"^TRAIN_CURVE\.json$", parse_train_curve,
        "multi-config chip campaign: per-config tokens/sec + MFU"),
    ArtifactFamily(
        "multichip-dryrun", r"^MULTICHIP_r\d+\.json$", parse_multichip,
        "8-device dryrun gate: ok/skipped per round"),
    ArtifactFamily(
        "zero-overlap", r"^ZERO_OVERLAP(_TPU)?\.jsonl$",
        parse_zero_overlap,
        "ZeRO-3 overlap + quantized-wire + decomposed-ring audit "
        "stream (bench.py --zero-overlap; hlo_audit rows; _TPU = the "
        "chip-truth capture from bin/chip_overlap_campaign.sh)"),
    ArtifactFamily(
        "serve-loop", r"^SERVE_LOOP\.jsonl$", parse_serve_loop,
        "continuous-batching serve-loop trace: per-request rows + "
        "summary percentiles + SLO row"),
    ArtifactFamily(
        "chaos-serve", r"^CHAOS_SERVE\.jsonl$", parse_chaos_serve,
        "chaos harness: fault plan, invariants, determinism gate"),
    ArtifactFamily(
        "fleet-serve", r"^FLEET_SERVE\.jsonl$", parse_fleet_serve,
        "fleet serving: N-replica router + latent migration under "
        "replica chaos (per-replica occupancy, migration accounting, "
        "span-derived overlap, determinism gate)"),
    ArtifactFamily(
        "disagg-serve", r"^DISAGG_SERVE\.jsonl$", parse_disagg_serve,
        "disaggregated prefill/decode serving: tier coordinator vs "
        "equal-replica colocated baseline (decode-tail win, stream "
        "parity, span-derived handoff overlap, int8 latent wire, "
        "chunked prefill, tier chaos, determinism gates)"),
    ArtifactFamily(
        "spec-serve", r"^SPEC_SERVE\.jsonl$", parse_spec_serve,
        "scheduler-dispatched speculative decode + fleet-wide radix "
        "prefix reuse with latent prefix broadcast (accepted-tokens/"
        "step, re-prefill savings, stream parity, SLO-aware ladder, "
        "determinism gates)"),
    ArtifactFamily(
        "fabric-serve", r"^FABRIC_SERVE\.jsonl$", parse_fabric_serve,
        "deployment fabric: process-vs-in-memory replica transport "
        "parity (digest invariance, bitwise streams, two-hop socket "
        "crossings, cross-process trace hops, measured-vs-priced "
        "wire) + the literal kill-a-process chaos leg"),
    ArtifactFamily(
        "fabric-obs", r"^FABRIC_OBS\.jsonl$", parse_fabric_obs,
        "cross-process telemetry plane: worker span/metric harvest "
        "over the fabric control channel (digest-invisibility gate, "
        "assembled cross-process timeline with real worker rows + "
        "cross-worker arrows, SIGKILL postmortem telemetry, harvest "
        "overhead budget, per-link wire percentiles)"),
    ArtifactFamily(
        "autoscale-serve", r"^AUTOSCALE_SERVE\.jsonl$",
        parse_autoscale_serve,
        "SLO-driven elastic autoscaling: hysteresis control loop vs "
        "equal-peak static fleets (attainment at strictly lower "
        "replica-step cost), span-verified scale events, scale-event "
        "chaos (aborted bootstrap / mid-drain crash / faulted "
        "pre-warm), process-mode worker spawn/kill-recovery/reap"),
    ArtifactFamily(
        "request-trace", r"^REQUEST_TRACE\.jsonl$",
        parse_request_trace,
        "fleet-wide causal request tracing: cross-replica span-DAG "
        "connectivity, additive critical-path attribution with the "
        "closure gate, p99-TTFT attribution profile, and the "
        "anomaly-triggered flight-recorder determinism gate"),
    ArtifactFamily(
        "restore-bench",
        r"^RESTORE_[A-Z0-9_]+\.jsonl$", parse_restore_bench,
        "HCache restore benchmarks: e2e/marginal speedups + "
        "crossover curve"),
    ArtifactFamily(
        "serve-bench",
        r"^(SERVE|DECODE_DIAG|LOOKUP|SWEEP)_[A-Z0-9_]+\.jsonl$",
        parse_serve_bench,
        "serving benchmark phase streams (prefill/decode/sweep/"
        "lookup/floors)"),
    ArtifactFamily(
        "paged-vet", r"^PAGED_VET\.jsonl$", parse_paged_vet,
        "paged-attention kernel numeric vetting rows"),
    ArtifactFamily(
        "last-measured", r"^\.bench_last_measured\.json$",
        parse_last_measured,
        "chip-truth best/last record (the dead-relay freshness "
        "source)"),
    ArtifactFamily(
        "chip-log",
        r"^(chip_[a-z0-9_]+|DOMINO_TPU_r\d+|relay_state_r\d+|"
        r"fullsuite_[a-z0-9]+|smoke_[a-z0-9]+)\.log$",
        parse_chip_log,
        "free-form chip/relay session logs (best-effort mining)"),
]


def classify(filename: str) -> Optional[ArtifactFamily]:
    for fam in FAMILIES:
        if fam.matches(filename):
            return fam
    return None


def parse_artifact(path, filename: str) -> ParsedArtifact:
    """Classify + parse one committed artifact. Raises ``KeyError`` on
    an unknown name and re-raises parser errors (a known family that
    stopped parsing is a broken artifact, not a skippable one)."""
    fam = classify(filename)
    if fam is None:
        raise KeyError(f"no artifact family matches {filename!r} "
                       "(declare one in perf/schemas.py or allowlist "
                       "it in perf/KNOWN_UNINDEXED)")
    with open(path, encoding="utf-8", errors="replace") as fh:
        text = fh.read()
    if not text.strip():
        return ParsedArtifact(filename, fam.name, "empty",
                              note="zero-byte artifact (interrupted "
                                   "chip session)")
    if filename.endswith(".jsonl") and not read_jsonl_rows(text):
        # log-prefix lines only: the run died before its first row —
        # visible as empty, same as a zero-byte session
        return ParsedArtifact(filename, fam.name, "empty",
                              note="no data rows (interrupted before "
                                   "first JSON row)")
    points = fam.parser(text, filename)
    status = "meta" if fam.name in ("baseline-meta", "perf-index") \
        else "ok"
    return ParsedArtifact(filename, fam.name, status, points)
