"""Perf-artifact registry: walk, classify, index.

Builds the committed ``PERF_TRAJECTORY.json`` — the machine-readable
trajectory the repo root's ~50 perf artifacts previously only implied:

* every root ``*.json`` / ``*.jsonl`` (plus the chip/relay ``*.log``
  files and ``.bench_last_measured.json``) is classified into a family
  (``perf.schemas``) and parsed into metric points;
* points are grouped into per-metric **series** (tok/s/chip, MFU,
  overlap ratios, wire fraction, serve-loop TTFT/TPOT percentiles,
  chaos invariants, ...), each point tagged with its producing file,
  bench phase, producer PR (first git commit that added the file, when
  git is available) and **freshness** — age in days since the
  measurement timestamp, reusing bench.py's dead-relay ``stale``
  convention;
* a **headline** block carries, per regression-gated metric
  (``perf.check.TOLERANCES``), the best committed value — the number
  ``perf check`` refuses to regress.

The golden-schema tier-1 test re-walks the root and fails on any
artifact the registry can't classify that is not allowlisted in
``perf/KNOWN_UNINDEXED`` (shipped empty — the allowlist is a debt
ledger, not a dumping ground).
"""

import json
import os
import re
import subprocess
import time
from typing import Dict, List, Optional

from .schemas import (FAMILIES, ParsedArtifact, classify,
                      parse_artifact, parse_utc, staleness_days)

INDEX_NAME = "PERF_TRAJECTORY.json"
ALLOWLIST_NAME = "KNOWN_UNINDEXED"
UTC_FMT = "%Y-%m-%dT%H:%M:%SZ"

#: root files that are code/config/docs, never perf artifacts
_NON_ARTIFACTS = {"pyproject.toml", INDEX_NAME}


def repo_root(start: Optional[str] = None) -> str:
    """Nearest ancestor containing bench.py + the package dir — the
    artifact root (works from an installed checkout or the repo)."""
    d = os.path.abspath(start or os.getcwd())
    while True:
        if os.path.exists(os.path.join(d, "bench.py")) and \
                os.path.isdir(os.path.join(d, "hcache_deepspeed_tpu")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            raise FileNotFoundError(
                "could not locate the repo root (bench.py) above "
                f"{start or os.getcwd()}")
        d = parent


def allowlist_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ALLOWLIST_NAME)


def load_allowlist() -> Dict[str, str]:
    """filename -> justification from perf/KNOWN_UNINDEXED (shipped
    empty; '#' comments and blank lines ignored)."""
    out: Dict[str, str] = {}
    try:
        with open(allowlist_path()) as fh:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                name, _, why = line.partition("#")
                out[name.strip()] = why.strip()
    except FileNotFoundError:
        pass
    return out


def iter_artifact_names(root: str) -> List[str]:
    """Committed root-level perf artifacts, sorted: every ``*.json`` /
    ``*.jsonl`` plus chip/relay logs and the hidden last-measured
    record."""
    names = []
    for name in sorted(os.listdir(root)):
        if name in _NON_ARTIFACTS:
            continue
        if not os.path.isfile(os.path.join(root, name)):
            continue
        if name.endswith((".json", ".jsonl")) or \
                (name.endswith(".log")) or \
                name == ".bench_last_measured.json":
            names.append(name)
    return names


def producer_pr(root: str, filename: str) -> str:
    """First commit that added ``filename`` (abbrev hash + subject),
    best-effort: 'uncommitted' for new files, 'unknown' without git."""
    try:
        out = subprocess.run(
            ["git", "log", "--follow", "--diff-filter=A",
             "--format=%h %s", "-1", "--", filename],
            cwd=root, capture_output=True, text=True, timeout=10)
        if out.returncode != 0:
            return "unknown"
        line = out.stdout.strip().splitlines()
        return line[0][:120] if line else "uncommitted"
    except (OSError, subprocess.TimeoutExpired):
        return "unknown"


# ----------------------------------------------------------------- #
def build_index(root: Optional[str] = None, now: Optional[float] = None,
                with_git: bool = False) -> Dict:
    """The full index dict (see module docstring). Deterministic for a
    fixed (tree, now); ``with_git`` adds producer-PR attribution via
    subprocess git calls."""
    from .check import TOLERANCES
    root = root or repo_root()
    # the ONE sanctioned wall-clock site in the deterministic-given-
    # (tree, now) index build: the freshness default when the CLI did
    # not inject --now; every other consumer threads now= through
    # hds: allow(HDS-P001) sanctioned freshness default, CLI --now injects
    now = time.time() if now is None else now
    artifacts: List[Dict] = []
    series: Dict[str, List[Dict]] = {}
    unindexed: List[str] = []
    allow = load_allowlist()
    for name in iter_artifact_names(root):
        path = os.path.join(root, name)
        if classify(name) is None:
            unindexed.append(name)
            artifacts.append({
                "file": name, "family": None, "status": "unindexed",
                "allowlisted": name in allow,
                "note": allow.get(name, "NOT ALLOWLISTED")})
            continue
        try:
            parsed: ParsedArtifact = parse_artifact(path, name)
        except Exception as exc:     # broken known artifact: visible
            artifacts.append({
                "file": name, "family": classify(name).name,
                "status": "error", "note": f"{type(exc).__name__}: "
                                           f"{exc}"})
            continue
        row = {"file": name, "family": parsed.family,
               "status": parsed.status, "points": len(parsed.points)}
        if parsed.note:
            row["note"] = parsed.note
        if with_git:
            row["producer_pr"] = producer_pr(root, name)
        artifacts.append(row)
        for p in parsed.points:
            rec = p.to_json()
            age = staleness_days(p.utc, now)
            if age is not None:
                rec["staleness_days"] = round(age, 2)
            if with_git and "producer_pr" in row:
                rec["producer_pr"] = row["producer_pr"]
            series.setdefault(p.metric, []).append(rec)
    for rows in series.values():
        rows.sort(key=lambda r: (r.get("utc") or "", r["file"],
                                 json.dumps(r.get("tags", {}),
                                            sort_keys=True)))
    headline = {}
    for metric, tol in sorted(TOLERANCES.items()):
        rows = series.get(metric)
        if not rows:
            continue
        pick = (min if tol.direction == "lower" else max)(
            rows, key=lambda r: r["value"])
        headline[metric] = {
            "value": pick["value"], "file": pick["file"],
            "utc": pick.get("utc"),
            "stale": bool(pick.get("stale")),
            "tags": pick.get("tags", {}),
            "direction": tol.direction,
            "rel_tolerance": tol.rel,
            "abs_tolerance": tol.abs,
        }
    freshness = _freshness_block(series, now)
    return {
        "version": 1,
        "generated_utc": time.strftime(UTC_FMT, time.gmtime(now)),
        "families": {f.name: f.description for f in FAMILIES},
        "artifacts": artifacts,
        "series": {k: series[k] for k in sorted(series)},
        "headline": headline,
        "freshness": freshness,
        "unindexed": sorted(unindexed),
        "allowlisted": allow,
    }


def _freshness_block(series: Dict, now: float) -> Dict:
    """The wedged-relay condition as a queryable gauge (ROADMAP item
    5): age of the last real chip measurement, from the chip-truth
    series' timestamps."""
    best_utc = None
    for metric in ("chip.last_tokens_per_sec",
                   "train.tokens_per_sec_per_chip"):
        for rec in series.get(metric, []):
            u = rec.get("utc")
            if u and (best_utc is None or
                      (parse_utc(u) or 0) > (parse_utc(best_utc) or 0)):
                best_utc = u
    out = {"last_chip_measurement_utc": best_utc}
    age = staleness_days(best_utc, now)
    out["staleness_days"] = round(age, 2) if age is not None else None
    # the bench dead-relay convention: stale once a round reports with
    # no fresh measurement; numerically: any positive age counts, 2+
    # days is the wedged-relay alarm threshold used in ROADMAP item 5
    out["stale"] = bool(age is not None and age > 1.0)
    return out


def write_index(path: Optional[str] = None, root: Optional[str] = None,
                with_git: bool = False,
                now: Optional[float] = None) -> Dict:
    root = root or repo_root()
    path = path or os.path.join(root, INDEX_NAME)
    index = build_index(root, now=now, with_git=with_git)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(index, fh, indent=1, sort_keys=False)
        fh.write("\n")
    os.replace(tmp, path)
    return index


def load_index(path: Optional[str] = None,
               root: Optional[str] = None) -> Dict:
    root = root or repo_root()
    path = path or os.path.join(root, INDEX_NAME)
    with open(path) as fh:
        return json.load(fh)


# ----------------------------------------------------------------- #
# lint: no source-written artifact without a schema
# ----------------------------------------------------------------- #
#: quoted artifact-style filename in source: ALL_CAPS stem + .json(l)
_ARTIFACT_LITERAL_RE = re.compile(
    r"""["']([A-Z][A-Z0-9_]*\.(?:json|jsonl))["']""")


def lint_sources(root: Optional[str] = None) -> List[str]:
    """Scan non-test source (bench.py + the package) for artifact-style
    filename literals and return one violation per literal the registry
    has no schema for. This is what keeps future bench phases from
    minting evidence files the index silently ignores."""
    root = root or repo_root()
    violations = []
    sources = [os.path.join(root, "bench.py")]
    pkg = os.path.join(root, "hcache_deepspeed_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        sources.extend(os.path.join(dirpath, f) for f in filenames
                       if f.endswith(".py"))
    for src in sources:
        try:
            with open(src, encoding="utf-8") as fh:
                text = fh.read()
        except OSError:
            continue
        for m in _ARTIFACT_LITERAL_RE.finditer(text):
            name = m.group(1)
            if classify(name) is None:
                line = text.count("\n", 0, m.start()) + 1
                violations.append(
                    f"{os.path.relpath(src, root)}:{line}: artifact "
                    f"literal {name!r} has no registry schema "
                    "(declare a family in perf/schemas.py)")
    return sorted(set(violations))
