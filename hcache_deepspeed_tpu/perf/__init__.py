"""Performance observatory: artifact registry + regression sentinel.

The repo root's committed perf evidence (bench JSON, phase-stream
JSONLs, chip logs) becomes machine-readable here:

* :mod:`.schemas` — one declared family + parser per artifact kind;
* :mod:`.registry` — walks/classifies/indexes into the committed
  ``PERF_TRAJECTORY.json`` (per-metric series with producer-PR,
  phase, and freshness tags) and lints source for artifact names
  without a schema;
* :mod:`.check` — the regression gate (`perf check`): fresh points vs
  the committed headline values, with per-metric tolerances, plus the
  ``self_check_rows`` hook bench runs call before writing artifacts.

CLI: ``python -m hcache_deepspeed_tpu.perf index|check|lint``.
See ``docs/observability.md``.
"""

from .check import (TOLERANCES, Tolerance, Verdict,  # noqa: F401
                    check_artifact, check_headline, check_points,
                    freshness_alarm, regressions, self_check_rows,
                    self_test)
from .registry import (INDEX_NAME, build_index, lint_sources,  # noqa: F401
                       load_allowlist, load_index, repo_root,
                       write_index)
from .schemas import (FAMILIES, ArtifactFamily, MetricPoint,  # noqa: F401
                      ParsedArtifact, classify, parse_artifact)

__all__ = [
    "FAMILIES", "ArtifactFamily", "MetricPoint", "ParsedArtifact",
    "classify", "parse_artifact", "INDEX_NAME", "build_index",
    "write_index", "load_index", "load_allowlist", "lint_sources",
    "repo_root", "TOLERANCES", "Tolerance", "Verdict", "check_points",
    "check_artifact", "check_headline", "regressions",
    "self_check_rows", "self_test", "freshness_alarm",
]
