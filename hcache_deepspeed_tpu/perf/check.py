"""Regression sentinel: compare fresh perf points against the
committed trajectory.

The repo's perf story is "claims computed from committed evidence"
(the hlo_audit / wire-bytes precedent): every number a PR committed as
evidence is a number a later PR can regress without noticing — unless
something diffs. This module is that diff:

* :data:`TOLERANCES` declares the **headline metrics** (the ones whose
  regression fails a check) with per-metric direction + tolerance;
* :func:`check_points` compares a list of fresh points against a
  baseline index's ``headline`` block;
* :func:`check_artifact` parses any file the registry understands and
  checks it — ``perf check --against PERF_TRAJECTORY.json FILE...``;
* :func:`self_check_rows` is the in-process hook ``bench.py
  --zero-overlap`` and ``serve_loop`` call before writing their
  artifact: the run self-compares and records the verdicts in the
  artifact itself (non-fatal there — the CLI gate is where failure
  has an exit code);
* :func:`self_test` synthesizes a baseline + a regressed point and
  proves the gate trips — ``perf check --self-test`` runs inside
  tier-1 (pure CPU, no chip).

A regression verdict compares against the baseline's **best** value
(per direction). Stale baselines still gate: "the relay is wedged" is
not a license to regress the last real measurement.
"""

import json
import os
import tempfile
from dataclasses import dataclass
from typing import Dict, List, Optional

from .schemas import MetricPoint


@dataclass(frozen=True)
class Tolerance:
    #: "higher" = bigger is better (throughput), "lower" = smaller is
    #: better (latency, wire fraction)
    direction: str = "higher"
    #: allowed relative slack vs the baseline headline
    rel: float = 0.05
    #: absolute slack floor (rescues near-zero baselines)
    abs: float = 0.0


#: the headline metrics the sentinel gates on. Everything else in the
#: index is informational trajectory.
TOLERANCES: Dict[str, Tolerance] = {
    # chip training throughput (stale-guarded history included)
    "train.tokens_per_sec_per_chip": Tolerance("higher", rel=0.10),
    "train.mfu": Tolerance("higher", rel=0.10),
    "train.best_measured_tokens_per_sec": Tolerance("higher", rel=0.05),
    "chip.best_tokens_per_sec": Tolerance("higher", rel=0.05),
    "chip.best_mfu": Tolerance("higher", rel=0.05),
    # ZeRO-3 overlap structure (CPU-deterministic: tight tolerances)
    "zero_overlap.gather_overlap_ratio": Tolerance("higher", rel=0.02),
    "zero_overlap.reduce_overlap_ratio": Tolerance("higher", rel=0.02),
    "zero_overlap.gather_pairs": Tolerance("higher", rel=0.0),
    "zero_overlap.qrs_wire_fraction_of_fp32":
        Tolerance("lower", rel=0.05),
    "zero_overlap.bitwise_parity": Tolerance("higher", rel=0.0),
    "zero_overlap.qrs_bitwise_depth_parity":
        Tolerance("higher", rel=0.0),
    "zero_overlap.qrs_trajectory_within_tol":
        Tolerance("higher", rel=0.0),
    # decomposed ring transport (CPU-deterministic structural audit)
    "zero_overlap.structural_overlap_ratio":
        Tolerance("higher", rel=0.02),
    "zero_overlap.decomposed_bitwise_vs_native":
        Tolerance("higher", rel=0.0),
    "zero_overlap.decomposed_qwire_bitwise":
        Tolerance("higher", rel=0.0),
    "domino.decomposed_overlapped_pairs": Tolerance("higher", rel=0.0),
    "domino.decomposed_value_parity": Tolerance("higher", rel=0.0),
    # hierarchical (2-D mesh) transport: bitwise bools are hard gates,
    # the wire fractions/seconds are byte-deterministic on CPU (tight),
    # structural ratio tolerates program-shape evolution like the flat
    # rings'
    "zero_overlap.hier_structural_overlap_ratio":
        Tolerance("higher", rel=0.02),
    "zero_overlap.hier_bitwise_vs_native": Tolerance("higher", rel=0.0),
    "zero_overlap.hier_bitwise_vs_flat": Tolerance("higher", rel=0.0),
    "zero_overlap.hier_qwire_bitwise": Tolerance("higher", rel=0.0),
    "zero_overlap.hier_longhaul_trajectory_within_tol":
        Tolerance("higher", rel=0.0),
    "zero_overlap.hier_interaxis_wire_fraction":
        Tolerance("lower", rel=0.05),
    "zero_overlap.hier_longhaul_gather_fraction":
        Tolerance("lower", rel=0.05),
    "zero_overlap.hier_pod_wire_seconds_inter":
        Tolerance("lower", rel=0.05),
    "zero_overlap.hier_pod_wire_seconds_intra":
        Tolerance("lower", rel=0.05),
    "domino.hier_overlapped_pairs": Tolerance("higher", rel=0.0),
    "domino.hier_value_parity": Tolerance("higher", rel=0.0),
    # ISSUE 15: unified hpZ tiering + phase pipelining + 16-device
    # factorings + measured wire calibration. Bitwise/parity bools and
    # shape validity are hard gates; the pipelined structural ratio
    # tolerates program-shape evolution like the other ratios; the
    # cross-axis pair count must never drop to zero. The measured
    # GB/s themselves are NOT gated (wall clock on whatever host ran
    # the bench — trajectory-informational only).
    "zero_overlap.hier_hpz_unified_bitwise":
        Tolerance("higher", rel=0.0),
    "zero_overlap.hier_hpz_secondary_on_mesh":
        Tolerance("higher", rel=0.0),
    "zero_overlap.hier_pipelined_bitwise":
        Tolerance("higher", rel=0.0),
    "zero_overlap.hier_pipelined_structural_ratio":
        Tolerance("higher", rel=0.02),
    "zero_overlap.hier_pipelined_cross_axis_pairs":
        Tolerance("higher", rel=0.0),
    "zero_overlap.hier_16dev_parity": Tolerance("higher", rel=0.0),
    "zero_overlap.wire_cal_shape_ok": Tolerance("higher", rel=0.0),
    # ISSUE 18: fused computation-collective kernels. The bitwise
    # parity bools, the in-kernel audit differential, the
    # fused<=unfused wall-clock verdict, the 3-D mesh bookkeeping
    # gates, and the 16-dev fused parity are HARD gates; the subsumed
    # pair count must never drop below the committed count; the
    # wall-clock speedup is trajectory-gated loosely (shared CI
    # hosts), never a hard floor above 1.0 — the boolean verdict at
    # the largest payload is the hard form of that claim.
    "zero_overlap.fused_parity_plain": Tolerance("higher", rel=0.0),
    "zero_overlap.fused_parity_qwire": Tolerance("higher", rel=0.0),
    "zero_overlap.fused_audit_gate": Tolerance("higher", rel=0.0),
    "zero_overlap.fused_subsumed_pairs": Tolerance("higher", rel=0.0),
    "zero_overlap.fused_mid_gather_leaves":
        Tolerance("higher", rel=0.0),
    "zero_overlap.fused_le_unfused_largest":
        Tolerance("higher", rel=0.0),
    "zero_overlap.fused_wallclock_speedup":
        Tolerance("higher", rel=0.50),
    "zero_overlap.mesh3d_bookkeeping_ok": Tolerance("higher", rel=0.0),
    "zero_overlap.fused_16dev_parity": Tolerance("higher", rel=0.0),
    # serve-loop percentiles (wall-clock on shared CI hosts: loose)
    "serve_loop.ttft_s_p50": Tolerance("lower", rel=0.50, abs=0.5),
    "serve_loop.ttft_s_p99": Tolerance("lower", rel=0.50, abs=0.5),
    "serve_loop.tpot_s_p50": Tolerance("lower", rel=0.50, abs=0.05),
    "serve_loop.tpot_s_p99": Tolerance("lower", rel=0.50, abs=0.05),
    "serve_loop.gen_tokens_per_sec": Tolerance("higher", rel=0.50),
    "serve_loop.restore_overlap_ratio": Tolerance("higher", rel=0.05),
    "serve_loop.restore_parity_ok": Tolerance("higher", rel=0.0),
    "serve_loop.dropped": Tolerance("lower", rel=0.0),
    # chaos invariants are booleans: any drop from 1.0 fails
    "chaos.deterministic": Tolerance("higher", rel=0.0),
    "chaos.invariants_ok": Tolerance("higher", rel=0.0),
    "chaos.ckpt_fallback_ok": Tolerance("higher", rel=0.0),
    # fleet chaos gates (CPU-deterministic; booleans are hard gates,
    # the overlap ratio tolerates router-policy evolution)
    "fleet.deterministic": Tolerance("higher", rel=0.0),
    "fleet.invariants_ok": Tolerance("higher", rel=0.0),
    "fleet.migration_balance_ok": Tolerance("higher", rel=0.0),
    "fleet.span_counter_agreement": Tolerance("higher", rel=0.0),
    "fleet.migration_overlap_ratio": Tolerance("higher", rel=0.25),
    "fleet.violations": Tolerance("lower", rel=0.0),
    # speculative serving + prefix reuse gates (CPU-deterministic:
    # booleans are hard gates; the two headline ratios tolerate trace
    # evolution like the other serving families)
    "spec.accepted_tokens_per_step": Tolerance("higher", rel=0.25),
    "spec.prefix_reprefill_savings": Tolerance("higher", rel=0.25),
    "spec.lookup_virtual_speedup": Tolerance("higher", rel=0.25),
    "spec.mixed_virtual_speedup": Tolerance("higher", rel=0.25),
    "spec.stream_parity": Tolerance("higher", rel=0.0),
    "spec.deterministic": Tolerance("higher", rel=0.0),
    "spec.invariants_ok": Tolerance("higher", rel=0.0),
    "spec.violations": Tolerance("lower", rel=0.0),
    # disaggregated serving gates (CPU-deterministic; booleans are
    # hard gates, the ratios tolerate scheduler-policy evolution)
    "disagg.deterministic": Tolerance("higher", rel=0.0),
    "disagg.stream_parity": Tolerance("higher", rel=0.0),
    "disagg.invariants_ok": Tolerance("higher", rel=0.0),
    "disagg.span_counter_agreement": Tolerance("higher", rel=0.0),
    "disagg.chaos_deterministic": Tolerance("higher", rel=0.0),
    "disagg.chaos_invariants_ok": Tolerance("higher", rel=0.0),
    "disagg.int8_wire_stream_parity": Tolerance("higher", rel=0.0),
    "disagg.chunked_invariants_ok": Tolerance("higher", rel=0.0),
    "disagg.violations": Tolerance("lower", rel=0.0),
    #: the headline ratio must stay above 1.0 (decode tier beats the
    #: colocated baseline); 25% slack absorbs policy evolution but a
    #: drop under ~1.0 regresses the architecture's reason to exist
    "disagg.decode_tpot_p99_speedup": Tolerance("higher", rel=0.25),
    "disagg.handoff_overlap_ratio": Tolerance("higher", rel=0.25),
    "disagg.int8_wire_fraction": Tolerance("lower", rel=0.10),
    # deployment fabric (ISSUE 16): the transport must move bytes, not
    # outcomes — parity/determinism/connectivity booleans are hard
    # gates, as are zero bootstrap mismatches and exactly-zero
    # violations. Hop/delivery counts may evolve with routing policy
    # (loose); the measured wire bytes/s is wall clock on whatever
    # host ran the bench and is deliberately NOT gated.
    "fabric.deterministic": Tolerance("higher", rel=0.0),
    "fabric.stream_parity": Tolerance("higher", rel=0.0),
    "fabric.digest_transport_invariant": Tolerance("higher", rel=0.0),
    "fabric.trace_connected": Tolerance("higher", rel=0.0),
    "fabric.chaos_ok": Tolerance("higher", rel=0.0),
    "fabric.invariants_ok": Tolerance("higher", rel=0.0),
    "fabric.bootstrap_mismatches": Tolerance("lower", rel=0.0),
    "fabric.violations": Tolerance("lower", rel=0.0),
    "fabric.two_hop_deliveries": Tolerance("higher", rel=0.50),
    "fabric.max_trace_hops": Tolerance("higher", rel=0.50),
    # cross-process telemetry plane (ISSUE 17): observation must be
    # digest-invisible and cheap — the invisibility/validity booleans
    # are hard gates, violations must be exactly zero, and the
    # measured harvest overhead is upper-bounded with absolute
    # headroom (it is a wall-clock ratio on whatever host ran the
    # bench, but the 5% budget is part of the contract). Span/arrow
    # counts may evolve with routing policy (loose); the per-link
    # wire percentiles are wall clock and deliberately NOT gated.
    "fabric_obs.deterministic": Tolerance("higher", rel=0.0),
    "fabric_obs.harvest_digest_invariant": Tolerance("higher",
                                                     rel=0.0),
    "fabric_obs.timeline_valid": Tolerance("higher", rel=0.0),
    "fabric_obs.postmortem_has_telemetry": Tolerance("higher",
                                                     rel=0.0),
    "fabric_obs.chaos_ok": Tolerance("higher", rel=0.0),
    "fabric_obs.invariants_ok": Tolerance("higher", rel=0.0),
    "fabric_obs.violations": Tolerance("lower", rel=0.0),
    "fabric_obs.harvest_failures": Tolerance("lower", rel=0.0),
    "fabric_obs.harvest_overhead_fraction":
        Tolerance("lower", rel=0.0, abs=0.05),
    "fabric_obs.worker_rows": Tolerance("higher", rel=0.0),
    "fabric_obs.worker_spans": Tolerance("higher", rel=0.50),
    "fabric_obs.cross_worker_arrows": Tolerance("higher", rel=0.50),
    # elastic autoscaling (ISSUE 19): the control loop must beat the
    # equal-peak static fleet on cost WITHOUT giving up SLO
    # attainment, deterministically, with every scale event
    # span-verified and every scale-fault recovered — all of that is
    # a hard boolean gate plus exactly-zero violations. Attainment
    # itself gets a little slack (trace/policy evolution), the
    # cost-savings fraction more (it moves with the control policy),
    # and the raw step costs / event counts are informational
    # trajectory (loose).
    "autoscale.deterministic": Tolerance("higher", rel=0.0),
    "autoscale.slo_vs_static_ok": Tolerance("higher", rel=0.0),
    "autoscale.cost_vs_static_ok": Tolerance("higher", rel=0.0),
    "autoscale.scale_events_span_verified": Tolerance("higher",
                                                      rel=0.0),
    "autoscale.chaos_deterministic": Tolerance("higher", rel=0.0),
    "autoscale.chaos_invariants_ok": Tolerance("higher", rel=0.0),
    "autoscale.process_ok": Tolerance("higher", rel=0.0),
    "autoscale.trace_connected": Tolerance("higher", rel=0.0),
    "autoscale.invariants_ok": Tolerance("higher", rel=0.0),
    "autoscale.violations": Tolerance("lower", rel=0.0),
    "autoscale.slo_attainment": Tolerance("higher", rel=0.05),
    "autoscale.cost_savings_fraction": Tolerance("higher", rel=0.25),
    "autoscale.cost_replica_steps": Tolerance("lower", rel=0.50),
    "autoscale.scale_ups": Tolerance("higher", rel=0.50),
    "autoscale.retires_completed": Tolerance("higher", rel=0.50),
    "autoscale.flaps": Tolerance("lower", rel=0.0, abs=2.0),
    # causal request tracing (CPU-deterministic; the booleans are hard
    # gates, the closure residual has an absolute bar — attribution
    # must sum to measured E2E within 1% regardless of baseline)
    "request_trace.dag_connected": Tolerance("higher", rel=0.0),
    "request_trace.closure_ok": Tolerance("higher", rel=0.0),
    "request_trace.deterministic": Tolerance("higher", rel=0.0),
    "request_trace.flight_deterministic": Tolerance("higher", rel=0.0),
    "request_trace.closure_max_residual":
        Tolerance("lower", rel=0.0, abs=0.01),
    "request_trace.violations": Tolerance("lower", rel=0.0),
    # the headline p99-TTFT attribution keys: which stage owns the
    # tail. Scheduler-policy evolution legitimately moves these, so
    # wide slack — what must not happen silently is the queue/prefill
    # share of the p99 TTFT exploding
    "request_trace.ttft_attr_queue_p99_s":
        Tolerance("lower", rel=0.50, abs=0.05),
    "request_trace.ttft_attr_prefill_p99_s":
        Tolerance("lower", rel=0.50, abs=0.05),
    # freshness alarm (ROADMAP item 5): informational headline — the
    # gate never fails on it (direction "lower" but compared via the
    # freshness block, not check_points)
}


@dataclass
class Verdict:
    metric: str
    status: str                  # "ok" | "regression" | "improved" | \
    #                              "no-baseline"
    new_value: float
    baseline: Optional[float] = None
    baseline_file: str = ""
    limit: Optional[float] = None
    detail: str = ""

    def to_json(self) -> Dict:
        out = {"metric": self.metric, "status": self.status,
               "new_value": self.new_value}
        if self.baseline is not None:
            out["baseline"] = self.baseline
            out["baseline_file"] = self.baseline_file
        if self.limit is not None:
            out["limit"] = round(self.limit, 6)
        if self.detail:
            out["detail"] = self.detail
        return out


def _limit(baseline: float, tol: Tolerance) -> float:
    slack = abs(baseline) * tol.rel + tol.abs
    return baseline - slack if tol.direction == "higher" \
        else baseline + slack


def check_points(points: List[MetricPoint],
                 baseline_index: Dict) -> List[Verdict]:
    """Compare fresh points against the baseline index headline. Only
    headline metrics produce verdicts; multiple fresh points for one
    metric are each checked (worst wins the summary)."""
    headline = baseline_index.get("headline", {})
    verdicts: List[Verdict] = []
    for p in points:
        tol = TOLERANCES.get(p.metric)
        if tol is None:
            continue
        base = headline.get(p.metric)
        if base is None:
            verdicts.append(Verdict(p.metric, "no-baseline", p.value))
            continue
        # like-for-like only: a point measured on a different config /
        # workload than the headline is a different program, not a
        # regression candidate (vet runs of 7B-layer shapes must not
        # "regress" the 350m headline)
        bcfg = (base.get("tags") or {}).get("config")
        pcfg = p.tags.get("config")
        if bcfg and pcfg and bcfg != pcfg:
            continue
        limit = _limit(base["value"], tol)
        if tol.direction == "higher":
            bad = p.value < limit
            better = p.value > base["value"]
        else:
            bad = p.value > limit
            better = p.value < base["value"]
        status = "regression" if bad else (
            "improved" if better else "ok")
        detail = ""
        if bad:
            detail = (f"{p.value} vs baseline {base['value']} "
                      f"({base['file']}), limit {round(limit, 6)} "
                      f"[{tol.direction} is better]")
        verdicts.append(Verdict(p.metric, status, p.value,
                                baseline=base["value"],
                                baseline_file=base["file"],
                                limit=limit, detail=detail))
    return verdicts


def regressions(verdicts: List[Verdict]) -> List[Verdict]:
    return [v for v in verdicts if v.status == "regression"]


def check_headline(fresh_index: Dict,
                   baseline_index: Dict) -> List[Verdict]:
    """The repo-level gate: rebuild the index from the working tree
    and require every gated headline metric to still reach the
    committed baseline's headline (within tolerance). History is not
    re-judged — old rounds stay old rounds; what must not happen is
    the *best committed evidence* for a metric getting worse (an
    artifact regenerated with a worse number, or deleted so a worse
    one becomes the best)."""
    base_head = baseline_index.get("headline", {})
    fresh_head = fresh_index.get("headline", {})
    verdicts: List[Verdict] = []
    for metric, base in base_head.items():
        tol = TOLERANCES.get(metric)
        if tol is None:
            continue
        fresh = fresh_head.get(metric)
        if fresh is None:
            verdicts.append(Verdict(
                metric, "regression", float("nan"),
                baseline=base["value"], baseline_file=base["file"],
                detail=f"headline metric vanished from the tree "
                       f"(was {base['value']} in {base['file']})"))
            continue
        limit = _limit(base["value"], tol)
        if tol.direction == "higher":
            bad = fresh["value"] < limit
            better = fresh["value"] > base["value"]
        else:
            bad = fresh["value"] > limit
            better = fresh["value"] < base["value"]
        status = "regression" if bad else (
            "improved" if better else "ok")
        detail = ""
        if bad:
            detail = (f"tree headline {fresh['value']} "
                      f"({fresh['file']}) vs committed "
                      f"{base['value']} ({base['file']}), limit "
                      f"{round(limit, 6)} [{tol.direction} is better]")
        verdicts.append(Verdict(metric, status, fresh["value"],
                                baseline=base["value"],
                                baseline_file=base["file"],
                                limit=limit, detail=detail))
    return verdicts


def check_artifact(path: str,
                   baseline_index: Dict) -> List[Verdict]:
    """Parse ``path`` with its registry schema and gate it."""
    from .schemas import parse_artifact
    parsed = parse_artifact(path, os.path.basename(path))
    return check_points(parsed.points, baseline_index)


def self_check_rows(filename: str, rows: List[Dict],
                    root: Optional[str] = None) -> Dict:
    """The bench hook: parse ``rows`` (the artifact about to be
    written) through ``filename``'s family schema and compare against
    the committed index. Returns a JSON-safe summary row the bench
    appends to its artifact; never raises and never blocks the write —
    a bench run's job is to record evidence, the CLI gate's job is to
    fail on it."""
    from .registry import INDEX_NAME, load_index, repo_root
    from .schemas import classify
    try:
        root = root or repo_root()
    except FileNotFoundError:
        return {"phase": "perf-check", "skipped": "no repo root"}
    fam = classify(os.path.basename(filename))
    if fam is None:
        return {"phase": "perf-check",
                "skipped": f"no schema for {filename}"}
    try:
        baseline = load_index(root=root)
    except (OSError, json.JSONDecodeError) as exc:
        return {"phase": "perf-check",
                "skipped": f"no committed {INDEX_NAME}: {exc}"}
    text = "\n".join(json.dumps(r) for r in rows)
    try:
        points = fam.parser(text, os.path.basename(filename))
        verdicts = check_points(points, baseline)
    except Exception as exc:   # noqa: BLE001 — evidence first
        return {"phase": "perf-check", "skipped": f"parse: {exc!r}"}
    regs = regressions(verdicts)
    return {
        "phase": "perf-check",
        "against": INDEX_NAME,
        "baseline_generated_utc": baseline.get("generated_utc"),
        "checked": len(verdicts),
        "regressions": [v.to_json() for v in regs],
        "ok": not regs,
    }


# ----------------------------------------------------------------- #
def self_test(verbose: bool = False) -> bool:
    """Prove the gate trips: build a synthetic baseline index, a
    matching fresh artifact, then regress one headline metric per
    direction and assert the verdicts flip. Pure CPU, no chip, no
    repo state — runs inside tier-1."""
    baseline = {
        "headline": {
            "train.tokens_per_sec_per_chip": {
                "value": 50000.0, "file": "BENCH_FRESH.json",
                "direction": "higher", "rel_tolerance": 0.10,
                "abs_tolerance": 0.0},
            "zero_overlap.qrs_wire_fraction_of_fp32": {
                "value": 0.33, "file": "ZERO_OVERLAP.jsonl",
                "direction": "lower", "rel_tolerance": 0.05,
                "abs_tolerance": 0.0},
            "chaos.deterministic": {
                "value": 1.0, "file": "CHAOS_SERVE.jsonl",
                "direction": "higher", "rel_tolerance": 0.0,
                "abs_tolerance": 0.0},
        }
    }
    ok_points = [
        MetricPoint("train.tokens_per_sec_per_chip", 49000.0, "new"),
        MetricPoint("zero_overlap.qrs_wire_fraction_of_fp32", 0.32,
                    "new"),
        MetricPoint("chaos.deterministic", 1.0, "new"),
    ]
    bad_points = [
        MetricPoint("train.tokens_per_sec_per_chip", 40000.0, "new"),
        MetricPoint("zero_overlap.qrs_wire_fraction_of_fp32", 0.50,
                    "new"),
        MetricPoint("chaos.deterministic", 0.0, "new"),
    ]
    ok_verdicts = check_points(ok_points, baseline)
    bad_verdicts = check_points(bad_points, baseline)
    checks = [
        (not regressions(ok_verdicts),
         "within-tolerance points must pass"),
        (len(regressions(bad_verdicts)) == 3,
         "all three synthetic regressions must trip"),
        (all(v.status == "regression" for v in bad_verdicts),
         "every regressed point gets a regression verdict"),
    ]
    # round-trip through a real file + the artifact path
    with tempfile.TemporaryDirectory() as tmp:
        art = os.path.join(tmp, "CHAOS_SERVE.jsonl")
        with open(art, "w") as fh:
            fh.write(json.dumps(
                {"phase": "chaos-summary", "deterministic": False,
                 "invariants_ok": True, "violations": []}) + "\n")
        file_verdicts = check_artifact(art, baseline)
        checks.append(
            (any(v.status == "regression" and
                 v.metric == "chaos.deterministic"
                 for v in file_verdicts),
             "file-based check must catch the regressed boolean"))
    passed = all(ok for ok, _ in checks)
    if verbose or not passed:
        for ok, what in checks:
            print(f"[perf self-test] {'PASS' if ok else 'FAIL'}: "
                  f"{what}")
    return passed


def freshness_alarm(index: Dict, max_age_days: float = 2.0) -> Optional[str]:
    """The wedged-relay gauge as a check: returns a message when the
    last real chip measurement is older than ``max_age_days`` (never a
    hard failure — the relay being down is an environment fact, not a
    code regression)."""
    fr = index.get("freshness", {})
    age = fr.get("staleness_days")
    if age is None:
        return "no timestamped chip measurement indexed"
    if age > max_age_days:
        return (f"last chip measurement "
                f"{fr.get('last_chip_measurement_utc')} is "
                f"{age:.1f} days old (> {max_age_days:g}d): relay "
                "wedged? (ROADMAP item 5)")
    return None
