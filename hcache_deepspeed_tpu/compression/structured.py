"""Structured compression library: sparse / row / head / channel
pruning, staged weight quantization, activation quantization, and layer
reduction — driven by the reference's ``compression_training`` config
block and exposed through ``init_compression`` / ``apply_compression`` /
``redundancy_clean`` (the reference's ``compress.py`` entry points).

Reference analogs (``/root/reference/deepspeed/compression/``):
* ``compress.py:102`` ``init_compression`` — module surgery replacing
  Linear/Conv2d with ``*_Compress`` layers; ``compress.py:148``
  ``redundancy_clean`` — mask baking + dimension reduction;
  ``compress.py:193`` ``student_initialization`` (layer reduction).
* ``basic_layer.py:121-430`` ``LinearLayer_Compress`` — per-module mask
  buffers/score parameters and the masked+quantized forward.
* ``scheduler.py`` ``compression_scheduler`` — step-offset gating.
* ``config.py`` / ``constants.py`` — the JSON schema re-used verbatim.

TPU re-design — no module surgery, no mutation:
* A **pure pytree transform**: ``apply_compression(params, comp, step)``
  rewrites matched kernels inside the jitted train step. Masks are
  arrays carried beside the params; schedule gating is
  ``jnp.where(step >= offset, ...)`` so one compiled step serves the
  whole schedule (no retrace at the enable boundary).
* ``topk`` methods learn mask scores by gradient. Scores live in a
  reserved ``_compression_scores`` subtree **inside** the params pytree,
  so any optimizer trains them with zero plumbing; a straight-through
  top-k binarizer (`TopKBinarizer` in the reference, ``utils.py:29``)
  turns scores into {0,1} masks at apply time.
* Mask fixing (``redundancy_clean``) is a one-time host-side pytree
  rewrite: bake masks into weights, and — when a group declares
  ``related_modules`` — physically slice the pruned axis out of both
  sides (flax kernels are ``(in, out)``: row pruning slices F1's out
  axis and the related F2's in axis; head pruning slices the attention
  out-projection's head-grouped in axis and the related QKV's out axis).
* Activation quantization uses ``flax.linen.intercept_methods`` — the
  functional analog of the reference's forward hook — to fake-quantize
  the inputs of matched Dense modules; trace-time interception, so XLA
  fuses the quantize into the surrounding matmul.
* Layer reduction gathers teacher layer subtrees (or an index gather on
  the layer axis for scan-stacked models) — ``student_initialization``.
"""

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .quantize import fake_quantize

# reserved params subtree for learnable topk mask scores
SCORES_KEY = "_compression_scores"

# techniques, in the reference's redundancy_clean fix order
# (compress.py:168)
WEIGHT_QUANTIZATION = "weight_quantization"
SPARSE_PRUNING = "sparse_pruning"
ROW_PRUNING = "row_pruning"
HEAD_PRUNING = "head_pruning"
CHANNEL_PRUNING = "channel_pruning"
ACTIVATION_QUANTIZATION = "activation_quantization"
LAYER_REDUCTION = "layer_reduction"
TECHNIQUES = (WEIGHT_QUANTIZATION, SPARSE_PRUNING, ROW_PRUNING,
              HEAD_PRUNING, CHANNEL_PRUNING, ACTIVATION_QUANTIZATION)

_SHARED_DEFAULTS = {
    WEIGHT_QUANTIZATION: dict(enabled=False, schedule_offset=0,
                              quantizer_kernel=False, quantize_verbose=False,
                              quantization_type="symmetric", rounding="nearest",
                              quantize_weight_in_forward=True,
                              fp16_mixed_quantize=False,
                              quantize_change_ratio=0.001),
    ACTIVATION_QUANTIZATION: dict(enabled=False, schedule_offset=0,
                                  quantization_type="symmetric",
                                  range_calibration="dynamic"),
    SPARSE_PRUNING: dict(enabled=False, schedule_offset=0,
                         schedule_offset_end=None, method="l1"),
    ROW_PRUNING: dict(enabled=False, schedule_offset=0, method="l1"),
    HEAD_PRUNING: dict(enabled=False, schedule_offset=0, method="topk",
                       num_heads=None),
    CHANNEL_PRUNING: dict(enabled=False, schedule_offset=0, method="l1"),
}


class CompressionError(ValueError):
    pass


@dataclass(frozen=True)
class GroupSpec:
    """One ``different_groups`` entry after regex resolution."""
    name: str
    method: str                    # l1 | topk (pruning) / quant params
    params: Dict[str, Any]         # merged group params + shared
    modules: Tuple[str, ...]       # resolved kernel-bearing module paths
    related: Tuple[Tuple[str, ...], ...] = ()  # per-module related paths


@dataclass(frozen=True)
class TechniqueSpec:
    enabled: bool
    schedule_offset: int
    schedule_offset_end: Optional[int]
    groups: Tuple[GroupSpec, ...]
    shared: Dict[str, Any] = field(default_factory=dict)


def get_compression_config(ds_config: Dict[str, Any]) -> Dict[str, Any]:
    """Normalize a config dict's ``compression_training`` block with the
    reference's keys and defaults (reference: compression/config.py)."""
    block = dict(ds_config.get("compression_training") or {})
    out: Dict[str, Any] = {}
    for tech in TECHNIQUES:
        sub = dict(block.get(tech) or {})
        shared = dict(_SHARED_DEFAULTS[tech])
        shared.update(sub.get("shared_parameters") or {})
        groups = {}
        for gname, g in (sub.get("different_groups") or {}).items():
            g = dict(g)
            scope = g.get("modules", ["*"])
            if isinstance(scope, str):
                scope = [scope]
            related = g.get("related_modules") or []
            groups[gname] = {
                "params": dict(g.get("params") or {}),
                "modules": list(scope),
                "related_modules": [list(r) if isinstance(r, (list, tuple))
                                    else [r] for r in related],
            }
        out[tech] = {"shared_parameters": shared,
                     "different_groups": groups}
    lr = dict(block.get(LAYER_REDUCTION) or {})
    lr.setdefault("enabled", False)
    out[LAYER_REDUCTION] = lr
    return out


# ------------------------------------------------------------------ #
# module resolution over the params pytree
# ------------------------------------------------------------------ #

def _module_paths(params) -> List[str]:
    """Kernel-bearing module paths, '/'-joined (e.g. ``h_0/mlp/c_fc``) —
    the pytree analog of ``model.named_modules()`` filtered by
    ``is_module_compressible`` (helper.py:303)."""
    paths = []

    def walk(node, prefix):
        if not isinstance(node, dict):
            return
        if "kernel" in node or "embedding" in node:
            paths.append("/".join(prefix))
            return
        for k in sorted(node.keys()):
            walk(node[k], prefix + [k])

    walk(_as_dict(params), [])
    return paths


def _as_dict(tree):
    # FrozenDict (older flax) or plain dict
    return tree.unfreeze() if hasattr(tree, "unfreeze") else tree


def _get_path(params, path: str):
    node = _as_dict(params)
    for k in path.split("/"):
        node = node[k]
    return node


def _set_path(params, path: str, value):
    """Functional set: returns a new tree with ``path`` replaced."""
    params = dict(_as_dict(params))
    keys = path.split("/")
    node = params
    for k in keys[:-1]:
        node[k] = dict(_as_dict(node[k]))
        node = node[k]
    node[keys[-1]] = value
    return params


def _match(pattern: str, path: str) -> bool:
    """``re.search`` over both '/'-joined and '.'-joined spellings (the
    reference's named_modules use dots — compress.py:35)."""
    if pattern == "*":
        return True
    dotted = path.replace("/", ".")
    try:
        return (re.search(pattern, path) is not None
                or re.search(pattern, dotted) is not None)
    except re.error as e:
        raise CompressionError(f"bad module scope regex {pattern!r}: {e}")


def _resolve_groups(cfg_tech: Dict[str, Any], method_key: str,
                    paths: List[str], tech: str) -> Tuple[GroupSpec, ...]:
    shared = cfg_tech["shared_parameters"]
    groups = []
    claimed: Dict[str, str] = {}
    for gname, g in cfg_tech["different_groups"].items():
        mods, related = [], []
        for pat in g["modules"]:
            hits = [p for p in paths if _match(pat, p)]
            for p in hits:
                if claimed.get(p, gname) != gname:
                    raise CompressionError(
                        f"{p} matched by both {claimed[p]!r} and "
                        f"{gname!r} for {tech} — check the config scopes")
                claimed[p] = gname
                # overlapping patterns WITHIN a group are fine, but the
                # technique must apply once
                if p not in mods:
                    mods.append(p)
        for rel_pats in g["related_modules"]:
            rel_hits: List[str] = []
            for rp in rel_pats:
                rel_hits.extend(p for p in paths if _match(rp, p))
            related.append(tuple(rel_hits))
        merged = dict(shared)
        merged.update(g["params"])
        groups.append(GroupSpec(
            name=gname,
            method=str(merged.get(method_key, shared.get(method_key, "l1"))),
            params=merged,
            modules=tuple(mods),
            related=tuple(related)))
    return tuple(groups)


# ------------------------------------------------------------------ #
# state
# ------------------------------------------------------------------ #

@dataclass
class CompressionState:
    """Static spec + mask buffers. ``masks`` maps ``method::path`` to an
    ndarray mask (l1 methods); ``topk`` masks are recomputed each step
    from the learnable scores the ``init`` injected into
    ``params[_compression_scores]``. The whole object is host-side
    static except ``masks``, which the engine threads through the jitted
    step like any other array argument. ``act_ranges`` holds calibrated
    (lo, hi) activation ranges per module for the ``static``
    range-calibration mode (reference ``QuantAct`` running min/max)."""
    spec: Dict[str, TechniqueSpec]
    masks: Dict[str, jnp.ndarray]
    num_heads: Dict[str, int]      # head-pruned path -> head count
    wq_bits_path: Dict[str, Tuple[int, ...]]  # path -> bit staircase
    wq_groups_path: Dict[str, int]
    wq_offset: int = 0
    act_ranges: Dict[str, Tuple[float, float]] = field(
        default_factory=dict)

    def enabled(self, tech: str) -> bool:
        t = self.spec.get(tech)
        return bool(t and t.enabled and t.groups)


def _skey(method: str, path: str) -> str:
    # flax module names cannot contain '/', so keep it as the separator
    return f"{method}::{path}"


def _topk_mask(scores, dense_ratio):
    """Straight-through top-k binarizer (reference utils.py:29
    ``TopKBinarizer``): hard {0,1} mask forward, identity gradient."""
    flat = scores.reshape(-1)
    k = max(int(round(flat.size * float(dense_ratio))), 1)
    kth = jnp.sort(flat)[flat.size - k]
    hard = (flat >= kth).astype(scores.dtype).reshape(scores.shape)
    return hard + scores - jax.lax.stop_gradient(scores)


def _l1_sparse_mask(w, dense_ratio) -> np.ndarray:
    a = np.abs(np.asarray(jax.device_get(w), np.float32)).reshape(-1)
    k = max(int(round(a.size * float(dense_ratio))), 1)
    kth = np.sort(a)[a.size - k]
    return (a >= kth).astype(np.float32).reshape(w.shape)


def _l1_axis_mask(w, dense_ratio, axis) -> np.ndarray:
    a = np.asarray(jax.device_get(w), np.float32)
    other = tuple(i for i in range(a.ndim) if i != axis)
    norms = np.abs(a).sum(axis=other)
    k = max(int(round(norms.size * float(dense_ratio))), 1)
    kth = np.sort(norms)[norms.size - k]
    return (norms >= kth).astype(np.float32)


def _wq_staircase(start_bits: int, target_bits: int,
                  horizon: int = 64) -> Tuple[int, ...]:
    """The MoQ bit staircase as a static table indexed by
    ``(step - offset) // period`` (see quantize.QuantizeScheduler)."""
    bits, stair = start_bits, [start_bits]
    for _ in range(horizon):
        if bits <= target_bits:
            break
        bits = max(bits - max((bits - target_bits + 1) // 2, 1),
                   target_bits)
        stair.append(bits)
    return tuple(stair)


def _wq_period(params: Dict[str, Any]) -> int:
    return max(int(params.get("quantization_period",
                              params.get("q_period", 1))), 1)


def init_compression(params, ds_config: Dict[str, Any],
                     rng: Optional[jax.Array] = None
                     ) -> Tuple[Any, CompressionState]:
    """Resolve the config against the params pytree; compute l1 masks
    from the current weights (the reference computes them at
    ``compression_preparation`` time from the module's weights —
    basic_layer.py:152) and inject learnable ``topk`` scores into
    ``params[_compression_scores]``. Returns ``(params', state)``."""
    cfg = get_compression_config(ds_config)
    paths = _module_paths(params)
    spec: Dict[str, TechniqueSpec] = {}
    masks: Dict[str, jnp.ndarray] = {}
    num_heads: Dict[str, int] = {}
    wq_bits_path: Dict[str, Tuple[int, ...]] = {}
    wq_groups_path: Dict[str, int] = {}
    scores: Dict[str, jnp.ndarray] = {}
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    method_key = {SPARSE_PRUNING: "method", ROW_PRUNING: "method",
                  HEAD_PRUNING: "method", CHANNEL_PRUNING: "method",
                  WEIGHT_QUANTIZATION: "quantization_type",
                  ACTIVATION_QUANTIZATION: "quantization_type"}

    for tech in TECHNIQUES:
        shared = cfg[tech]["shared_parameters"]
        groups = _resolve_groups(cfg[tech], method_key[tech], paths, tech)
        spec[tech] = TechniqueSpec(
            enabled=bool(shared["enabled"]),
            schedule_offset=int(shared.get("schedule_offset") or 0),
            schedule_offset_end=(int(shared["schedule_offset_end"])
                                 if shared.get("schedule_offset_end")
                                 is not None else None),
            groups=groups,
            shared=shared)
        if not spec[tech].enabled:
            continue
        for g in groups:
            for path in g.modules:
                node = _get_path(params, path)
                w = node.get("kernel", node.get("embedding"))
                if tech == SPARSE_PRUNING:
                    ratio = g.params.get("dense_ratio", 0.5)
                    if g.method == "l1":
                        masks[_skey("sparse", path)] = jnp.asarray(
                            _l1_sparse_mask(w, ratio))
                    elif g.method == "topk":
                        rng, sub = jax.random.split(rng)
                        scores[_skey("sparse", path)] = (
                            jax.random.normal(sub, w.shape, jnp.float32)
                            * 0.01)
                    else:
                        raise CompressionError(
                            f"sparse_pruning method {g.method!r} not "
                            "supported (l1 | topk)")
                elif tech in (ROW_PRUNING, CHANNEL_PRUNING):
                    # flax kernel (in, out): row pruning = output-neuron
                    # pruning = axis -1; channel pruning = input-channel
                    # pruning = axis 0 (conv NHWC kernel: axis 2)
                    axis = (w.ndim - 1) if tech == ROW_PRUNING else (
                        2 if w.ndim == 4 else 0)
                    key = _skey("row" if tech == ROW_PRUNING else "channel",
                                path)
                    ratio = g.params.get("dense_ratio", 0.5)
                    if g.method == "l1":
                        masks[key] = jnp.asarray(
                            _l1_axis_mask(w, ratio, axis))
                    elif g.method == "topk":
                        rng, sub = jax.random.split(rng)
                        scores[key] = jax.random.normal(
                            sub, (w.shape[axis],), jnp.float32) * 0.01
                    else:
                        raise CompressionError(
                            f"{tech} method {g.method!r} not supported")
                elif tech == HEAD_PRUNING:
                    if g.method != "topk":
                        raise CompressionError(
                            "head_pruning supports only the topk method "
                            "(reference basic_layer.py:195)")
                    heads = g.params.get("num_heads") or shared.get(
                        "num_heads")
                    if not heads:
                        raise CompressionError(
                            "head_pruning needs num_heads (shared or "
                            "group params)")
                    if w.shape[0] % int(heads):
                        raise CompressionError(
                            f"{path}: in-dim {w.shape[0]} not divisible "
                            f"by num_heads={heads}")
                    num_heads[path] = int(heads)
                    rng, sub = jax.random.split(rng)
                    scores[_skey("head", path)] = jax.random.normal(
                        sub, (int(heads),), jnp.float32) * 0.01
                elif tech == WEIGHT_QUANTIZATION:
                    wq_bits_path[path] = _wq_staircase(
                        int(g.params.get("start_bits", 16)),
                        int(g.params.get("target_bits", 8)))
                    wq_groups_path[path] = int(
                        g.params.get("quantize_groups", 1))

    state = CompressionState(
        spec=spec, masks=masks, num_heads=num_heads,
        wq_bits_path=wq_bits_path, wq_groups_path=wq_groups_path,
        wq_offset=spec[WEIGHT_QUANTIZATION].schedule_offset)
    if scores:
        params = dict(_as_dict(params))
        params[SCORES_KEY] = {**_as_dict(params.get(SCORES_KEY, {})),
                              **scores}
    return params, state


# ------------------------------------------------------------------ #
# traced application (inside the jitted step)
# ------------------------------------------------------------------ #

def _gate(step, offset, end, yes, no):
    on = step >= offset
    if end is not None:
        on = jnp.logical_and(on, step <= end)
    return jnp.where(on, yes, no)


def _apply_head_mask(w, mask):
    """(in, out) kernel, in = heads * head_dim."""
    h = mask.shape[0]
    return (w.reshape(h, -1, w.shape[-1])
            * mask[:, None, None].astype(w.dtype)).reshape(w.shape)


def apply_compression(params, comp: CompressionState, step,
                      masks: Optional[Dict[str, jnp.ndarray]] = None):
    """Pure, jit-safe: rewrite matched kernels with the step-gated
    compression pipeline in the reference forward's order
    (basic_layer.py:363-393: quantize → sparse → row → head). ``step``
    may be a traced scalar; ``masks`` overrides ``comp.masks`` so the
    engine can thread device-resident masks as step args."""
    masks = comp.masks if masks is None else masks
    scores = _as_dict(params).get(SCORES_KEY, {})
    step = jnp.asarray(step)

    def mask_for(key, group, axis_size=None):
        if key in masks:
            return masks[key]
        if key in scores:
            return _topk_mask(scores[key],
                              group.params.get("dense_ratio", 0.5))
        return None

    for tech, method, kind in ((WEIGHT_QUANTIZATION, None, "wq"),
                               (SPARSE_PRUNING, "sparse", "mask"),
                               (ROW_PRUNING, "row", "mask"),
                               (HEAD_PRUNING, "head", "mask"),
                               (CHANNEL_PRUNING, "channel", "mask")):
        t = comp.spec.get(tech)
        if not (t and t.enabled):
            continue
        for g in t.groups:
            for path in g.modules:
                node = dict(_as_dict(_get_path(params, path)))
                wname = "kernel" if "kernel" in node else "embedding"
                w = node[wname]
                if kind == "wq":
                    stair = comp.wq_bits_path[path]
                    period = _wq_period(g.params)
                    idx = jnp.clip((step - t.schedule_offset) // period,
                                   0, len(stair) - 1)
                    bits_now = jnp.take(jnp.asarray(stair), idx)
                    from .quantize import fake_quantize_traced
                    qw = fake_quantize_traced(
                        w, bits_now, groups=comp.wq_groups_path[path])
                    node[wname] = _gate(step, t.schedule_offset, None,
                                        qw, w)
                else:
                    m = mask_for(_skey(method, path), g)
                    if m is None:
                        continue
                    if method == "sparse":
                        mw = w * m.astype(w.dtype)
                    elif method == "row":
                        mw = w * m.astype(w.dtype)
                        mb = None
                        if "bias" in node:
                            mb = node["bias"] * m.astype(node["bias"].dtype)
                            node["bias"] = _gate(
                                step, t.schedule_offset,
                                t.schedule_offset_end, mb, node["bias"])
                    elif method == "head":
                        mw = _apply_head_mask(w, m)
                    else:  # channel: input axis
                        axis = 2 if w.ndim == 4 else 0
                        shape = [1] * w.ndim
                        shape[axis] = m.shape[0]
                        mw = w * m.reshape(shape).astype(w.dtype)
                    node[wname] = _gate(step, t.schedule_offset,
                                        t.schedule_offset_end, mw, w)
                params = _set_path(params, path, node)
    return params


# ------------------------------------------------------------------ #
# activation quantization (flax method interception)
# ------------------------------------------------------------------ #

def quantize_activation(x, bits: int, symmetric: bool = True,
                        static_range: Optional[Tuple[float, float]] = None):
    """Fake-quantize activations (reference basic_layer.py:355
    ``QuantAct`` / Sym/AsymQuantizer on the input). Dynamic range uses
    per-token groups like the reference (num_groups = numel // last);
    a static range quantizes symmetrically over ±max(|lo|,|hi|) or —
    asymmetric — over [lo, hi] with a zero offset (post-ReLU ranges
    would otherwise waste half the code space)."""
    if static_range is not None:
        lo, hi = float(static_range[0]), float(static_range[1])
        if hi <= lo or (lo == 0.0 and hi == 0.0):
            return x   # degenerate calibration: pass through, no /0
        if symmetric:
            qmax = 2.0 ** (bits - 1) - 1
            scale = max(abs(lo), abs(hi)) / qmax
            return jnp.clip(jnp.round(x / scale), -qmax - 1, qmax) * scale
        levels = 2.0 ** bits - 1
        scale = (hi - lo) / levels
        q = jnp.clip(jnp.round((x - lo) / scale), 0.0, levels)
        return q * scale + lo
    groups = max(x.size // x.shape[-1], 1) if x.ndim > 1 else 1
    return fake_quantize(x, bits, symmetric=symmetric, groups=groups)


def activation_interceptor(comp: CompressionState, step):
    """Build a ``flax.linen.intercept_methods`` interceptor that
    quantizes the first argument of matched modules' ``__call__`` —
    the functional analog of the reference's compressed forward
    (basic_layer.py:385-391)."""
    t = comp.spec.get(ACTIVATION_QUANTIZATION)
    targets: Dict[str, Any] = {}
    if t and t.enabled:
        for g in t.groups:
            for path in g.modules:
                targets[path] = g

    def interceptor(next_fun, args, kwargs, context):
        if context.method_name != "__call__" or not targets:
            return next_fun(*args, **kwargs)
        path = "/".join(context.module.path)
        g = targets.get(path)
        if g is None or not args:
            return next_fun(*args, **kwargs)
        bits = int(g.params.get("bits", 8))
        sym = g.params.get("quantization_type", "symmetric") == "symmetric"
        cal = g.params.get("range_calibration",
                           t.shared.get("range_calibration", "dynamic"))
        rng = None
        if cal == "static":
            # calibrated range (reference QuantAct running min/max —
            # run calibrate_activation_ranges BEFORE the first compiled
            # step: the range is a trace-time constant inside jit, so
            # later calibration cannot take effect without a retrace);
            # an explicit group-level static_range overrides
            explicit = g.params.get("static_range")
            calibrated = comp.act_ranges.get(path)
            if explicit is not None:
                rng = tuple(explicit)
            elif calibrated is not None:
                rng = tuple(calibrated)
            else:
                from ..utils.logging import warning_once
                warning_once(
                    f"activation_quantization: static range for {path} "
                    "was never calibrated (run "
                    "calibrate_activation_ranges) — falling back to "
                    "(-1, 1), which clips anything larger")
                rng = (-1.0, 1.0)
        qx = quantize_activation(args[0], bits, symmetric=sym,
                                 static_range=rng)
        x = jnp.where(jnp.asarray(step) >= t.schedule_offset, qx, args[0])
        return next_fun(x, *args[1:], **kwargs)

    return interceptor


def calibrate_activation_ranges(apply_fn, comp: CompressionState,
                                batches, momentum: float = 0.95
                                ) -> CompressionState:
    """Run ``apply_fn(batch)`` (a model forward under
    ``flax.linen.intercept_methods`` supplied here) over calibration
    ``batches``, tracking a momentum-smoothed min/max of each
    STATIC-calibrated module's input — the reference ``QuantAct``
    calibration (basic_layer.py:355) done as an offline pass. Fills
    ``comp.act_ranges`` in place and returns ``comp``.

    Run this BEFORE the first compiled train/eval step: the interceptor
    reads the ranges at trace time, so mutations after the first jit
    compile do not take effect (build a fresh engine to re-calibrate)."""
    import flax.linen as fnn

    t = comp.spec.get(ACTIVATION_QUANTIZATION)
    targets = set()
    if t and t.enabled:
        for g in t.groups:
            cal = g.params.get("range_calibration",
                               t.shared.get("range_calibration",
                                            "dynamic"))
            if cal == "static":
                targets.update(g.modules)
    if not targets:
        return comp

    def recorder(next_fun, args, kwargs, context):
        if context.method_name == "__call__" and args:
            path = "/".join(context.module.path)
            if path in targets:
                x = np.asarray(jax.device_get(args[0]), np.float32)
                lo, hi = float(x.min()), float(x.max())
                prev = comp.act_ranges.get(path)
                if prev is None:
                    comp.act_ranges[path] = (lo, hi)
                else:
                    m = momentum
                    comp.act_ranges[path] = (
                        m * prev[0] + (1 - m) * lo,
                        m * prev[1] + (1 - m) * hi)
        return next_fun(*args, **kwargs)

    for batch in batches:
        with fnn.intercept_methods(recorder):
            apply_fn(batch)
    return comp


# ------------------------------------------------------------------ #
# mask fixing / dimension reduction (redundancy_clean)
# ------------------------------------------------------------------ #

def _concrete_mask(comp, params, method, path, group) -> Optional[np.ndarray]:
    key = _skey(method, path)
    if key in comp.masks:
        return np.asarray(jax.device_get(comp.masks[key]))
    scores = _as_dict(params).get(SCORES_KEY, {})
    if key in scores:
        return np.asarray(jax.device_get(
            _topk_mask(scores[key], group.params.get("dense_ratio", 0.5))))
    return None


def fix_compression(params, comp: CompressionState,
                    dim_reduction: bool = False):
    """Bake every enabled technique's masks/quantization into the
    weights (the reference's per-module ``fix_*_helper`` family), then
    drop the learnable scores. With ``dim_reduction`` row/head-pruned
    axes are physically sliced — including each group's
    ``related_modules`` — so the exported tree is genuinely smaller.
    Returns ``(params, dims)`` where ``dims[path]`` reports
    ``{"axis": int, "keep": int}`` for every sliced module."""
    params = jax.tree.map(np.asarray, _as_dict(params))
    dims: Dict[str, Dict[str, int]] = {}

    # 1. weight quantization at target bits (fix_weight_quantization)
    wq = comp.spec.get(WEIGHT_QUANTIZATION)
    if wq and wq.enabled:
        for g in wq.groups:
            for path in g.modules:
                node = dict(_get_path(params, path))
                wname = "kernel" if "kernel" in node else "embedding"
                node[wname] = np.asarray(fake_quantize(
                    jnp.asarray(node[wname]),
                    int(g.params.get("target_bits", 8)),
                    symmetric=g.params.get(
                        "quantization_type", "symmetric") == "symmetric",
                    groups=comp.wq_groups_path.get(path, 1)))
                params = _set_path(params, path, node)

    # 2. sparse masks (fix_sparse_pruning_helper)
    sp = comp.spec.get(SPARSE_PRUNING)
    if sp and sp.enabled:
        for g in sp.groups:
            for path in g.modules:
                m = _concrete_mask(comp, params, "sparse", path, g)
                if m is None:
                    continue
                node = dict(_get_path(params, path))
                wname = "kernel" if "kernel" in node else "embedding"
                node[wname] = node[wname] * m.astype(node[wname].dtype)
                params = _set_path(params, path, node)

    # 3/4. row + head pruning (fix_row_col_pruning_helper /
    # fix_head_pruning_helper), with related-module slicing
    for tech, method in ((ROW_PRUNING, "row"), (HEAD_PRUNING, "head"),
                         (CHANNEL_PRUNING, "channel")):
        t = comp.spec.get(tech)
        if not (t and t.enabled):
            continue
        for g in t.groups:
            for i, path in enumerate(g.modules):
                m = _concrete_mask(comp, params, method, path, g)
                if m is None:
                    continue
                keep = np.flatnonzero(m > 0.5)
                node = dict(_get_path(params, path))
                wname = "kernel" if "kernel" in node else "embedding"
                w = node[wname]
                if method == "row":
                    if dim_reduction and g.related:
                        node[wname] = w[:, keep]
                        if "bias" in node:
                            node["bias"] = node["bias"][keep]
                        dims[path] = {"axis": w.ndim - 1,
                                      "keep": int(keep.size)}
                    else:
                        node[wname] = w * m.astype(w.dtype)
                        if "bias" in node:
                            node["bias"] = node["bias"] * m.astype(
                                node["bias"].dtype)
                elif method == "head":
                    heads = comp.num_heads[path]
                    hd = w.shape[0] // heads
                    # slice only when THIS group declared related
                    # modules (the QKV side must shrink in lockstep);
                    # a bare head group masks, same as row/channel
                    if dim_reduction and g.related:
                        wk = w.reshape(heads, hd, -1)[keep].reshape(
                            -1, w.shape[-1])
                        node[wname] = wk
                        dims[path] = {"axis": 0, "keep": int(keep.size * hd),
                                      "heads": int(keep.size)}
                    else:
                        node[wname] = np.asarray(_apply_head_mask(
                            jnp.asarray(w), jnp.asarray(m)))
                else:  # channel
                    axis = 2 if w.ndim == 4 else 0
                    if dim_reduction and g.related:
                        node[wname] = np.take(w, keep, axis=axis)
                        dims[path] = {"axis": axis, "keep": int(keep.size)}
                    else:
                        shape = [1] * w.ndim
                        shape[axis] = m.shape[0]
                        node[wname] = w * m.reshape(shape).astype(w.dtype)
                params = _set_path(params, path, node)
                # related modules lose the matching input/output slice;
                # pair each pruned module with the related paths that
                # share its parent subtree (same layer), falling back to
                # all matches (the reference pairs by config order —
                # compress.py:64-79 — which the per-layer regex expansion
                # makes positional; parent pairing is the same mapping
                # expressed structurally)
                if dim_reduction and g.related:
                    parent = path.rsplit("/", 1)[0]
                    rel_all = [r for rr in g.related for r in rr]
                    rel = [r for r in rel_all
                           if r.rsplit("/", 1)[0] == parent] or rel_all
                    for rpath in rel:
                        rnode = dict(_get_path(params, rpath))
                        rwname = ("kernel" if "kernel" in rnode
                                  else "embedding")
                        rw = rnode[rwname]
                        if method == "row":
                            # F1 out-slice -> F2 in-slice (axis 0)
                            rnode[rwname] = rw[keep, :]
                            dims[rpath] = {"axis": 0,
                                           "keep": int(keep.size)}
                        elif method == "head":
                            # attn out-proj head slice -> fused QKV out
                            # slice: kernel (C, 3*heads*hd), slice the
                            # kept heads out of each of q, k, v
                            heads = comp.num_heads[path]
                            hd = rw.shape[-1] // 3 // heads
                            three = rw.reshape(rw.shape[0], 3, heads, hd)
                            rnode["kernel"] = three[:, :, keep, :].reshape(
                                rw.shape[0], -1)
                            if "bias" in rnode:
                                b = rnode["bias"].reshape(3, heads, hd)
                                rnode["bias"] = b[:, keep, :].reshape(-1)
                            dims[rpath] = {"axis": rw.ndim - 1,
                                           "keep": int(keep.size * hd * 3),
                                           "heads": int(keep.size)}
                        else:   # channel: upstream loses output slices
                            rnode[rwname] = np.take(rw, keep,
                                                    axis=rw.ndim - 1)
                            if "bias" in rnode:
                                rnode["bias"] = rnode["bias"][keep]
                            dims[rpath] = {"axis": rw.ndim - 1,
                                           "keep": int(keep.size)}
                        params = _set_path(params, rpath, rnode)

    params.pop(SCORES_KEY, None)
    return params, dims


def redundancy_clean(params, ds_config: Dict[str, Any],
                     comp: CompressionState):
    """The reference's export entry (compress.py:148): fix techniques in
    the canonical order and dimension-reduce where a group declares
    ``related_modules``. Returns ``(params, dims)``."""
    need_reduction = any(
        g.related
        for tech in (ROW_PRUNING, HEAD_PRUNING, CHANNEL_PRUNING)
        for g in (comp.spec.get(tech).groups if comp.spec.get(tech) else ())
    )
    return fix_compression(params, comp, dim_reduction=need_reduction)


# ------------------------------------------------------------------ #
# layer reduction (student_initialization)
# ------------------------------------------------------------------ #

def student_initialization(student_params, teacher_params,
                           ds_config: Dict[str, Any]):
    """Initialize a depth-reduced student from teacher layers
    (compress.py:193). Supports per-layer subtrees named
    ``{prefix}_{i}`` / ``{prefix}.{i}`` and scan-stacked arrays (layer
    axis 0), the TPU-idiomatic layout — there the copy is one gather."""
    cfg = get_compression_config(ds_config)[LAYER_REDUCTION]
    if not cfg.get("enabled"):
        return student_params
    prefix = cfg["module_name_prefix"]
    teacher_layer = list(cfg["teacher_layer"])
    other = list(cfg.get("other_module_name") or [])
    student = dict(_as_dict(student_params))
    teacher = _as_dict(teacher_params)

    # Per-layer subtrees (h_0/h.0 spellings) take precedence — a
    # dict-of-layers under the prefix would otherwise be misread as a
    # stacked array and row-gathered. Only when no per-layer name
    # resolves AND the prefix subtree is array-leaved with the layer
    # axis up front (scan-stacked models) is the copy one gather.
    per_layer = any(
        _subtree_or_none(teacher, cand) is not None
        for cand in (f"{prefix}_{teacher_layer[0]}",
                     f"{prefix}.{teacher_layer[0]}"))
    t_stack = None if per_layer else _subtree_or_none(teacher, prefix)
    leaves = jax.tree.leaves(t_stack) if t_stack is not None else []
    if leaves and all(hasattr(x, "shape") and x.ndim >= 1
                      and x.shape[0] > max(teacher_layer) for x in leaves):
        idx = jnp.asarray(teacher_layer)
        student = _set_dotted(
            student, prefix,
            jax.tree.map(lambda x: jnp.take(x, idx, axis=0), t_stack))
    else:
        for s_i, t_i in enumerate(teacher_layer):
            t_sub = _layer_subtree(teacher, prefix, t_i)
            name = (f"{prefix}_{s_i}"
                    if _subtree_or_none(student, f"{prefix}_{s_i}")
                    is not None else f"{prefix}.{s_i}")
            student = _set_dotted(student, name, t_sub)
    for name in other:
        src = _subtree_or_none(teacher, name)
        if src is None:
            raise CompressionError(f"other_module_name {name!r} not in "
                                   "teacher params")
        student = _set_dotted(student, name, src)
    return student


def _subtree_or_none(tree, dotted):
    node = tree
    for k in dotted.split("."):
        node = _as_dict(node) if hasattr(node, "unfreeze") else node
        if not isinstance(node, dict) or k not in node:
            return None
        node = node[k]
    return node


def _layer_subtree(tree, prefix, i):
    for cand in (f"{prefix}_{i}", f"{prefix}.{i}"):
        node = _subtree_or_none(tree, cand)
        if node is not None:
            return node
    raise CompressionError(f"teacher layer {prefix}[{i}] not found")


def _set_dotted(tree, dotted, value):
    tree = dict(_as_dict(tree))
    keys = dotted.split(".")
    node = tree
    for k in keys[:-1]:
        node[k] = dict(_as_dict(node[k]))
        node = node[k]
    node[keys[-1]] = value
    return tree


# ------------------------------------------------------------------ #
# scheduler (host-side bookkeeping)
# ------------------------------------------------------------------ #

class CompressionScheduler:
    """Step counter + activation logging (reference scheduler.py). The
    actual gating is compiled into the step via ``jnp.where``; this
    object reports which techniques are live and feeds the step scalar
    the engine threads into ``apply_compression``."""

    def __init__(self, comp: CompressionState):
        self.comp = comp
        self.training_steps = 0
        self._announced = set()

    def live(self, tech: str) -> bool:
        t = self.comp.spec.get(tech)
        if not (t and t.enabled and t.groups):
            return False
        if self.training_steps < t.schedule_offset:
            return False
        end = t.schedule_offset_end
        return end is None or self.training_steps <= end

    def step(self, step_zero_check: bool = False):
        if not step_zero_check:
            self.training_steps += 1
        for tech in TECHNIQUES:
            if self.live(tech) and tech not in self._announced:
                self._announced.add(tech)
                from ..utils.logging import logger
                logger.info(f"{tech} engaged at step "
                            f"{self.training_steps}")
        return self.training_steps
