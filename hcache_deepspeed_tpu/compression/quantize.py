"""MoQ — quantize-aware training (Mixture of Quantization).

Reference analogs: ``deepspeed/compression/`` (weight quantization
config groups) and ``deepspeed/runtime/quantize.py`` (the MoQ
``Quantizer``: symmetric/asymmetric fake quantization with a bit
schedule that tightens from ``start_bits`` to ``target_bits`` over
training, optionally driven by the eigenvalue estimate).

TPU re-design: fake quantization is a pure function with a
straight-through estimator VJP (``round`` passes gradients through
unchanged), applied to the parameter pytree before the forward — one
fused XLA pass, no module surgery. The bit width is a trace-time
constant per schedule stage, so each bit level compiles once.
"""



import jax
import jax.numpy as jnp


@jax.custom_vjp
def _ste_round(x):
    return jnp.round(x)


def _ste_fwd(x):
    return jnp.round(x), None


def _ste_bwd(_, g):
    return (g,)  # straight-through


_ste_round.defvjp(_ste_fwd, _ste_bwd)


def _leaf_groups(x, groups: int) -> int:
    """Per-leaf group count: fall back to one scale group when the leaf
    size is not divisible (a global quantize_groups must not crash odd-
    sized parameters)."""
    return groups if groups > 0 and x.size % groups == 0 else 1


def _symmetric_quantize(flat, qmax):
    """Shared symmetric core (static and traced paths)."""
    scale = jnp.max(jnp.abs(flat), axis=-1, keepdims=True) / qmax
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(_ste_round(flat / scale), -qmax - 1.0, qmax)
    return q * scale


def fake_quantize(x, bits: int, symmetric: bool = True, groups: int = 1):
    """Quantize-dequantize ``x`` to ``bits`` with a straight-through
    gradient (reference: runtime/quantize.py Quantizer.compute_quantization).
    ``groups`` splits the flattened tensor into equal scale groups."""
    if bits >= 32:
        return x
    groups = _leaf_groups(x, groups)
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(groups, -1)
    qmax = 2.0 ** (bits - 1) - 1
    if symmetric:
        out = _symmetric_quantize(flat, qmax)
    else:
        lo = jnp.min(flat, axis=-1, keepdims=True)
        hi = jnp.max(flat, axis=-1, keepdims=True)
        span = jnp.where(hi - lo == 0, 1.0, hi - lo)
        scale = span / (2.0 ** bits - 1)
        q = _ste_round((flat - lo) / scale)
        out = q * scale + lo
    return out.reshape(orig_shape).astype(orig_dtype)


class QuantizeScheduler:
    """Bit schedule: start_bits → target_bits, halving the distance every
    ``quantize_period`` steps (the reference's MoQ period doubling —
    runtime/quantize.py:update_fp16_ratio semantics simplified to the
    bit staircase it produces)."""

    def __init__(self, start_bits: int = 16, target_bits: int = 8,
                 quantize_period: int = 100, schedule_offset: int = 0):
        self.start_bits = start_bits
        self.target_bits = target_bits
        self.quantize_period = quantize_period
        self.schedule_offset = schedule_offset

    def bits_at(self, step: int) -> int:
        if step < self.schedule_offset:
            return 32  # quantization not engaged yet
        k = (step - self.schedule_offset) // self.quantize_period
        bits = self.start_bits
        for _ in range(k):
            if bits <= self.target_bits:
                break
            bits = max(bits - max((bits - self.target_bits + 1) // 2, 1),
                       self.target_bits)
        return bits


def quantize_param_tree(params, bits: int, groups: int = 1,
                        min_size: int = 2 ** 12):
    """Fake-quantize every floating leaf with ≥ ``min_size`` elements
    (small leaves — norms, biases — stay full precision, matching the
    reference's modules-to-quantize selection)."""
    def leaf(p):
        if not jnp.issubdtype(p.dtype, jnp.floating) or p.size < min_size:
            return p
        return fake_quantize(p, bits, groups=groups)

    return jax.tree.map(leaf, params)


def fake_quantize_traced(x, bits, groups: int = 1):
    """``fake_quantize`` with a TRACED bit width (device scalar), so the
    engine's compiled step serves every schedule stage without
    retracing; ``bits >= 32`` passes through unchanged."""
    groups = _leaf_groups(x, groups)
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(groups, -1)
    bits_f = bits.astype(jnp.float32)
    qmax = 2.0 ** (bits_f - 1.0) - 1.0
    out = _symmetric_quantize(flat, qmax).reshape(orig_shape).astype(
        orig_dtype)
    return jnp.where(bits_f >= 32.0, x, out)


def quantize_param_tree_traced(params, bits, groups: int = 1,
                               min_size: int = 2 ** 12):
    def leaf(p):
        if not jnp.issubdtype(p.dtype, jnp.floating) or p.size < min_size:
            return p
        return fake_quantize_traced(p, bits, groups=groups)

    return jax.tree.map(leaf, params)
