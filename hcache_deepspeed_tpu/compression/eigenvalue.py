"""Hessian eigenvalue estimation (power iteration).

Reference analog: ``deepspeed/runtime/eigenvalue.py`` — per-layer
largest-eigenvalue estimates of the loss Hessian via power iteration on
Hessian-vector products; MoQ uses the estimates to decide which layers
tolerate aggressive quantization.

TPU re-design: the HVP is ``jvp(grad(loss))`` — one extra forward-
backward per iteration, fully jitted; no autograd-graph retention tricks
needed. Estimates are per parameter subtree (the "layer" granularity the
reference uses module names for).
"""

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np


def _normalize(tree):
    norm = jnp.sqrt(sum(jnp.vdot(x, x).real
                        for x in jax.tree.leaves(tree)))
    norm = jnp.maximum(norm, 1e-12)
    return jax.tree.map(lambda x: x / norm, tree), norm


def hessian_eigenvalue(loss_fn: Callable, params, max_iter: int = 20,
                       tol: float = 1e-2, seed: int = 0):
    """Largest eigenvalue of the Hessian of ``loss_fn(params)`` by power
    iteration on HVPs (reference: eigenvalue.py compute_eigenvalue).
    Returns (eigenvalue, iterations_used)."""
    grad_fn = jax.grad(loss_fn)

    @jax.jit
    def hvp(v):
        return jax.jvp(grad_fn, (params,), (v,))[1]

    key = jax.random.PRNGKey(seed)
    leaves, treedef = jax.tree.flatten(params)
    keys = jax.random.split(key, len(leaves))
    v = jax.tree.unflatten(treedef, [
        jax.random.normal(k, p.shape, jnp.float32)
        for k, p in zip(keys, leaves)])
    v, _ = _normalize(v)

    prev = 0.0
    for i in range(max_iter):
        hv = hvp(v)
        v, norm = _normalize(hv)
        eig = float(norm)
        if prev and abs(eig - prev) / max(abs(prev), 1e-12) < tol:
            return eig, i + 1
        prev = eig
    return prev, max_iter


def layer_eigenvalues(loss_fn: Callable, params: Dict, max_iter: int = 20,
                      tol: float = 1e-2, seed: int = 0) -> Dict[str, float]:
    """Per-top-level-subtree eigenvalue estimates: the Hessian block of
    each subtree with the rest of the parameters frozen (the reference's
    per-layer loop, eigenvalue.py:' for block in self.layer_num')."""
    out = {}
    for name in params:
        def sub_loss(sub, name=name):
            merged = dict(params)
            merged[name] = sub
            return loss_fn(merged)

        eig, _ = hessian_eigenvalue(sub_loss, params[name],
                                    max_iter=max_iter, tol=tol, seed=seed)
        out[name] = eig
    return out


def moq_bit_assignment(eigenvalues: Dict[str, float], low_bits: int = 4,
                       high_bits: int = 8) -> Dict[str, int]:
    """MoQ layer policy: high-curvature (sensitive) layers keep more
    bits (reference: MoQ eigenvalue-driven schedule)."""
    if not eigenvalues:
        return {}
    vals = np.asarray(list(eigenvalues.values()))
    median = float(np.median(vals))
    return {k: (high_bits if v >= median else low_bits)
            for k, v in eigenvalues.items()}
