"""Compression subsystem (reference: deepspeed/compression/ +
runtime/{quantize,progressive_layer_drop,eigenvalue}.py): MoQ
quantize-aware training, progressive layer drop, Hessian eigenvalues."""

from .eigenvalue import (hessian_eigenvalue, layer_eigenvalues,
                         moq_bit_assignment)
from .progressive_layer_drop import ProgressiveLayerDrop, pld_layer
from .quantize import (QuantizeScheduler, fake_quantize,
                       fake_quantize_traced, quantize_param_tree,
                       quantize_param_tree_traced)
from .structured import (CompressionError, CompressionScheduler,
                         CompressionState, activation_interceptor,
                         apply_compression, calibrate_activation_ranges,
                         fix_compression,
                         get_compression_config, init_compression,
                         quantize_activation, redundancy_clean,
                         student_initialization)

__all__ = ["fake_quantize", "fake_quantize_traced", "QuantizeScheduler",
           "quantize_param_tree", "quantize_param_tree_traced",
           "ProgressiveLayerDrop", "pld_layer", "hessian_eigenvalue",
           "layer_eigenvalues", "moq_bit_assignment",
           "CompressionError", "CompressionScheduler", "CompressionState",
           "activation_interceptor", "apply_compression",
           "calibrate_activation_ranges",
           "fix_compression", "get_compression_config", "init_compression",
           "quantize_activation", "redundancy_clean",
           "student_initialization"]
