"""Progressive layer dropping (PLD).

Reference analog: ``deepspeed/runtime/progressive_layer_drop.py`` —
``theta(t) = (1 - theta) * exp(-gamma * t) + theta`` keep probability,
decreasing over training; layers are stochastically bypassed with the
residual identity, scaled at the layer level.
"""

import math

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist


class ProgressiveLayerDrop:
    """Keep-probability schedule (reference: same formula + state)."""

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        log_dist(f"Enabled progressive layer dropping "
                 f"(theta = {self.theta})", ranks=[0])

    def update_state(self, global_step: int) -> float:
        self.current_theta = (1.0 - self.theta) * math.exp(
            -self.gamma * global_step) + self.theta
        return self.current_theta

    def get_theta(self) -> float:
        return self.current_theta

    def layer_keep_prob(self, layer_idx: int, n_layers: int) -> float:
        """Deeper layers drop more (the PLD paper's i/L ramp)."""
        frac = (layer_idx + 1) / max(n_layers, 1)
        return 1.0 - frac * (1.0 - self.current_theta)


def pld_layer(layer_fn, x, keep_prob, rng, *args, **kwargs):
    """Stochastically bypass ``layer_fn`` (must be residual-style:
    x -> x + f(x)): with probability 1-keep_prob the layer contributes
    nothing; when kept, its residual delta is scaled by 1/keep_prob so
    the expectation matches the full network (inverted-dropout
    convention). ``keep_prob`` may be a traced scalar."""
    if isinstance(keep_prob, (int, float)) and keep_prob >= 1.0:
        return layer_fn(x, *args, **kwargs)
    keep = jax.random.bernoulli(rng, keep_prob)
    out = layer_fn(x, *args, **kwargs)
    delta = (out - x) / keep_prob
    return jnp.where(keep, x + delta, x)
