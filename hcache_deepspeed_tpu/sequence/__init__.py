"""Sequence parallelism (Ulysses) + long-context engines.

Reference analog: ``deepspeed/sequence/`` — ``DistributedAttention``
(layer.py:311), ``_SeqAllToAll`` (layer.py:257), sequence-parallel vocab
cross-entropy (cross_entropy.py), and the FPDT chunked long-context engine
(fpdt_layer.py).
"""

from .layer import (DistributedAttention, seq_all_to_all,  # noqa: F401
                    ulysses_attention)
from .cross_entropy import vocab_sequence_parallel_cross_entropy  # noqa: F401
from .fpdt import (HostOffloadKV, chunked_attention,  # noqa: F401
                   chunked_lm_loss, make_fpdt_attention_fn)
from .ring import make_ring_attention_fn, ring_attention  # noqa: F401
