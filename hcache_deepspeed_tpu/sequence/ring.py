"""Ring attention: P2P sequence parallelism for long context.

Reference analog: none — the reference's tree has no ring-attention P2P
variant (SURVEY.md §5: its long-context story is Ulysses all-to-all +
FPDT chunking); this module supplies the equivalent capability the
TPU-native way, as called for by the survey's long-context plan.

Design: Q/K/V arrive sequence-sharded over the ``seq`` mesh axis
([B, T/n, H, D] per device). Each device keeps its Q block resident while
K/V blocks rotate around the ring with ``lax.ppermute`` (neighbor hops on
ICI); partial attention is merged with the online-softmax update (the
same update_out_and_lse recurrence FPDT uses, fpdt_layer.py:58). The
whole loop is a ``lax.scan`` inside ``shard_map`` manual over ``seq``
only, so it is differentiable (autodiff transposes the scan + ppermute
into the reverse ring) and composes with data/tensor sharding on auto
axes. Causality is handled per (q_block, kv_block) pair: full blocks
below the diagonal, masked on the diagonal, skipped above it via
``jnp.where`` on the block index — no dynamic control flow.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.topology import SEQ_AXIS, get_topology


def _merge(o1, lse1, o2, lse2):
    """Online-softmax merge of two partial attention results.

    o: [B, T, H, D]; lse: [B, H, T] log-sum-exp. The FPDT
    ``update_out_and_lse`` recurrence, associative formulation. Fully
    masked partials carry lse = -inf; the merge must stay NaN-free (and
    NaN-free in the backward) when either or both sides are -inf, so the
    exponentials are taken against a finite-clamped max."""
    max_lse = jnp.maximum(lse1, lse2)
    safe_max = jnp.where(jnp.isfinite(max_lse), max_lse, 0.0)
    w1 = jnp.where(jnp.isfinite(lse1), jnp.exp(lse1 - safe_max), 0.0)
    w2 = jnp.where(jnp.isfinite(lse2), jnp.exp(lse2 - safe_max), 0.0)
    denom = w1 + w2
    safe_denom = jnp.maximum(denom, 1e-38)
    out = (o1 * w1.transpose(0, 2, 1)[..., None] +
           o2 * w2.transpose(0, 2, 1)[..., None]) / \
        safe_denom.transpose(0, 2, 1)[..., None]
    new_lse = jnp.where(denom > 0, safe_max + jnp.log(safe_denom),
                        -jnp.inf)
    return out, new_lse


def _block_attention(q, k, v, scale, mask):
    """Partial attention of one (q-block, kv-block) pair.

    Returns (out [B,T,H,D], lse [B,H,T]); fully-masked rows produce
    -inf lse => zero weight in the merge."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    big_neg = jnp.finfo(jnp.float32).min
    scores = jnp.where(mask, scores.astype(jnp.float32), big_neg)
    lse = jax.nn.logsumexp(scores, axis=-1)                    # [B,H,Tq]
    # fully masked rows: lse == big_neg; normalize against a clamped lse
    # so exp stays 0 (never exp(-inf - -inf) = NaN), and report -inf lse
    fully_masked = lse <= big_neg / 2
    safe_lse = jnp.where(fully_masked, 0.0, lse)
    probs = jnp.exp(scores - safe_lse[..., None])
    probs = jnp.where(fully_masked[..., None], 0.0, probs)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v)
    return out, jnp.where(fully_masked, -jnp.inf, lse)


def ring_attention(q, k, v, causal=True, scale=None, axis_name=SEQ_AXIS,
                   topology=None):
    """Sequence-sharded exact attention over the ``seq`` ring.

    q/k/v: [B, T_global, H, D] arrays sequence-sharded on dim 1 (the
    standard activation sharding under ``seq`` parallelism). Must run
    under jit (partial-manual shard_map).
    """
    topo = topology or get_topology()
    n = topo.axis_size(axis_name)
    if n == 1:
        from ..ops.flash_attention import reference_attention
        return reference_attention(q, k, v, causal=causal, scale=scale)
    mesh = topo.mesh
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(D)

    from jax.sharding import PartitionSpec as P
    spec = P(None, axis_name)

    @functools.partial(jax.shard_map, mesh=mesh, axis_names={axis_name},
                       in_specs=(spec, spec, spec), out_specs=spec,
                       check_vma=False)
    def ring(q, k, v):
        B, T, H, _ = q.shape  # local block length T = T_global / n
        my = jax.lax.axis_index(axis_name)
        perm = [(i, (i + 1) % n) for i in range(n)]
        rel = jnp.arange(T)
        neg_inf_lse = jnp.full((B, H, T), -jnp.inf, jnp.float32)

        def step(carry, i):
            out, lse, kv = carry
            ki, vi = kv
            src = (my - i) % n  # whose kv block we hold at hop i
            if causal:
                # diagonal: causal triangle; below: all ones; above: none
                diag = rel[:, None] >= rel[None, :]
                full = jnp.ones((T, T), bool)
                none = jnp.zeros((T, T), bool)
                mask = jnp.where(src == my, diag,
                                 jnp.where(src < my, full, none))
            else:
                mask = jnp.ones((T, T), bool)
            o_i, lse_i = _block_attention(q, ki, vi, scale,
                                          mask[None, None])
            out, lse = _merge(out, lse, o_i, lse_i)
            kv = jax.tree.map(
                lambda x: jax.lax.ppermute(x, axis_name, perm), (ki, vi))
            return (out, lse, kv), None

        out0 = jnp.zeros_like(q)
        (out, lse, _), _ = jax.lax.scan(
            step, (out0, neg_inf_lse, (k, v)), jnp.arange(n))
        return out

    return ring(q, k, v)


def make_ring_attention_fn(topology=None, axis_name=SEQ_AXIS):
    """Drop-in ``attention_fn`` for the model families (same contract as
    ``make_ulysses_attention_fn``)."""

    def attention_fn(q, k, v, causal=True, scale=None):
        return ring_attention(q, k, v, causal=causal, scale=scale,
                              axis_name=axis_name, topology=topology)

    return attention_fn
