"""Sequence-parallel vocab cross-entropy.

Reference analog: ``deepspeed/sequence/cross_entropy.py`` —
``vocab_sequence_parallel_cross_entropy`` computes the softmax CE when
logits are *vocab*-sharded across the sequence-parallel group: local max /
local sum-exp are combined with allreduces so no rank materialises the full
vocab. Explicit-collective form for shard_map code; under plain jit the
engine's loss is already partitioner-sharded and needs no special handling.
"""

import jax
import jax.numpy as jnp

from ..parallel.topology import SEQ_AXIS


def vocab_sequence_parallel_cross_entropy(logits, labels,
                                          axis_name=SEQ_AXIS,
                                          vocab_start=None):
    """CE over vocab-sharded logits inside shard_map.

    logits: [B, T, V_local] — the vocab dim sharded over ``axis_name``.
    labels: [B, T] global ids (-100 = ignore).
    vocab_start: this shard's first vocab id (default rank * V_local).
    """
    V_local = logits.shape[-1]
    idx = jax.lax.axis_index(axis_name)
    if vocab_start is None:
        vocab_start = idx * V_local
    logits = logits.astype(jnp.float32)

    # numerically stable log-softmax across shards
    local_max = jnp.max(logits, axis=-1)
    global_max = jax.lax.pmax(local_max, axis_name)
    shifted = logits - global_max[..., None]
    local_sumexp = jnp.sum(jnp.exp(shifted), axis=-1)
    global_sumexp = jax.lax.psum(local_sumexp, axis_name)
    log_z = jnp.log(global_sumexp)

    valid = labels != -100
    local_labels = jnp.where(valid, labels, 0) - vocab_start
    in_shard = (local_labels >= 0) & (local_labels < V_local)
    safe = jnp.clip(local_labels, 0, V_local - 1)
    picked = jnp.take_along_axis(shifted, safe[..., None],
                                 axis=-1).squeeze(-1)
    picked = jnp.where(in_shard, picked, 0.0)
    # each label lives in exactly one shard -> psum assembles the full term
    picked = jax.lax.psum(picked, axis_name)

    nll = jnp.where(valid, log_z - picked, 0.0)
    return nll.sum() / jnp.maximum(valid.sum(), 1)
