"""FPDT-style chunked long-context attention + chunked loss.

Reference analog: ``deepspeed/sequence/fpdt_layer.py`` (Ulysses-Offload /
Fully Pipelined Distributed Transformer, 1,225 LoC):
* online-softmax chunk merging (``update_out_and_lse``, :58),
* chunked-sequence attention with host offload of chunks, double-buffered
  streams (``_FPDTGPUOffloadingAttentionImpl_``, :510),
* chunked FFN + logits loss (:1056, :1137).

TPU re-design:
* ``chunked_attention`` — the compute schedule: q processed in chunks via
  ``lax.scan`` with an inner online-softmax scan over kv chunks. Peak
  memory O(T·chunk) instead of O(T²); differentiable; the scan carries
  the (out, lse) recurrence so XLA never materializes full attention.
  With ``remat=True`` each chunk recomputes in the backward (the
  reference's activation strategy).
* ``chunked_lm_loss`` — the chunked-logits loss: per-chunk [B, c, V]
  logits reduced immediately, so the full [B, T, V] tensor never exists.
* ``HostOffloadKV`` — the offload piece: KV chunks live in HOST memory;
  a double-buffered device window streams them through HBM (the dual
  cuda-stream pattern, engine-side) for forward-only/inference scoring of
  million-token contexts.
"""

import functools


import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.topology import get_topology
from .ring import _block_attention, _merge


def _causal_mask(q_idx, k_idx, q_chunk, k_chunk):
    """Mask for (q chunk index, kv chunk index) at given chunk sizes."""
    q_pos = q_idx * q_chunk + jnp.arange(q_chunk)
    k_pos = k_idx * k_chunk + jnp.arange(k_chunk)
    return q_pos[:, None] >= k_pos[None, :]


def chunked_attention(q, k, v, causal=True, scale=None, q_chunk=512,
                      k_chunk=None, remat=True):
    """Memory-O(chunk) exact attention. q/k/v: [B, T, H, D]."""
    q, k, v = jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    B, T, H, D = q.shape
    k_chunk = k_chunk or q_chunk
    if T % q_chunk or k.shape[1] % k_chunk:
        raise ValueError(f"T={T}/{k.shape[1]} not divisible by chunks "
                         f"{q_chunk}/{k_chunk}")
    scale = scale if scale is not None else 1.0 / np.sqrt(D)
    nq = T // q_chunk
    nk = k.shape[1] // k_chunk
    qs = q.reshape(B, nq, q_chunk, H, D)
    ks = k.reshape(B, nk, k_chunk, H, D)
    vs = v.reshape(B, nk, k_chunk, H, D)

    def one_q_chunk(qi, q_blk):
        def kv_step(carry, ki):
            out, lse = carry
            k_blk = ks[:, ki]
            v_blk = vs[:, ki]
            if causal:
                mask = _causal_mask(qi, ki, q_chunk, k_chunk)[None, None]
            else:
                mask = jnp.ones((1, 1, q_chunk, k_chunk), bool)
            o_i, lse_i = _block_attention(q_blk, k_blk, v_blk, scale, mask)
            return _merge(out, lse, o_i, lse_i), None

        out0 = jnp.zeros_like(q_blk)
        lse0 = jnp.full((B, H, q_chunk), -jnp.inf, jnp.float32)
        (out, _), _ = jax.lax.scan(kv_step, (out0, lse0), jnp.arange(nk))
        return out

    fn = jax.checkpoint(one_q_chunk) if remat else one_q_chunk

    def q_step(_, qi):
        return None, fn(qi, qs[:, qi])

    _, outs = jax.lax.scan(q_step, None, jnp.arange(nq))  # [nq, B, c, H, D]
    return outs.transpose(1, 0, 2, 3, 4).reshape(B, T, H, D)


def chunked_lm_loss(hidden, lm_head_kernel, labels, chunk=1024):
    """Causal-LM loss without materializing [B, T, V] logits (reference:
    fpdt_layer.py:1137 chunked logits loss). hidden: [B, T, H];
    lm_head_kernel: [H, V]; labels: [B, T] with -100 ignore."""
    hidden = jnp.asarray(hidden)
    labels = jnp.asarray(labels)
    lm_head_kernel = jnp.asarray(lm_head_kernel)
    B, T, H = hidden.shape
    if T % chunk:
        raise ValueError(f"T={T} not divisible by chunk={chunk}")
    n = T // chunk
    hs = hidden.reshape(B, n, chunk, H)
    ls = labels.reshape(B, n, chunk)

    # remat the chunk body: without it, autodiff-of-scan saves every
    # chunk's [B, chunk, V] fp32 logits as residuals — exactly the
    # materialization this function exists to avoid. With it, backward
    # recomputes each chunk's logits GEMM (the FPDT trade).
    @jax.checkpoint
    def chunk_nll(h_blk, lab):
        logits = (h_blk @ lm_head_kernel).astype(jnp.float32)
        valid = lab != -100
        safe = jnp.where(valid, lab, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, safe[..., None],
                                   axis=-1).squeeze(-1)
        nll = jnp.where(valid, nll, 0.0)
        return nll.sum(), valid.sum()

    def step(acc, i):
        nll_sum, count = acc
        nll, valid = chunk_nll(hs[:, i], ls[:, i])
        return (nll_sum + nll, count + valid), None

    (nll_sum, count), _ = jax.lax.scan(
        step, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
        jnp.arange(n))
    return nll_sum / jnp.maximum(count, 1)


def offloaded_chunked_attention(q, k, v, causal=True, scale=None,
                                q_chunk=512, k_chunk=None):
    """TRAINING-capable host-offloaded chunked attention.

    Reference: ``_FPDTGPUOffloadingAttentionImpl_``
    (``deepspeed/sequence/fpdt_layer.py:510``) — KV chunks live in host
    memory during the forward and stream back for the backward on a
    second stream. TPU-native mechanism: K/V are tagged with
    ``checkpoint_name('fpdt_kv')`` inside the chunked-attention remat
    region; compiling the training step with
    :func:`fpdt_offload_policy` makes XLA *store those residuals in
    pinned host memory* and prefetch them back during the backward wave
    — the double-buffered dual-stream pattern, scheduled by the
    compiler instead of hand-written events.

    Differentiable; numerics identical to :func:`chunked_attention`.
    Without the policy it behaves as plain remat (the name tag is
    inert), so the same model code runs on hosts without offload
    support.
    """
    from jax.ad_checkpoint import checkpoint_name
    k = checkpoint_name(k, "fpdt_kv")
    v = checkpoint_name(v, "fpdt_kv")
    return chunked_attention(q, k, v, causal=causal, scale=scale,
                             q_chunk=q_chunk, k_chunk=k_chunk, remat=True)


def make_fpdt_attention_fn(q_chunk=512, k_chunk=None, remat=True,
                           topology=None):
    """``attention_fn`` hook for the model zoo: memory-O(chunk) exact
    attention, composed with Ulysses over the ``seq`` axis when the
    topology has one — the FPDT composition (reference:
    ``sequence/fpdt_layer.py`` = chunked schedule inside the Ulysses
    all-to-alls). Symmetric with ``make_ulysses_attention_fn`` /
    ``make_ring_attention_fn``.

    Not GQA-native (the chunk kernel wants dense heads); the model hook
    and the Ulysses wrapper both consult ``supports_gqa`` and expand
    compact k/v before calling in."""
    local = functools.partial(chunked_attention, q_chunk=q_chunk,
                              k_chunk=k_chunk, remat=remat)

    def attention_fn(q, k, v, causal=True, scale=None):
        # resolve at CALL time like the sibling factories (layer.py:133,
        # ring.py:79): a factory built before initialize_topology must
        # still engage the Ulysses composition on a seq mesh
        topo = topology or get_topology()
        if topo is not None and topo.seq_size > 1:
            from .layer import ulysses_attention
            return ulysses_attention(q, k, v, causal=causal, scale=scale,
                                     topology=topo, local_attn=local)
        return local(q, k, v, causal=causal, scale=scale)

    attention_fn.supports_gqa = False
    return attention_fn


def fpdt_offload_policy(extra_save_names=()):
    """Remat policy that offloads ``fpdt_kv``-tagged residuals to pinned
    host memory (pass to ``jax.checkpoint``/``jax.remat`` around the
    train step, or via the engine's ``compile.remat_policy`` machinery).
    """
    return jax.checkpoint_policies.save_and_offload_only_these_names(
        names_which_can_be_saved=list(extra_save_names),
        names_which_can_be_offloaded=["fpdt_kv"],
        offload_src="device",
        offload_dst="pinned_host")


class HostOffloadKV:
    """Host-resident KV with a double-buffered HBM window (reference:
    _FPDTGPUOffloadingAttentionImpl_ — chunks offloaded to host, prefetch
    on a second stream). Forward-only scoring path for contexts that
    exceed HBM; the training path uses ``chunked_attention`` + remat.
    """

    def __init__(self, k_host: np.ndarray, v_host: np.ndarray,
                 chunk: int, device=None):
        T = k_host.shape[1]
        if T % chunk:
            raise ValueError(f"T={T} not divisible by chunk {chunk}")
        self.k_host, self.v_host = k_host, v_host
        self.chunk = chunk
        self.n_chunks = T // chunk
        self.device = device or jax.devices()[0]

    def _put(self, i):
        s = slice(i * self.chunk, (i + 1) * self.chunk)
        return (jax.device_put(self.k_host[:, s], self.device),
                jax.device_put(self.v_host[:, s], self.device))

    def attend(self, q, causal=True, scale=None, q_start: int = 0):
        """q: [B, Tq, H, D] device array at absolute position q_start.
        Streams host KV chunks through a 2-deep window, merging with
        online softmax on device (async dispatch overlaps the next H2D
        with the current chunk's attention math)."""
        B, Tq, H, D = q.shape
        scale = scale if scale is not None else 1.0 / np.sqrt(D)
        merge = jax.jit(_merge)
        attend_chunk = jax.jit(
            functools.partial(self._attend_chunk, scale=scale,
                              causal=causal),
            static_argnums=(4,))
        out = jnp.zeros_like(q)
        lse = jnp.full((B, H, Tq), -jnp.inf, jnp.float32)
        buf = self._put(0)
        q_pos = q_start + np.arange(Tq)
        for i in range(self.n_chunks):
            cur = buf
            if i + 1 < self.n_chunks:
                buf = self._put(i + 1)  # prefetch: next H2D in flight
            o_i, lse_i = attend_chunk(q, cur[0], cur[1],
                                      jnp.asarray(q_pos), i * self.chunk)
            out, lse = merge(out, lse, o_i, lse_i)
        return out

    @staticmethod
    def _attend_chunk(q, k, v, q_pos, k_start, *, scale, causal):
        Tk = k.shape[1]
        if causal:
            mask = (q_pos[:, None] >= (k_start + jnp.arange(Tk))[None, :])
            mask = mask[None, None]
        else:
            mask = jnp.ones((1, 1, q.shape[1], Tk), bool)
        return _block_attention(q, k, v, scale, mask)
