"""Ulysses sequence-parallel attention.

Reference analog: ``deepspeed/sequence/layer.py`` — ``_SeqAllToAll`` (:257)
scatters heads / gathers sequence before local attention and inverts after;
``DistributedAttention`` (:311) wraps any local attention callable. The
reference drives NCCL ``all_to_all_single`` by hand (plus a dual-stream
overlap path, :347); on TPU both collective choice and overlap belong to
XLA, so this module provides the same capability in two idiomatic forms:

1. ``ulysses_attention`` — *sharding-constraint* form for code running under
   ``jit`` over the global mesh (the engine's train step). Activations
   arrive sequence-sharded ``[B, T/sp, H, D]``; a resharding constraint to
   head-sharded ``[B, T, H/sp, D]`` makes GSPMD insert exactly the
   head-scatter/seq-gather all-to-all on the ``seq`` axis, the local flash
   kernel runs on full sequences with H/sp heads, and the output constraint
   restores sequence sharding. XLA overlaps the all-to-alls with neighbouring
   compute (the reference's ``sp_stream`` overlap, for free).

2. ``seq_all_to_all`` / ``DistributedAttention`` — *explicit collective*
   form (``lax.all_to_all``) for code already inside ``shard_map`` over the
   ``seq`` axis (the pipeline engine's stages, custom kernels, tests).
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..parallel.topology import SEQ_AXIS, get_topology


def _maybe_expand_kv(q, k, v, sp, force_dense=False):
    """GQA under Ulysses: compact k/v heads scatter across ``seq`` only
    when sp divides them — the a2a then moves KV-sized tensors (H/KV x
    less wire than the repeated layout) and the GQA-native local flash
    kernel does the group broadcast. Indivisible KV (or a local kernel
    that needs dense heads, ``force_dense``) expands to q's heads."""
    KV, H = k.shape[2], q.shape[2]
    if KV != H and (force_dense or KV % sp):
        rep = H // KV
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def _pad_dim(x, mult, axis):
    """Zero-pad ``axis`` up to the next multiple of ``mult``."""
    pad = (-x.shape[axis]) % mult
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _prep_uneven_heads(q, k, v, sp, axis=2):
    """Head counts not divisible by sp (reference: the uneven head
    distribution of ``deepspeed/sequence/layer.py:111``): dense-expand
    GQA k/v, then zero-pad the head dim to the next sp multiple. The
    padded heads ride the all-to-alls and compute garbage that the
    caller slices off after the inverse a2a — shapes stay static (XLA-
    friendly) at < sp/H extra head compute, vs the reference's ragged
    per-rank head counts."""
    k, v = _maybe_expand_kv(q, k, v, sp, force_dense=True)
    return tuple(_pad_dim(x, sp, axis) for x in (q, k, v))


def seq_all_to_all(x, axis_name=SEQ_AXIS, scatter_dim=2, gather_dim=1):
    """Explicit all-to-all: split ``scatter_dim`` across the axis, gather
    ``gather_dim``. Equivalent to the reference's ``_SeqAllToAll.forward``
    (layer.py:257). Must run inside shard_map/pmap over ``axis_name``."""
    return jax.lax.all_to_all(x, axis_name, split_axis=scatter_dim,
                              concat_axis=gather_dim, tiled=True)


class DistributedAttention:
    """Ulysses wrapper over a local attention callable.

    Reference: ``DistributedAttention`` (sequence/layer.py:311) —
    q/k/v arrive ``[B, T_local, H, D]`` (sequence-sharded); heads are
    scattered / sequence gathered via all-to-all, ``local_attn`` runs on
    ``[B, T, H_local, D]``, and the output is transformed back. Explicit
    collective form: call inside ``shard_map`` over ``seq``.
    """

    def __init__(self, local_attn: Callable, axis_name: str = SEQ_AXIS,
                 scatter_idx: int = 2, gather_idx: int = 1,
                 supports_gqa: Optional[bool] = None):
        self.local_attn = local_attn
        self.axis_name = axis_name
        self.scatter_idx = scatter_idx
        self.gather_idx = gather_idx
        #: whether LOCAL attention accepts compact GQA k/v; derived from
        #: the callable unless stated — a wrapped kernel written for
        #: dense heads must keep getting dense heads
        self.supports_gqa = getattr(local_attn, "supports_gqa", False) \
            if supports_gqa is None else supports_gqa

    def __call__(self, q, k, v, *args, **kwargs):
        sp = jax.lax.axis_size(self.axis_name)
        H = q.shape[self.scatter_idx]
        uneven = H % sp != 0
        if uneven:
            if self.scatter_idx != 2:
                raise NotImplementedError(
                    "uneven head padding assumes heads at dim 2")
            q, k, v = _prep_uneven_heads(q, k, v, sp)
        else:
            k, v = _maybe_expand_kv(q, k, v, sp,
                                    force_dense=not self.supports_gqa)
        a2a = lambda x: seq_all_to_all(x, self.axis_name, self.scatter_idx,
                                       self.gather_idx)
        out = self.local_attn(a2a(q), a2a(k), a2a(v), *args, **kwargs)
        # inverse: scatter sequence back, gather heads
        out = seq_all_to_all(out, self.axis_name,
                             scatter_dim=self.gather_idx,
                             gather_dim=self.scatter_idx)
        if uneven:
            out = jax.lax.slice_in_dim(out, 0, H, axis=self.scatter_idx)
        return out


def ulysses_attention(q, k, v, causal=True, scale=None, topology=None,
                      local_attn: Optional[Callable] = None):
    """Sharding-constraint Ulysses for use under jit over the global mesh.

    q/k/v: ``[B, T, H, D]`` logical arrays whose T dim is sharded on the
    ``seq`` mesh axis (the engine's batch sharding). Internally resharded to
    head-parallel for the local attention (GSPMD inserts the all-to-all
    pair), then back.
    """
    topo = topology or get_topology()
    # the built-in flash path (and GQA-declaring custom kernels) take
    # compact k/v; others get dense heads — including on the sp=1 fast
    # path, so behavior doesn't change with topology
    dense = not (local_attn is None
                 or getattr(local_attn, "supports_gqa", False))
    if topo.seq_size <= 1:
        from ..ops.flash_attention import attention as flash
        k, v = _maybe_expand_kv(q, k, v, 1, force_dense=dense)
        return (local_attn or flash)(q, k, v, causal=causal, scale=scale)

    H = q.shape[2]
    uneven = H % topo.seq_size != 0
    if uneven:
        q, k, v = _prep_uneven_heads(q, k, v, topo.seq_size)
    else:
        k, v = _maybe_expand_kv(q, k, v, topo.seq_size, force_dense=dense)

    mesh = topo.mesh
    batch_axes = topo.batch_shard_axes() or None
    heads = NamedSharding(mesh, PartitionSpec(batch_axes, None, SEQ_AXIS,
                                              None))
    seqs = NamedSharding(mesh, PartitionSpec(batch_axes, SEQ_AXIS, None,
                                             None))

    wsc = jax.lax.with_sharding_constraint
    qh, kh, vh = (wsc(x, heads) for x in (q, k, v))
    from ..ops.flash_attention import attention as flash
    out = (local_attn or flash)(qh, kh, vh, causal=causal, scale=scale)
    out = wsc(out, heads)
    out = wsc(out, seqs)
    if uneven:
        out = jax.lax.slice_in_dim(out, 0, H, axis=2)
    return out


def make_ulysses_attention_fn(topology=None, local_attn=None):
    """Returns an ``attention_fn`` pluggable into the model zoo's attention
    modules (e.g. ``LlamaAttention(attention_fn=...)``)."""

    def attention_fn(q, k, v, causal=True, scale=None):
        return ulysses_attention(q, k, v, causal=causal, scale=scale,
                                 topology=topology, local_attn=local_attn)

    # compact k/v accepted iff the local kernel handles GQA (the built-in
    # flash path does)
    attention_fn.supports_gqa = local_attn is None or getattr(
        local_attn, "supports_gqa", False)
    return attention_fn
