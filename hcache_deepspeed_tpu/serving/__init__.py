"""Continuous-batching serving subsystem over ``InferenceEngineV2``.

No reference analog inside DeepSpeed itself — the reference delegates
this layer to MII's serving loop. Here it is built in: a request
lifecycle (``request.py``), a continuous-batching scheduler with
HCache-aware preemption and restore/decode overlap (``scheduler.py``),
a thread-based frontend with admission control and a deterministic
virtual-clock simulation mode (``server.py``), and serving metrics
emitted through the ``monitor.MonitorMaster`` event path
(``metrics.py``). ``sim.py`` provides a model-free engine double with
the real block-budget arithmetic so the whole policy is CPU-testable.
``crossover.py`` prices restore vs recompute per preempted sequence —
the analytic model the scheduler consults at re-entry. Above all of
that sits the fleet layer: ``router.py`` (KV-pressure- and
prefix-aware placement, per-replica health breakers, migration
planning priced by the crossover's per-link transfer term) and
``fleet.py`` (N replicas sharing one clock, cross-replica migration
with HCache latents as the transfer payload, replica failure domains:
crash/hang/partition, graceful drain, crash recovery).

Two newer layers ride the same machinery: ``spec.py`` (scheduler-
dispatched fused speculative decoding — host-side prompt-lookup
drafting, the engine's ``put_spec`` verify step with per-lane KV
rollback, and the SLO-aware degradation mode driven by TTFT/TPOT
burn) and ``prefix_tree.py`` (the fleet-shared radix prefix tree over
full token-id paths, per-replica warm-prefix caches, and the latent
prefix-broadcast primitive the router prices through ``crossover.py``).
"""

from .autoscale import (AutoscaleConfig, Autoscaler,  # noqa: F401
                        build_autoscale_trace,
                        validate_autoscale_config)
from .clock import MonotonicClock, VirtualClock  # noqa: F401
from .crossover import (CrossoverConfig,  # noqa: F401
                        RestoreCrossoverModel)
from .disagg import (DisaggConfig, DisaggregatedFleet,  # noqa: F401
                     build_mixed_trace, compare_disagg_vs_colocated)
from .fleet import (FleetConfig, FleetReplica,  # noqa: F401
                    Migration, ReplicaRole, ReplicaState,
                    ScaleUpAborted, ServingFleet)
from .metrics import Histogram, ServingMetrics  # noqa: F401
from .prefix_tree import (PrefixReuseConfig,  # noqa: F401
                          RadixPrefixTree, ReplicaPrefixCache,
                          validate_prefix_reuse_config)
from .request import Request, RequestState  # noqa: F401
from .router import (FleetRouter, ReplicaSnapshot,  # noqa: F401
                     RouterConfig)
from .scheduler import (ContinuousBatchingScheduler,  # noqa: F401
                        StepReport)
from .server import ServerConfig, ServingServer  # noqa: F401
from .sim import SimulatedEngine  # noqa: F401
from .spec import (SLODegradation, SLOModeConfig,  # noqa: F401
                   SpeculationConfig, lookup_draft,
                   validate_slo_mode_config,
                   validate_speculation_config)
