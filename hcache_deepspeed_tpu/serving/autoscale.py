"""SLO-driven elastic autoscaling: the control loop over the fleet.

ROADMAP item 2. Every actuator this loop drives already exists and is
individually gated — this module only *decides*:

* **Membership** — :meth:`~.fleet.ServingFleet.add_replica` (bootstrap
  + radix-prefix-tree pre-warm over the latent broadcast wire) and
  :meth:`~.fleet.ServingFleet.retire_replica` (drain-via-migration,
  never-dropped at fleet scope). Under the process transport these
  spawn and reap REAL supervised workers.
* **Re-roling** — :meth:`~.fleet.ServingFleet.set_role` shifts
  replicas between the prefill/decode tiers of a disaggregated fleet
  when tier load diverges.
* **The degradation ladder** — the per-request pressure valve (PR 14:
  speculation off → forced chunked prefill → shed) keeps absorbing
  load BETWEEN scale events; the loop counts the steps where the
  valve is what held the line (``valve_steps``).

Control policy (deliberately boring): three pressure signals — worst
SLO burn rate across stepping replicas
(:meth:`~..telemetry.slo.SLOTracker.burn_rates` via the per-step
``slo_gauges``), mean KV utilization, and per-replica backlog — are
squashed into hot/calm booleans with separate high/low thresholds
(hysteresis band). ``hot_steps`` consecutive hot steps trigger a
scale-up; ``calm_steps`` consecutive calm steps trigger a
drain-retirement of the coldest replica; a ``cooldown_steps`` dead
time follows every event, and a direction reversal inside
``flap_window_steps`` counts a flap — at ``max_flaps`` the loop
refuses further reversals (the chaos invariant bounds the flap
counter, not the operator's patience).

Determinism: the loop reads only virtual-clock fleet state and
actuates synchronously inside :meth:`Autoscaler.observe` — a run is a
pure function of (trace, seed, fault plan). With ``enabled=False``
``observe`` returns before reading anything, so an attached-but-off
autoscaler is digest-invisible (the regression gate replays every
committed digest that way).
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..runtime.config import HDSConfigError
from .fleet import ReplicaRole, ReplicaState, ScaleUpAborted, \
    ServingFleet
from .request import Request

_STEPPING = (ReplicaState.UP, ReplicaState.DRAINING)


@dataclass
class AutoscaleConfig:
    enabled: bool = True
    #: membership bounds (peak size is what the cost gate compares
    #: against: the autoscaled fleet must beat a static fleet of
    #: ``max_replicas`` on cost at equal-or-better SLO attainment)
    min_replicas: int = 1
    max_replicas: int = 4
    #: pressure thresholds — hot when ANY signal crosses its high
    #: mark, calm only when ALL sit under their low marks
    burn_high: float = 1.0
    burn_low: float = 0.5
    kv_high: float = 0.80
    kv_low: float = 0.35
    backlog_high: float = 6.0
    backlog_low: float = 1.5
    #: hysteresis (consecutive steps) + post-event dead time
    hot_steps: int = 3
    calm_steps: int = 12
    cooldown_steps: int = 20
    #: flap guard: a direction reversal within ``flap_window_steps``
    #: of the previous event is a flap; at ``max_flaps`` reversals
    #: are refused for the rest of the run
    flap_window_steps: int = 30
    max_flaps: int = 2
    #: prefill<->decode re-roling on mixed-role fleets
    rerole: bool = True
    rerole_gap: float = 4.0
    rerole_cooldown_steps: int = 25
    #: freshest radix-tree paths shipped to a freshly added replica
    prewarm_paths: int = 4


def validate_autoscale_config(cfg: AutoscaleConfig) -> None:
    if cfg.min_replicas < 1:
        raise HDSConfigError("min_replicas must be >= 1")
    if cfg.max_replicas < cfg.min_replicas:
        raise HDSConfigError("max_replicas < min_replicas")
    if cfg.burn_low > cfg.burn_high or cfg.kv_low > cfg.kv_high or \
            cfg.backlog_low > cfg.backlog_high:
        raise HDSConfigError(
            "hysteresis bands must satisfy low <= high")
    if cfg.hot_steps < 1 or cfg.calm_steps < 1:
        raise HDSConfigError("hot_steps/calm_steps must be >= 1")


class Autoscaler:
    """The control loop. Construct over a fleet, then call
    :meth:`observe` after every fleet step (or let :meth:`run` drive
    a whole trace). Attaching sets ``fleet.autoscaler`` so the fleet's
    metrics surface exports the scale-event counters and flap gauge —
    the fleet itself never calls back into the loop."""

    def __init__(self, fleet: ServingFleet,
                 config: AutoscaleConfig = None):
        self.fleet = fleet
        self.config = config or AutoscaleConfig()
        validate_autoscale_config(self.config)
        fleet.autoscaler = self
        self.counters: Dict[str, int] = {
            "scale_ups": 0, "scale_up_aborts": 0, "retires": 0,
            "reroles": 0, "blocked_cooldown": 0, "blocked_flap": 0,
            "blocked_bounds": 0, "valve_steps": 0,
        }
        #: direction reversals inside the flap window (bounded by
        #: ``max_flaps`` — the chaos invariant checks exactly this)
        self.flaps = 0
        #: decision log: ``(fleet_step, action, detail)`` — the
        #: autoscaler's own narrative, NOT part of any fleet digest
        self.decisions: List[Tuple[int, str, str]] = []
        self._hot_streak = 0
        self._calm_streak = 0
        self._last_event_step = -(10 ** 9)
        self._last_event_dir = 0
        self._last_rerole_step = -(10 ** 9)
        self.last_signals: Dict[str, float] = {}

    # ------------------------------------------------------------- #
    # signals
    # ------------------------------------------------------------- #
    def _signals(self) -> Dict[str, float]:
        burn = 0.0
        kv_sum = 0.0
        backlog = 0.0
        n = 0
        for r in self.fleet.replicas:
            if r.state not in _STEPPING:
                continue
            n += 1
            g = r.server.metrics.slo_gauges
            burn = max(burn, float(g.get("slo_ttft_burn_rate", 0.0)),
                       float(g.get("slo_tpot_burn_rate", 0.0)))
            kv_sum += r.kv_utilization
            backlog += r.live_requests
        backlog += len(self.fleet.pending)
        n = max(n, 1)
        return {"burn": burn, "kv": kv_sum / n,
                "backlog": backlog / n,
                "replicas_live": float(self.fleet.live_replicas)}

    def _hot(self, s: Dict[str, float]) -> bool:
        c = self.config
        return (s["burn"] >= c.burn_high or s["kv"] >= c.kv_high or
                s["backlog"] >= c.backlog_high)

    def _calm(self, s: Dict[str, float]) -> bool:
        c = self.config
        return (s["burn"] <= c.burn_low and s["kv"] <= c.kv_low and
                s["backlog"] <= c.backlog_low)

    # ------------------------------------------------------------- #
    # the loop body
    # ------------------------------------------------------------- #
    def observe(self) -> Optional[str]:
        """One control decision after one fleet step. Returns the
        action taken (``"scale_up"`` / ``"retire"`` / ``"rerole"``)
        or None. Disabled loops return before reading ANY fleet
        state — attachment must be digest-invisible."""
        if not self.config.enabled:
            return None
        step = self.fleet.step_idx
        s = self._signals()
        self.last_signals = s
        hot, calm = self._hot(s), self._calm(s)
        self._hot_streak = self._hot_streak + 1 if hot else 0
        self._calm_streak = self._calm_streak + 1 if calm else 0
        action = None
        if self._hot_streak >= self.config.hot_steps:
            action = self._try_scale(step, +1, s)
        elif self._calm_streak >= self.config.calm_steps:
            action = self._try_scale(step, -1, s)
        if action is None and self.config.rerole:
            action = self._maybe_rerole(step)
        if action is None and hot and \
                self.fleet.degradation_level > 0:
            # blocked or waiting out hysteresis while hot: the
            # per-request ladder is the pressure valve holding the
            # line between scale events
            self.counters["valve_steps"] += 1
        return action

    def _blocked(self, step: int, direction: int) -> Optional[str]:
        c = self.config
        live = self.fleet.live_replicas
        if direction > 0 and live >= c.max_replicas:
            self.counters["blocked_bounds"] += 1
            return "bounds"
        if direction < 0 and live <= c.min_replicas:
            self.counters["blocked_bounds"] += 1
            return "bounds"
        if direction < 0 and self.fleet.degradation_level > 0:
            # never retire capacity while any replica is degraded —
            # calm signals with an active ladder are a lie
            self.counters["blocked_bounds"] += 1
            return "degraded"
        if step - self._last_event_step < c.cooldown_steps:
            self.counters["blocked_cooldown"] += 1
            return "cooldown"
        if self._last_event_dir and direction != self._last_event_dir \
                and step - self._last_event_step <= \
                c.flap_window_steps:
            if self.flaps + 1 > c.max_flaps:
                self.counters["blocked_flap"] += 1
                return "flap"
        return None

    def _try_scale(self, step: int, direction: int,
                   s: Dict[str, float]) -> Optional[str]:
        why = self._blocked(step, direction)
        if why is not None:
            return None
        if self._last_event_dir and \
                direction != self._last_event_dir and \
                step - self._last_event_step <= \
                self.config.flap_window_steps:
            self.flaps += 1
        if direction > 0:
            try:
                rid = self.fleet.add_replica(
                    prewarm_paths=self.config.prewarm_paths)
            except ScaleUpAborted as exc:
                # clean abort: prior fleet shape, zero requests
                # touched — charge the cooldown anyway so a broken
                # bootstrap cannot hot-loop spawn attempts
                self.counters["scale_up_aborts"] += 1
                self.decisions.append(
                    (step, "scale_up_abort", str(exc)))
                self._note_event(step, direction)
                return None
            self.counters["scale_ups"] += 1
            self.decisions.append((
                step, "scale_up",
                f"replica={rid} burn={s['burn']:.2f} "
                f"kv={s['kv']:.2f} backlog={s['backlog']:.1f}"))
            self._note_event(step, direction)
            return "scale_up"
        victim = self._coldest()
        if victim is None:
            return None
        self.fleet.retire_replica(victim.id)
        self.counters["retires"] += 1
        self.decisions.append((
            step, "retire",
            f"replica={victim.id} burn={s['burn']:.2f} "
            f"kv={s['kv']:.2f} backlog={s['backlog']:.1f}"))
        self._note_event(step, direction)
        return "retire"

    def _note_event(self, step: int, direction: int) -> None:
        self._last_event_step = step
        self._last_event_dir = direction
        self._hot_streak = 0
        self._calm_streak = 0

    def _coldest(self):
        """Deterministic drain victim: the UP replica carrying the
        least work (live requests, then KV, then id)."""
        up = [r for r in self.fleet.replicas
              if r.state is ReplicaState.UP
              and r.id not in self.fleet._retiring]
        if len(up) <= self.config.min_replicas:
            return None
        return min(up, key=lambda r: (r.live_requests,
                                      r.kv_utilization, r.id))

    def _maybe_rerole(self, step: int) -> Optional[str]:
        c = self.config
        if step - self._last_rerole_step < c.rerole_cooldown_steps:
            return None
        pre = [r for r in self.fleet.replicas
               if r.state is ReplicaState.UP
               and r.role is ReplicaRole.PREFILL]
        dec = [r for r in self.fleet.replicas
               if r.state is ReplicaState.UP
               and r.role is ReplicaRole.DECODE]
        if not pre or not dec:
            return None
        pre_load = sum(r.live_requests for r in pre) / len(pre)
        dec_load = sum(r.live_requests for r in dec) / len(dec)
        if pre_load - dec_load >= c.rerole_gap and len(dec) > 1:
            mover = min(dec, key=lambda r: (r.live_requests, r.id))
            self.fleet.set_role(mover.id, ReplicaRole.PREFILL)
            detail = f"replica={mover.id} decode->prefill " \
                     f"gap={pre_load - dec_load:.1f}"
        elif dec_load - pre_load >= c.rerole_gap and len(pre) > 1:
            mover = min(pre, key=lambda r: (r.live_requests, r.id))
            self.fleet.set_role(mover.id, ReplicaRole.DECODE)
            detail = f"replica={mover.id} prefill->decode " \
                     f"gap={dec_load - pre_load:.1f}"
        else:
            return None
        self.counters["reroles"] += 1
        self.decisions.append((step, "rerole", detail))
        self._last_rerole_step = step
        return "rerole"

    # ------------------------------------------------------------- #
    # driver + surface
    # ------------------------------------------------------------- #
    def run(self, requests: List[Request],
            max_steps: int = 1_000_000) -> Dict:
        """Drive a whole trace: the fleet's ``run_trace`` loop with
        one control decision after every step."""
        fleet = self.fleet
        arrivals = sorted(requests,
                          key=lambda r: (r.arrival_time, r.uid))
        steps = 0
        while arrivals or fleet.has_work:
            now = fleet.clock.now()
            while arrivals and arrivals[0].arrival_time <= now:
                fleet.submit(request=arrivals.pop(0))
            if not fleet.has_work and arrivals:
                fleet.clock.advance_to(arrivals[0].arrival_time)
                continue
            fleet.step()
            self.observe()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    "autoscaled run exceeded step budget\n"
                    + fleet.snapshot())
        out = fleet.summary()
        out["autoscale"] = self.summary()
        return out

    def summary(self) -> Dict:
        return {
            "enabled": self.config.enabled,
            "counters": dict(self.counters),
            "flaps": self.flaps,
            "replicas_live": self.fleet.live_replicas,
            "decisions": [list(d) for d in self.decisions],
            "last_signals": {k: round(v, 6)
                             for k, v in
                             sorted(self.last_signals.items())},
        }


# ----------------------------------------------------------------- #
# deterministic diurnal / bursty multi-tenant trace generator
# ----------------------------------------------------------------- #
def build_autoscale_trace(seed: int = 0, n_requests: int = 160,
                          horizon_s: float = 60.0, tenants: int = 4,
                          flash_crowds: int = 2,
                          swarm_fraction: float = 0.4,
                          prompt_tokens: Tuple[int, int] = (6, 16),
                          new_tokens: Tuple[int, int] = (4, 12),
                          uid_base: int = 0) -> List[Request]:
    """The bursty multi-tenant trace the autoscaler is judged on —
    a pure function of its arguments.

    * **Diurnal curve**: arrival intensity follows one sinusoidal
      period over ``horizon_s`` (quiet start, peak mid-horizon), so a
      static fleet sized for the peak idles through the valleys.
    * **Flash crowds**: ``flash_crowds`` narrow Gaussian bursts
      stacked on the curve at deterministic offsets.
    * **Tenant skew**: tenants draw Zipf-like weights (tenant 0
      dominates), each owning a disjoint token-id range.
    * **Shared-prefix agent swarms**: a ``swarm_fraction`` of each
      tenant's requests share that tenant's base prefix (8+ tokens,
      over the broadcast threshold), so prefix-tree pre-warm has real
      traffic to win on.
    """
    rng = np.random.default_rng([int(seed), 0xA5CA1E])
    grid = np.linspace(0.0, horizon_s, 512)
    intensity = 1.0 + 0.8 * np.sin(
        2.0 * np.pi * grid / horizon_s - np.pi / 2.0)
    for i in range(flash_crowds):
        center = horizon_s * (i + 0.7) / (flash_crowds + 0.4)
        width = horizon_s * 0.02
        intensity += 3.0 * np.exp(-((grid - center) ** 2)
                                  / (2.0 * width ** 2))
    cdf = np.cumsum(intensity)
    cdf /= cdf[-1]
    arrivals = np.interp(np.sort(rng.random(n_requests)), cdf, grid)
    weights = 1.0 / np.arange(1, tenants + 1, dtype=np.float64)
    weights /= weights.sum()
    tenant_of = rng.choice(tenants, size=n_requests, p=weights)
    swarm = rng.random(n_requests) < swarm_fraction
    lo_p, hi_p = prompt_tokens
    lo_n, hi_n = new_tokens
    plens = rng.integers(lo_p, hi_p + 1, size=n_requests)
    nnews = rng.integers(lo_n, hi_n + 1, size=n_requests)
    requests = []
    for i in range(n_requests):
        t = int(tenant_of[i])
        base = 1000 * (t + 1)
        if swarm[i]:
            # the tenant's shared agent-swarm prefix: identical
            # leading 8 tokens, then a unique suffix
            prompt = [base + k for k in range(8)]
            prompt += [base + 100 + int(x) for x in
                       rng.integers(0, 64, size=max(
                           int(plens[i]) - 8, 1))]
        else:
            prompt = [base + 200 + int(x) for x in
                      rng.integers(0, 512, size=int(plens[i]))]
        requests.append(Request(
            uid=uid_base + i, prompt=prompt,
            max_new_tokens=int(nnews[i]),
            arrival_time=float(arrivals[i])))
    return requests
