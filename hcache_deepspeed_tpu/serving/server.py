"""Thread-based serving frontend with admission control.

Two operating modes over one scheduler:

* **thread mode** (production shape): callers ``submit()`` from any
  thread into a bounded ingress queue; a single scheduler thread drains
  it and runs continuous-batching steps against the engine. One thread
  owns the engine — the ragged engine is not thread-safe, and a single
  dispatch loop is the TPU-native discipline anyway.
* **virtual-clock simulation** (``run_trace`` with a
  :class:`.clock.VirtualClock`): the same scheduler steps over a
  simulated timeline whose step costs come from a deterministic cost
  model, so the entire policy — admissions, preemptions, restores,
  token streams — replays identically for the same trace. This is what
  makes the subsystem CPU-testable without a TPU.

Admission control happens at ingress, before the scheduler sees the
request: a full queue or an estimated-KV-demand overload rejects
immediately with a distinct reason (the caller can shed load upstream),
while schedulable-but-not-yet requests queue normally.
"""

import threading
from dataclasses import dataclass
from typing import List, Optional

from ..analysis.runtime import make_lock
from ..telemetry.context import TraceContext
from ..telemetry.flight import get_flight_recorder
from ..telemetry.tracer import get_tracer
from .clock import MonotonicClock, VirtualClock
from .metrics import ServingMetrics
from .request import Request, RequestState
from .scheduler import ContinuousBatchingScheduler


@dataclass
class ServerConfig:
    #: ingress bound: queued-but-not-admitted requests beyond this are
    #: rejected with reason "queue_full"
    max_queue_depth: int = 64
    #: reject when the estimated whole-stretch KV demand of every live
    #: request exceeds this multiple of the usable block pool (demand
    #: beyond 1.0 is served by queueing + preemption; this caps how far
    #: the backlog may run ahead of the hardware)
    kv_demand_fraction: float = 8.0
    #: thread mode: sleep when a step had nothing to do
    idle_sleep_s: float = 0.002
    #: replay chunks issued per scheduler step while a restore lane is
    #: open (the decode-interleave grain; 0 drains a lane in one step)
    restore_chunks_per_step: int = 1
    #: scheduler-grain chunked prefill (Dynamic SplitFuse): long
    #: prompts dispatch in per-step slices of this many tokens so they
    #: never head-of-line block resident decode (0 = monolithic
    #: prefill, the historical behavior). Pair with the engine's
    #: ``state_manager.prefill_chunk`` when its per-forward token
    #: budget also needs the chunk accounting.
    prefill_chunk: int = 0
    #: restore→preempt livelock guard (see the scheduler): a resident
    #: restored within the last N steps is not a preemption victim.
    #: 0 = historical victim policy (committed chaos digests replay)
    preempt_restore_grace: int = 0
    #: head-of-line restore admission (see the scheduler): a large
    #: suspended payload that does not fit blocks smaller ones from
    #: leapfrogging it. False = historical smaller-may-still-fit
    restore_priority_barrier: bool = False
    #: scheduler-dispatched speculative decode (a
    #: :class:`~.spec.SpeculationConfig`; None = the historical
    #: one-token-per-lane step — committed chaos digests replay)
    speculation: object = None
    #: SLO-aware degradation mode (a :class:`~.spec.SLOModeConfig`;
    #: None = the fault-driven ladder alone)
    slo_mode: object = None
    # -- virtual-clock cost model (seconds) -------------------------- #
    step_overhead_s: float = 1e-3
    prefill_token_s: float = 1e-4
    decode_lane_s: float = 5e-4
    restore_token_s: float = 2e-5
    restore_chunk_s: float = 1e-4
    #: per drafted-token verification cost of a fused speculative
    #: step: drafts verify inside one dispatch on lanes the MXU
    #: already occupies, so a verified token is far cheaper than a
    #: dispatched decode step — that gap is the whole speedup
    spec_draft_token_s: float = 5e-5


class ServingServer:

    def __init__(self, engine, config: ServerConfig = None, clock=None,
                 metrics: ServingMetrics = None, sample_fn=None,
                 monitor=None, emit_every_steps: int = 50,
                 crossover=None, resilience=None, replica_id: int = 0,
                 prefix_cache=None):
        self.config = config or ServerConfig()
        self.clock = clock or MonotonicClock()
        self.virtual = isinstance(self.clock, VirtualClock)
        self.metrics = metrics or ServingMetrics()
        #: fleet position (0 = standalone); threaded to the scheduler
        #: so per-replica retry jitter streams stay independent
        self.replica_id = int(replica_id)
        self.scheduler = ContinuousBatchingScheduler(
            engine, clock=self.clock, sample_fn=sample_fn,
            metrics=self.metrics, crossover=crossover,
            restore_chunks_per_step=self.config.restore_chunks_per_step,
            resilience=resilience, replica_id=self.replica_id,
            prefill_chunk=self.config.prefill_chunk,
            preempt_restore_grace=self.config.preempt_restore_grace,
            restore_priority_barrier=
            self.config.restore_priority_barrier,
            speculation=self.config.speculation,
            slo_mode=self.config.slo_mode,
            prefix_cache=prefix_cache)
        self.monitor = monitor
        self.emit_every_steps = emit_every_steps
        self._lock = make_lock("ServingServer._lock")
        self._ingress: List[Request] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._next_uid = 0
        #: the exception that killed the scheduler thread, if any;
        #: ``wait()`` re-raises it and ``submit()`` rejects while set
        self.error: Optional[BaseException] = None
        self._metrics_httpd = None
        self._metrics_http_thread = None

    @property
    def healthy(self) -> bool:
        return self.error is None

    # ------------------------------------------------------------- #
    # ingress
    # ------------------------------------------------------------- #
    def _estimated_demand_blocks(self) -> int:
        bs = self.scheduler.engine.block_size
        live = (self._ingress + self.scheduler.queue +
                list(self.scheduler.running.values()) +
                list(self.scheduler.suspended.values()))
        return sum(-(-r.total_tokens // bs) for r in live)

    def _usable_blocks(self) -> int:
        return self.scheduler.engine.state.allocator.num_blocks - 1

    def submit(self, prompt=None, request: Request = None,
               **kw) -> Request:
        """Enqueue a request (or build one from ``prompt`` + kwargs).

        Returns the request; a rejected one comes back already in
        ``REJECTED`` state with ``reject_reason`` set ("queue_full" or
        "kv_overload") — the caller is expected to check.
        """
        with self._lock:
            if request is None:
                request = Request(uid=self._next_uid, prompt=list(prompt),
                                  arrival_time=self.clock.now(), **kw)
            if request.trace is None:
                # causal tracing starts at the front door: the root
                # queue span opens at arrival so queue-wait attribution
                # matches Request.queue_wait(); ingress rejects below
                # still close the chain with a terminal outcome
                request.trace = TraceContext.mint(
                    request.uid, clock=self.clock,
                    t0=request.arrival_time)
            self._next_uid = max(self._next_uid, request.uid) + 1
            depth = len(self._ingress) + len(self.scheduler.queue)
            reason = ""
            if self.error is not None:
                reason = "server_down"
            elif depth >= self.config.max_queue_depth:
                reason = "queue_full"
            else:
                bs = self.scheduler.engine.block_size
                demand = self._estimated_demand_blocks() + \
                    -(-request.total_tokens // bs)
                if demand > self.config.kv_demand_fraction * \
                        self._usable_blocks():
                    reason = "kv_overload"
            if reason:
                request.reject_reason = reason
                request.finished_at = self.clock.now()
                request.transition(RequestState.REJECTED)
                self.scheduler.done[request.uid] = request
                self.scheduler.events.append(
                    (self.scheduler.step_idx, "reject_ingress",
                     request.uid, reason))
                self.metrics.rejected[reason] = \
                    self.metrics.rejected.get(reason, 0) + 1
                return request
            self._ingress.append(request)
            return request

    def cancel(self, uid: int) -> None:
        with self._lock:
            for req in self._ingress:
                if req.uid == uid:
                    req.cancelled = True
                    return
            self.scheduler.cancel(uid)

    # ------------------------------------------------------------- #
    # stepping
    # ------------------------------------------------------------- #
    def _virtual_cost(self, report) -> float:
        c = self.config
        return (c.step_overhead_s +
                c.prefill_token_s * report.prefill_tokens +
                c.decode_lane_s * (report.decode_lanes +
                                   report.spec_lanes +
                                   len(report.admitted)) +
                c.restore_token_s * report.restored_tokens +
                c.restore_chunk_s * report.restore_chunks +
                c.spec_draft_token_s * report.spec_drafted)

    def step(self, advance_clock: bool = True):
        """Drain ingress + one scheduler step (thread mode calls this
        in a loop; simulation calls it from ``run_trace``).
        ``advance_clock=False`` leaves the virtual clock to the caller
        — the fleet steps N replicas at one simulated instant and
        advances the shared clock once by the parallel-max cost."""
        with self._lock:
            for req in self._ingress:
                self.scheduler.submit(req)
            self._ingress.clear()
            report = self.scheduler.step()
            if self.virtual and advance_clock:
                self.clock.sleep(self._virtual_cost(report))
            if self.monitor is not None and \
                    report.step % self.emit_every_steps == 0:
                self.metrics.emit(self.monitor, report.step)
        return report

    # ------------------------------------------------------------- #
    # deterministic trace replay (simulation AND single-thread bench)
    # ------------------------------------------------------------- #
    def run_trace(self, requests: List[Request],
                  max_steps: int = 1_000_000):
        """Feed ``requests`` at their ``arrival_time``s and step until
        everything finished. Under a VirtualClock this is a pure
        function of the trace; under a real clock it is the
        single-threaded open-loop replay the serve_loop bench uses."""
        pending = sorted(requests,
                         key=lambda r: (r.arrival_time, r.uid))
        steps = 0
        while pending or self.scheduler.has_work or self._ingress:
            now = self.clock.now()
            while pending and pending[0].arrival_time <= now:
                self.submit(request=pending.pop(0))
            if not self.scheduler.has_work and not self._ingress \
                    and pending:
                # idle until the next arrival
                if self.virtual:
                    self.clock.advance_to(pending[0].arrival_time)
                else:
                    self.clock.sleep(pending[0].arrival_time - now)
                continue
            report = self.step()
            if not report.work_done and not self.virtual:
                self.clock.sleep(self.config.idle_sleep_s)
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"run_trace exceeded {max_steps} steps — "
                    "scheduling livelock?\n" + self._snapshot())
        if self.monitor is not None:
            # trace end: flush buffered sinks deterministically (the
            # Monitor.flush contract — CSV buffers, TB flushes per
            # write; both are safe to flush here)
            self.metrics.emit(self.monitor, self.scheduler.step_idx,
                              flush=True)
        return self.metrics

    def _snapshot(self, last_events: int = 20) -> str:
        """Diagnostic scheduler snapshot attached to livelock/crash
        errors — the state one actually needs to debug a wedge.
        Locked: it renders ``_ingress`` and the scheduler pools that
        the loop thread mutates, and its callers (``run_trace``'s
        livelock raise, the post-mortem log in ``_on_loop_error``)
        hold nothing — an unlocked render here was a torn diagnostic
        (HDS-L002)."""
        with self._lock:
            return self._snapshot_locked(last_events)

    def _snapshot_locked(self, last_events: int = 20) -> str:
        s = self.scheduler
        lanes = list(getattr(s.engine, "restoring_uids", ()))
        lines = [
            "scheduler snapshot:",
            f"  step={s.step_idx} degradation={int(s.degradation)} "
            f"breaker={s.breaker.state.name}",
            f"  queue={[r.uid for r in s.queue]}",
            f"  running={sorted(s.running)}",
            f"  suspended={sorted(s.suspended)}",
            f"  restoring={sorted(s.restoring)} open_lanes={lanes}",
            f"  ingress={[r.uid for r in self._ingress]}",
            f"  free_blocks={s.engine.state.free_blocks}",
            f"  last {min(last_events, len(s.events))} events: "
            f"{s.events[-last_events:]}",
        ]
        return "\n".join(lines)

    # ------------------------------------------------------------- #
    # observability surface
    # ------------------------------------------------------------- #
    def metrics_snapshot(self) -> dict:
        """Point-in-time introspection dict: the full metrics summary
        (histograms, counters, gauges, SLO burn rates), scheduler pool
        depths, health, and the Prometheus text rendering — everything
        an operator probe or test needs in one locked read."""
        tracer = get_tracer()
        with self._lock:
            s = self.scheduler
            return {
                "healthy": self.healthy,
                "error": None if self.error is None
                else repr(self.error),
                "step": s.step_idx,
                "pools": {"ingress": len(self._ingress),
                          "queue": len(s.queue),
                          "running": len(s.running),
                          "suspended": len(s.suspended),
                          "restoring": len(s.restoring),
                          "done": len(s.done)},
                "metrics": self.metrics.summary(),
                "slo_gauges": dict(self.metrics.slo_gauges),
                "critical_path": self.metrics.critical_path_summary(),
                "tracer": {"dropped_events": tracer.dropped,
                           "buffered": tracer.buffered},
                "flight": get_flight_recorder().summary(),
                "prometheus": self.metrics.prometheus_text(),
            }

    def start_metrics_http(self, host: str = "127.0.0.1",
                           port: int = 0) -> int:
        """Optional stdlib exposition endpoint: serves the Prometheus
        text at ``/metrics`` (and a JSON-ish health line at
        ``/healthz``) from a daemon thread. Returns the bound port
        (``port=0`` picks a free one). The endpoint only *reads*
        snapshots — it can never steer the scheduler."""
        if self._metrics_httpd is not None:
            return self._metrics_httpd.server_address[1]
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.split("?")[0] == "/metrics":
                    body = server.metrics_snapshot()[
                        "prometheus"].encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.split("?")[0] == "/healthz":
                    body = _json.dumps(
                        {"healthy": server.healthy}).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):   # no stderr chatter
                pass

        self._metrics_httpd = ThreadingHTTPServer((host, port),
                                                  _Handler)
        self._metrics_http_thread = threading.Thread(
            target=self._metrics_httpd.serve_forever,
            name="hds-metrics-http", daemon=True)
        self._metrics_http_thread.start()
        return self._metrics_httpd.server_address[1]

    def stop_metrics_http(self) -> None:
        if self._metrics_httpd is None:
            return
        self._metrics_httpd.shutdown()
        self._metrics_httpd.server_close()
        self._metrics_http_thread.join(timeout=5.0)
        self._metrics_httpd = None
        self._metrics_http_thread = None

    # ------------------------------------------------------------- #
    # thread mode
    # ------------------------------------------------------------- #
    def start(self) -> None:
        if self.virtual:
            raise RuntimeError(
                "thread mode needs a real clock; use run_trace for "
                "virtual-clock simulation")
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="hds-serving", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                report = self.step()
                if not report.work_done:
                    self._stop.wait(self.config.idle_sleep_s)
        except BaseException as exc:          # noqa: BLE001
            self._on_loop_error(exc)

    def _on_loop_error(self, exc: BaseException) -> None:
        """The scheduler thread died: capture the error, fail every
        in-flight request typed, and flip the server unhealthy so
        ``submit`` rejects and ``wait`` raises instead of timing out.
        The engine is presumed broken — no engine calls here."""
        with self._lock:
            self.error = exc
            error = f"server_down: {exc!r}"
            for req in self._ingress:
                req.error = error
                req.transition(RequestState.FAILED)
                req.finished_at = self.clock.now()
                self.scheduler.done[req.uid] = req
            self._ingress.clear()
            self.scheduler.fail_all_live(error)
            self.scheduler.events.append(
                (self.scheduler.step_idx, "server_error", -1,
                 repr(exc)))
        get_tracer().instant("server.error", error=repr(exc),
                             replica=self.replica_id)
        try:
            # the crash-path flight dump: the postmortem bundle is the
            # whole point of the recorder — capture it before the log
            # line, while the scheduler state is still coherent
            rec = get_flight_recorder()
            rec.dump("server_crash", repr(exc),
                     source=f"replica{self.replica_id}",
                     step=self.scheduler.step_idx,
                     t=self.clock.now(),
                     snapshot=self.scheduler.flight_snapshot(),
                     spans=get_tracer().events()[-rec.span_tail:]
                     if get_tracer().enabled else None)
        except Exception:       # noqa: BLE001 — the server is already
            pass                # dying; the dump must not mask why
        from ..utils.logging import logger
        logger.error(f"serving loop died: {exc!r}\n{self._snapshot()}")

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        if self._thread is None:
            return
        if drain:
            deadline = self.clock.now() + timeout
            while (self.scheduler.has_work or self._ingress) and \
                    self.clock.now() < deadline:
                if not self._thread.is_alive():
                    break       # nobody is draining; don't spin it out
                self.clock.sleep(self.config.idle_sleep_s)
        self._stop.set()
        self._thread.join(timeout=timeout)
        self._thread = None
        self.stop_metrics_http()

    def wait(self, req: Request, timeout: float = 60.0) -> Request:
        """Block until ``req`` finishes (thread mode helper). Raises
        the captured loop error if the server died while waiting."""
        deadline = self.clock.now() + timeout
        while not req.finished and self.clock.now() < deadline:
            if self.error is not None:
                raise self.error
            self.clock.sleep(self.config.idle_sleep_s)
        if not req.finished and self.error is not None:
            raise self.error
        return req
