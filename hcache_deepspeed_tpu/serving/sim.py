"""Model-free engine double for CPU-deterministic serving simulation.

Exposes the exact ``InferenceEngineV2`` serving surface the scheduler
consumes — ``can_schedule`` (same verdict arithmetic, same order), the
ragged ``put``, ``restore_kv``, ``suspend_sequence``/
``resume_sequence``, ``flush`` — over the REAL ``StateManager`` /
``BlockedAllocator``, so block budgets, tracked-slot limits and
scratch-block reservation behave bit-identically to the real engine.
What it fakes is only the transformer: ``put`` returns one-hot logits
whose argmax is a deterministic hash of ``(uid, seen_tokens)``, and
token-thin latents that honor the restore shape contract. That makes
every scheduling policy decision — and the token streams themselves —
a pure function of the trace, with zero model compute.
"""

from typing import Dict, Iterable, List

import numpy as np

from ..inference.config import RaggedInferenceEngineConfig
from ..inference.ragged.kv_cache import StateManager
from ..inference.scheduling import SchedulingError, SchedulingResult
from ..resilience.faults import InjectedFault, get_injector


class SimulatedEngine:

    #: latent stack shape stand-ins (restore contract: [L, T, H])
    N_LAYER = 2
    HIDDEN = 4
    #: ``put_spec`` captures accepted-span latents, so speculation
    #: composes with latent preemption (matching the real engine, whose
    #: ``forward_chunk_tail_lat`` capture path keeps the same contract)
    spec_latent_capture = True

    def __init__(self, config: RaggedInferenceEngineConfig = None,
                 vocab_size: int = 64):
        self.config = config or RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8,
                           "max_ragged_batch_size": 256,
                           "max_ragged_sequence_count": 8,
                           "max_context": 256},
            kv_cache={"block_size": 16, "num_blocks": 32})
        sm = self.config.state_manager
        kv = self.config.kv_cache
        self.vocab_size = vocab_size
        self.block_size = kv.block_size
        self.max_context = sm.max_context
        num_blocks = kv.num_blocks or 32
        self.state = StateManager(sm.max_tracked_sequences, num_blocks,
                                  self.block_size, self.max_context)
        # mirror the real engine's reserved scratch block so block
        # budgets match it exactly
        self._scratch_block = self.state.allocator.allocate(1)[0]
        # op counters the tests/cost models read
        self.counts = {"put": 0, "restore": 0, "suspend": 0,
                       "resume": 0, "flush": 0}
        self.restore_stats = {"restores": 0, "sequences": 0,
                              "chunks_issued": 0, "bytes_shipped": 0}
        #: fused speculative-step accounting (``put_spec``): the
        #: scheduler's accepted-tokens/step metric cross-checks these
        self.spec_stats = {"steps": 0, "lanes": 0, "drafted": 0,
                           "accepted": 0, "emitted": 0,
                           "rolled_back": 0}
        #: open restore lanes, mirroring the real engine's decode-
        #: interleaved surface: each lane is a dict with the staged
        #: items, a chunk cursor and the owed post_forward state ops
        self._restore_lanes: List[Dict] = []

    # ------------------------------------------------------------- #
    @property
    def free_blocks(self) -> int:
        return self.state.free_blocks

    def _token(self, uid: int, position: int) -> int:
        """Deterministic next token: depends only on (uid, position),
        like a greedy model's output depends only on the context — so a
        preempt/restore cycle reproduces the uninterrupted stream iff
        the scheduler's bookkeeping is exact."""
        return (uid * 7919 + position * 131 + 17) % self.vocab_size

    # ------------------------------------------------------------- #
    # scheduling surface (verbatim verdict order of the real engine)
    # ------------------------------------------------------------- #
    def can_schedule(self, uids: Iterable[int],
                     lengths: Iterable[int]) -> SchedulingResult:
        uids, lengths = list(uids), list(lengths)
        sm = self.config.state_manager
        new_seqs = sum(1 for u in uids
                       if self.state.get_sequence(u) is None)
        if self.state.n_tracked_sequences + new_seqs > \
                sm.max_tracked_sequences:
            return SchedulingResult.EngineSequenceLimitExceeded
        if len(uids) > sm.max_ragged_sequence_count:
            return SchedulingResult.BatchSequenceLimitExceeded
        per_fwd = [min(n, sm.prefill_chunk) if sm.prefill_chunk else n
                   for n in lengths]
        if sum(per_fwd) > sm.max_ragged_batch_size:
            return SchedulingResult.BatchTokenLimitExceeded
        blocks = 0
        for uid, n in zip(uids, lengths):
            seq = self.state.get_sequence(uid)
            seen = seq.seen_tokens if seq else 0
            if seen + n > self.max_context:
                return SchedulingResult.SequenceTokenLimitExceeded
            blocks += self.state.blocks_needed(seq, n)
        if blocks > self.state.free_blocks:
            return SchedulingResult.KVCacheLimitExceeded
        return SchedulingResult.Success

    # ------------------------------------------------------------- #
    def _reject_suspended(self, uids) -> None:
        restoring = set(self.restoring_uids) if self._restore_lanes \
            else ()
        for uid in uids:
            if uid in restoring:
                raise RuntimeError(
                    f"sequence {uid} has an open restore lane; drain "
                    "advance_restores before forwarding it")
            seq = self.state.get_sequence(uid)
            if seq is not None and seq.host_kv is not None:
                raise RuntimeError(
                    f"sequence {uid} is suspended (KV on host); call "
                    "resume_sequence first")

    def put(self, batch_uids: Iterable[int], batch_tokens: Iterable,
            do_checks: bool = True):
        batch_uids = list(batch_uids)
        batch_tokens = [np.asarray(t, np.int32).reshape(-1)
                        for t in batch_tokens]
        if do_checks:
            result = self.can_schedule(batch_uids,
                                       [len(t) for t in batch_tokens])
            if result != SchedulingResult.Success:
                raise SchedulingError(result)
        self._reject_suspended(batch_uids)
        inj = get_injector()
        if inj.enabled and batch_uids:
            # fire BEFORE any state mutates, so a faulted dispatch can
            # be retried (or its batch quarantined) without divergence;
            # blame is deterministically pinned on the newest lane
            site = ("engine.prefill"
                    if any(len(t) > 1 for t in batch_tokens)
                    else "engine.decode")
            inj.fire(site, uid=batch_uids[-1],
                     uids=tuple(batch_uids))
        # allocation pre-pass: every sequence's blocks are claimed
        # before any forward state advances, so an alloc.blocks fault
        # leaves seen_tokens untouched everywhere (claimed-but-unused
        # blocks are reused by the retry — blocks_needed sees them)
        for uid, tokens in zip(batch_uids, batch_tokens):
            seq = self.state.get_or_create_sequence(uid)
            try:
                self.state.maybe_allocate_kv(seq, len(tokens))
            except InjectedFault as f:
                if f.uid is None:
                    f.uid = uid
                    f.ctx["uid"] = uid
                raise
        self.counts["put"] += 1
        logits = np.zeros((len(batch_uids), self.vocab_size), np.float32)
        latents: List = []
        for j, (uid, tokens) in enumerate(zip(batch_uids, batch_tokens)):
            seq = self.state.get_sequence(uid)
            seq.pre_forward(len(tokens))
            seq.post_forward()
            logits[j, self._token(uid, seq.seen_tokens)] = 1.0
            if self.config.hcache.enable_latents:
                latents.append(np.full(
                    (self.N_LAYER, len(tokens), self.HIDDEN),
                    float(seq.seen_tokens), np.float32))
            else:
                latents.append(None)
        return logits, latents

    # ------------------------------------------------------------- #
    # fused speculative verify step (the serving speculation surface)
    # ------------------------------------------------------------- #
    def put_spec(self, batch_uids: Iterable[int], batch_feeds,
                 do_checks: bool = True):
        """One fused speculative step over DECODE residents: each feed
        is ``[fed_token] + draft``. The engine verifies the stretch
        against its own greedy targets, accepts the matching draft
        prefix plus the bonus token, rolls the rejected draft KV back
        (``SequenceDescriptor.rollback`` — blocks stay allocated, the
        next dispatch overwrites the same slots, exactly the real
        engine's arithmetic), and captures latents **only for the
        accepted span** — a preempt after this call trivially holds a
        latent payload ending at the last accepted token.

        Returns ``(emitted, latents)``: ``emitted[i]`` is the accepted
        greedy tokens (``>= 1``, ``<= len(feed)``), ``latents[i]`` a
        ``[L, len(emitted[i]), H]`` slab (None without latent capture).
        Greedy-exact: the emitted stream is bitwise identical to
        feeding the same lanes one token at a time through ``put``."""
        batch_uids = list(batch_uids)
        batch_feeds = [list(np.asarray(f, np.int32).reshape(-1))
                       for f in batch_feeds]
        if any(len(f) < 1 for f in batch_feeds):
            raise ValueError("put_spec feeds need >= 1 token "
                             "(the fed token)")
        if do_checks:
            result = self.can_schedule(
                batch_uids, [len(f) for f in batch_feeds])
            if result != SchedulingResult.Success:
                raise SchedulingError(result)
        self._reject_suspended(batch_uids)
        for uid in batch_uids:
            if self.state.get_sequence(uid) is None:
                raise KeyError(
                    f"put_spec: unknown sequence {uid} (speculation "
                    "runs on decode residents only)")
        inj = get_injector()
        if inj.enabled and batch_uids:
            # fires BEFORE any state mutates (same discipline as put):
            # a faulted speculative dispatch is cleanly retryable /
            # quarantinable with every lane still at its last accepted
            # token
            inj.fire("engine.spec", uid=batch_uids[-1],
                     uids=tuple(batch_uids))
        # allocation pre-pass for the WORST case (full feed incl. the
        # draft tail) — claimed-but-rolled-back blocks stay with the
        # sequence and are reused by later growth
        for uid, feed in zip(batch_uids, batch_feeds):
            seq = self.state.get_sequence(uid)
            try:
                self.state.maybe_allocate_kv(seq, len(feed))
            except InjectedFault as f:
                if f.uid is None:
                    f.uid = uid
                    f.ctx["uid"] = uid
                raise
        self.counts["put"] += 1
        self.spec_stats["steps"] += 1
        emitted_out: List[List[int]] = []
        latents: List = []
        for uid, feed in zip(batch_uids, batch_feeds):
            seq = self.state.get_sequence(uid)
            start = seq.seen_tokens
            d = len(feed) - 1
            greedy = [self._token(uid, start + 1 + t)
                      for t in range(d + 1)]
            acc = 0
            while acc < d and feed[1 + acc] == greedy[acc]:
                acc += 1
            seq.pre_forward(len(feed))
            seq.post_forward()
            seq.rollback(d - acc)       # rejected draft KV
            emitted = greedy[:acc + 1]
            emitted_out.append(emitted)
            self.spec_stats["lanes"] += 1
            self.spec_stats["drafted"] += d
            self.spec_stats["accepted"] += acc
            self.spec_stats["emitted"] += len(emitted)
            self.spec_stats["rolled_back"] += d - acc
            if self.config.hcache.enable_latents:
                latents.append(np.full(
                    (self.N_LAYER, acc + 1, self.HIDDEN),
                    float(seq.seen_tokens), np.float32))
            else:
                latents.append(None)
        return emitted_out, latents

    # ------------------------------------------------------------- #
    def restore_kv(self, batch_uids: Iterable[int], batch_tokens,
                   batch_latents) -> None:
        """Run-to-completion restore (begin + drain), mirroring the
        real engine's driver over its decode-interleaved lane."""
        self.begin_restore(batch_uids, batch_tokens, batch_latents)
        self.advance_restores()

    def begin_restore(self, batch_uids: Iterable[int], batch_tokens,
                      batch_latents) -> Dict:
        """Open a restore lane: the same all-or-nothing admission
        arithmetic as the real engine, with KV allocated and the
        sequences marked in-flight at begin; ``advance_restores`` then
        issues one synthetic layer-chunk per call per lane (N_LAYER
        chunks per restore) and runs the owed ``post_forward`` state
        ops at lane completion."""
        batch_uids = list(batch_uids)
        self._reject_suspended(batch_uids)
        items = []
        for uid, tokens, latents in zip(batch_uids, batch_tokens,
                                        batch_latents):
            if latents is None:
                continue
            tokens = np.asarray(tokens, np.int32).reshape(-1)
            latents = np.asarray(latents)
            if latents.shape[1] != len(tokens):
                raise ValueError(
                    f"uid {uid}: {len(tokens)} tokens but latents for "
                    f"{latents.shape[1]}")
            items.append((uid, tokens, latents))
        new_seqs = sum(1 for uid, _, _ in items
                       if self.state.get_sequence(uid) is None)
        if self.state.n_tracked_sequences + new_seqs > \
                self.config.state_manager.max_tracked_sequences:
            raise SchedulingError(
                SchedulingResult.EngineSequenceLimitExceeded)
        need = 0
        for uid, tokens, _ in items:
            seq = self.state.get_sequence(uid)
            seen = seq.seen_tokens if seq else 0
            if seen + len(tokens) > self.max_context:
                raise SchedulingError(
                    SchedulingResult.SequenceTokenLimitExceeded)
            need += self.state.blocks_needed(seq, len(tokens))
        if need > self.state.free_blocks:
            raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)
        from ..telemetry.tracer import get_tracer
        seqs = []
        with get_tracer().span(
                "serve.restore_kv", sequences=len(items),
                tokens=int(sum(len(it[1]) for it in items)),
                latent_bytes=int(sum(it[2].nbytes for it in items))):
            for uid, tokens, latents in items:
                seq = self.state.get_or_create_sequence(uid)
                self.state.maybe_allocate_kv(seq, len(tokens))
                seq.pre_forward(len(tokens))
                seqs.append(seq)
                self.restore_stats["sequences"] += 1
        self.counts["restore"] += 1
        self.restore_stats["restores"] += 1
        ticket = {"uids": [it[0] for it in items], "done": not items}
        if items:
            self._restore_lanes.append({
                "uids": ticket["uids"], "seqs": seqs,
                "nbytes": int(sum(it[2].nbytes for it in items)),
                "next_chunk": 0, "chunks": self.N_LAYER,
                "ticket": ticket})
        return ticket

    def advance_restores(self, max_chunks: int = 0):
        """(chunks_issued, completed_uids, touched_uids) — the same
        contract as ``InferenceEngineV2.advance_restores``."""
        from ..telemetry.tracer import get_tracer
        tracer = get_tracer()
        issued = 0
        completed: List[int] = []
        touched: List[int] = []
        while self._restore_lanes and (max_chunks <= 0 or
                                       issued < max_chunks):
            lane = self._restore_lanes[0]
            base = lane["nbytes"] // lane["chunks"]
            n0 = lane["next_chunk"]
            inj = get_injector()
            while lane["next_chunk"] < lane["chunks"] and \
                    (max_chunks <= 0 or issued < max_chunks):
                last = lane["next_chunk"] == lane["chunks"] - 1
                per_chunk = lane["nbytes"] - base * \
                    (lane["chunks"] - 1) if last else base
                if inj.enabled:
                    # both lane sites fire before the chunk is counted
                    # or any state advances: a faulted ship/replay is
                    # cleanly re-issuable by the retry policy
                    ctx = dict(uid=lane["uids"][0],
                               uids=tuple(lane["uids"]),
                               chunk=lane["next_chunk"])
                    inj.fire("restore.ship", **ctx)
                    inj.fire("restore.replay", **ctx)
                with tracer.span("serve.restore.stage",
                                 layer0=lane["next_chunk"], layers=1,
                                 bytes=per_chunk):
                    pass
                lane["next_chunk"] += 1
                issued += 1
                self.restore_stats["chunks_issued"] += 1
                self.restore_stats["bytes_shipped"] += per_chunk
            if lane["next_chunk"] > n0:
                touched.extend(lane["uids"])
            if lane["next_chunk"] < lane["chunks"]:
                break
            for seq in lane["seqs"]:
                seq.post_forward()
            completed.extend(lane["uids"])
            lane["ticket"]["done"] = True
            self._restore_lanes.pop(0)
        return issued, completed, touched

    def abort_restore(self, uid: int) -> List[int]:
        """Abort the open lane holding ``uid``: flush every sequence
        it staged (frees their blocks + tracked slots) and drop the
        lane. Returns the aborted uids; [] when no lane holds ``uid``.
        The host latent payload lives with the caller's Request, so an
        aborted restore can be re-begun or recomputed later."""
        for i, lane in enumerate(self._restore_lanes):
            if uid in lane["uids"]:
                self._restore_lanes.pop(i)
                for u in lane["uids"]:
                    self.state.flush_sequence(u)
                lane["ticket"]["done"] = True
                lane["ticket"]["aborted"] = True
                self.counts["abort"] = self.counts.get("abort", 0) + 1
                return list(lane["uids"])
        return []

    @property
    def pending_restore_chunks(self) -> int:
        return sum(l["chunks"] - l["next_chunk"]
                   for l in self._restore_lanes)

    @property
    def restoring_uids(self) -> List[int]:
        return [u for l in self._restore_lanes for u in l["uids"]]

    def restore_profile(self) -> Dict:
        """Synthetic profile for the crossover model: float32 latents
        of shape [N_LAYER, T, HIDDEN], one chunk per layer, and a 50%
        replay FLOPs share."""
        return {
            "n_layer": self.N_LAYER,
            "latent_bytes_per_token": self.N_LAYER * self.HIDDEN * 4,
            "replay_flops_frac": 0.5,
            "restore_chunk_layers": 1,
            "restore_chunk_bytes": 0,
        }

    # ------------------------------------------------------------- #
    def suspend_sequence(self, uid: int) -> None:
        seq = self.state.get_sequence(uid)
        if seq is None:
            raise KeyError(f"unknown sequence {uid}")
        if seq.host_kv is not None:
            return
        seq.host_kv = ("sim", seq.seen_tokens)
        if seq.blocks:
            self.state.allocator.free(seq.blocks)
            seq.blocks = []
        self.counts["suspend"] += 1

    def resume_sequence(self, uid: int) -> None:
        seq = self.state.get_sequence(uid)
        if seq is None:
            raise KeyError(f"unknown sequence {uid}")
        if seq.host_kv is None:
            return
        need = self.state.blocks_needed(seq, 0)
        if need > self.state.free_blocks:
            raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)
        self.state.maybe_allocate_kv(seq, 0)
        seq.host_kv = None
        self.counts["resume"] += 1

    def flush(self, uid: int) -> None:
        if self._restore_lanes and uid in self.restoring_uids:
            raise RuntimeError(
                f"sequence {uid} has an open restore lane; its blocks "
                "cannot be freed while replay chunks are in flight")
        self.state.flush_sequence(uid)
        self.counts["flush"] += 1

    # ------------------------------------------------------------- #
    # snapshot round-trip (migration + crash-replay substrate)
    # ------------------------------------------------------------- #
    def serialize(self) -> Dict:
        """Full JSON-safe snapshot of the engine's host state: tracked
        sequences (seen/in-flight tokens, block ids, suspension
        marker), the allocator's exact free-list ORDER and refcounts
        (block ids are identity — a faithful replay must hand out the
        same ids), op counters, restore stats, and open restore lanes.
        ``deserialize`` rebuilds a bitwise-identical engine:
        ``SimulatedEngine.deserialize(e.serialize()).serialize() ==
        e.serialize()``, and both engines produce identical logits /
        block assignments for identical subsequent calls — the
        round-trip contract migration and crash replay lean on."""
        alloc = self.state.allocator
        return {
            "config": self.config.model_dump(),
            "vocab_size": self.vocab_size,
            "sequences": {
                str(uid): {"seen_tokens": s.seen_tokens,
                           "in_flight_tokens": s.in_flight_tokens,
                           "blocks": list(s.blocks),
                           "host_kv": (list(s.host_kv)
                                       if s.host_kv is not None
                                       else None)}
                for uid, s in self.state._seqs.items()
            },
            "free_blocks": self.state.free_blocks,
            "free_list": list(alloc._free),
            "refcounts": {str(b): n for b, n in alloc._refs.items()},
            "scratch_block": self._scratch_block,
            "counts": dict(self.counts),
            "restore_stats": dict(self.restore_stats),
            "spec_stats": dict(self.spec_stats),
            "restore_lanes": [
                {"uids": list(l["uids"]), "nbytes": l["nbytes"],
                 "next_chunk": l["next_chunk"], "chunks": l["chunks"]}
                for l in self._restore_lanes
            ],
        }

    @classmethod
    def deserialize(cls, snapshot: Dict) -> "SimulatedEngine":
        """Rebuild an engine from :meth:`serialize` output (accepts the
        dict directly or its JSON round-trip). See ``serialize`` for
        the fidelity contract."""
        from ..inference.config import RaggedInferenceEngineConfig
        from .request import Request  # noqa: F401 (doc cross-ref)
        eng = cls(RaggedInferenceEngineConfig(**snapshot["config"]),
                  vocab_size=int(snapshot["vocab_size"]))
        alloc = eng.state.allocator
        # the constructor grabbed a scratch block; replace the whole
        # allocator state with the snapshot's exact free order + refs
        alloc._free = [int(b) for b in snapshot["free_list"]]
        alloc._refs = {int(b): int(n)
                       for b, n in snapshot["refcounts"].items()}
        eng._scratch_block = int(snapshot["scratch_block"])
        eng.state._seqs = {}
        from ..inference.ragged.sequence import SequenceDescriptor
        for uid_s, d in snapshot["sequences"].items():
            seq = SequenceDescriptor(int(uid_s))
            seq.seen_tokens = int(d["seen_tokens"])
            seq.in_flight_tokens = int(d["in_flight_tokens"])
            seq.blocks = [int(b) for b in d["blocks"]]
            hkv = d.get("host_kv")
            seq.host_kv = (hkv[0], int(hkv[1])) \
                if hkv is not None else None
            eng.state._seqs[seq.uid] = seq
        eng.counts = {k: int(v) for k, v in snapshot["counts"].items()}
        eng.restore_stats = {k: int(v) for k, v
                             in snapshot["restore_stats"].items()}
        eng.spec_stats = {k: int(v) for k, v
                          in snapshot.get("spec_stats",
                                          eng.spec_stats).items()}
        eng._restore_lanes = []
        for lane in snapshot["restore_lanes"]:
            uids = [int(u) for u in lane["uids"]]
            ticket = {"uids": list(uids), "done": False}
            eng._restore_lanes.append({
                "uids": uids,
                "seqs": [eng.state.get_sequence(u) for u in uids],
                "nbytes": int(lane["nbytes"]),
                "next_chunk": int(lane["next_chunk"]),
                "chunks": int(lane["chunks"]),
                "ticket": ticket})
        return eng
