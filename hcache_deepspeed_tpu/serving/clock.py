"""Clock abstraction for the serving loop.

The scheduler and server never call ``time`` directly — they read a
``Clock``. ``MonotonicClock`` serves production; ``VirtualClock`` makes
the whole scheduling policy a deterministic function of (trace, seed):
time advances only when the simulation says so, so two runs of the same
trace produce identical admission/preemption event logs (asserted in
``tests/unit/serving/``).
"""

import time


class MonotonicClock:
    """Wall clock (monotonic): real serving and on-hardware benches."""

    def now(self) -> float:
        # sanctioned: this IS the real-clock implementation behind
        # the Clock interface — everything sim-deterministic reads a
        # Clock, never time.* directly
        # hds: allow(HDS-P001) the real-clock impl behind Clock
        return time.monotonic()

    def sleep(self, dt: float) -> None:
        if dt > 0:
            time.sleep(dt)


class VirtualClock:
    """Deterministic simulated clock; ``sleep`` advances it instantly."""

    def __init__(self, start: float = 0.0):
        self._t = float(start)

    def now(self) -> float:
        return self._t

    def sleep(self, dt: float) -> None:
        if dt > 0:
            self._t += dt

    def advance_to(self, t: float) -> None:
        """Jump forward (never backward) to absolute time ``t``."""
        self._t = max(self._t, float(t))
